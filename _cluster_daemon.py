"""A live mini-cluster behind the HTTP server, for CLI verification.

Control plane runs behind leader election (ControlPlane — the
cmd/kube-scheduler server.go:281 / controller-manager wiring): the full
controller set including DisruptionController, so PDB status stays live.

Debug knobs (read by APIStore at construction, so they apply here too):
STORE_LOCK_ORDER_CHECK=1 arms the runtime lock-order assertion (schedlint
LK001's dynamic companion), CACHE_MUTATION_DETECTOR=1 the event mutation
detector."""
import sys, time
from kubernetes_tpu.agent import HollowCluster
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.server.controlplane import ControlPlane
from kubernetes_tpu.store import APIStore

store = APIStore()
srv = APIServer(store, port=18080).start()
cluster = HollowCluster(store, n_nodes=3)
cluster.register_all()
for k in cluster.kubelets:
    k.start(heartbeat_interval=2.0)
cp = ControlPlane(store, identity="daemon-0").start()
deadline = time.time() + 30
while not cp.is_leader and time.time() < deadline:
    time.sleep(0.05)
print("READY", srv.url, flush=True)
time.sleep(600)
