"""A live mini-cluster behind the HTTP server, for CLI verification."""
import sys, time
from kubernetes_tpu.agent import HollowCluster
from kubernetes_tpu.controllers import DeploymentController, ReplicaSetController, NodeLifecycleController
from kubernetes_tpu.scheduler import Framework
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.store import APIStore

store = APIStore()
srv = APIServer(store, port=18080).start()
cluster = HollowCluster(store, n_nodes=3)
cluster.register_all()
for k in cluster.kubelets:
    k.start(heartbeat_interval=2.0)
sched = BatchScheduler(store, Framework(default_plugins()), solver="auto")
sched.sync(); sched.start()
dc, rsc = DeploymentController(store), ReplicaSetController(store)
for c in (dc, rsc):
    c.sync_all(); c.start()
print("READY", srv.url, flush=True)
time.sleep(600)
