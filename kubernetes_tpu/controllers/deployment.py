"""Deployment controller — manages ReplicaSets per template revision.

reference: pkg/controller/deployment (syncDeployment, rolling.go). Semantics:
one ReplicaSet per pod-template hash; RollingUpdate scales the new RS up and
old RSes down within maxSurge/maxUnavailable; Recreate scales old to 0 first.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Tuple

from ..api.workloads import Deployment, ReplicaSet, ReplicaSetSpec
from ..api.types import ObjectMeta, new_uid
from ..store import AlreadyExistsError, NotFoundError
from .base import Controller


def template_hash(dep: Deployment) -> str:
    from .revision import template_fingerprint

    return template_fingerprint(dep.spec.template)


# revision bookkeeping (deployment/util/deployment_util.go Revision/
# SetNewReplicaSetAnnotations): each template generation gets a monotonically
# increasing revision on its RS; rollbacks re-activate an old RS's template,
# which then receives the NEW max revision
REVISION_ANNOTATION = "deployment.kubernetes.io/revision"


def rs_revision(rs: ReplicaSet) -> int:
    try:
        return int(rs.metadata.annotations.get(REVISION_ANNOTATION, "0"))
    except ValueError:
        return 0


def is_owned_by_dep(rs: ReplicaSet, dep: Deployment) -> bool:
    return any(
        ref.get("kind") == "Deployment" and ref.get("uid") == dep.metadata.uid
        for ref in rs.metadata.owner_references
    )


class DeploymentController(Controller):
    watch_kinds = ("deployments", "replicasets")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "deployments":
            return obj.key
        for ref in obj.metadata.owner_references:
            if ref.get("kind") == "Deployment":
                return f"{obj.metadata.namespace}/{ref['name']}"
        return None

    def sync(self, key: str) -> None:
        try:
            dep: Deployment = self.store.get("deployments", key)
        except NotFoundError:
            self._delete_owned(key)
            return
        new_rs, old_rses = self._get_or_create_rses(dep)
        if dep.spec.strategy == "Recreate":
            self._sync_recreate(dep, new_rs, old_rses)
        else:
            self._sync_rolling(dep, new_rs, old_rses)
        self._update_status(dep, new_rs, old_rses)

    # -- RS management ---------------------------------------------------------

    def _get_or_create_rses(self, dep: Deployment) -> Tuple[ReplicaSet, List[ReplicaSet]]:
        h = template_hash(dep)
        rses, _ = self.store.list(
            "replicasets",
            lambda rs: rs.metadata.namespace == dep.metadata.namespace and is_owned_by_dep(rs, dep),
        )
        new_rs = None
        old = []
        for rs in rses:
            if rs.metadata.labels.get("pod-template-hash") == h:
                new_rs = rs
            else:
                old.append(rs)
        max_rev = max((rs_revision(rs) for rs in rses), default=0)
        if new_rs is not None and old and rs_revision(new_rs) < max_rev:
            # rollback: an OLD template became current again — it takes the
            # next revision so history stays monotonic (deployment_util.go)
            def bump(obj: ReplicaSet) -> ReplicaSet:
                obj.metadata.annotations[REVISION_ANNOTATION] = str(max_rev + 1)
                return obj

            new_rs = self.store.guaranteed_update("replicasets", new_rs.key, bump)
        if new_rs is None:
            import copy

            template = copy.deepcopy(dep.spec.template)
            template.metadata.labels["pod-template-hash"] = h
            new_rs = ReplicaSet(
                metadata=ObjectMeta(
                    name=f"{dep.metadata.name}-{h}",
                    namespace=dep.metadata.namespace,
                    uid=new_uid(),
                    labels={**template.metadata.labels},
                    annotations={REVISION_ANNOTATION: str(max_rev + 1)},
                    owner_references=[{
                        "kind": "Deployment",
                        "name": dep.metadata.name,
                        "uid": dep.metadata.uid,
                        "controller": True,
                    }],
                ),
                spec=ReplicaSetSpec(replicas=0, selector=dep.spec.selector, template=template),
            )
            try:
                new_rs = self.store.create("replicasets", new_rs)
            except AlreadyExistsError:
                new_rs = self.store.get("replicasets", new_rs.key)
        return new_rs, old

    def _scale(self, rs: ReplicaSet, replicas: int) -> None:
        if rs.spec.replicas == replicas:
            return

        def mutate(obj: ReplicaSet) -> ReplicaSet:
            obj.spec.replicas = replicas
            return obj

        self.store.guaranteed_update("replicasets", rs.key, mutate)

    # -- strategies ------------------------------------------------------------

    def _sync_recreate(self, dep, new_rs, old_rses) -> None:
        old_total = sum(rs.spec.replicas for rs in old_rses)
        if old_total > 0:
            for rs in old_rses:
                self._scale(rs, 0)
            return  # next sync (triggered by RS events) scales the new one up
        self._scale(new_rs, dep.spec.replicas)

    def _sync_rolling(self, dep, new_rs, old_rses) -> None:
        desired = dep.spec.replicas
        max_total = desired + dep.spec.max_surge
        old_total = sum(rs.spec.replicas for rs in old_rses)
        if new_rs.spec.replicas > desired:
            # deployment scaled down: shrink the new RS directly
            self._scale(new_rs, desired)
            new_rs.spec.replicas = desired
        # scale up new within surge budget
        new_target = min(desired, max_total - old_total)
        if new_target > new_rs.spec.replicas:
            self._scale(new_rs, new_target)
        # scale down old as new pods become ready (simplified readiness: running)
        new_ready = self._ready_count(new_rs)
        min_available = desired - dep.spec.max_unavailable
        can_remove = max(0, old_total + new_ready - min_available)
        for rs in sorted(old_rses, key=lambda r: r.metadata.name):
            if can_remove <= 0:
                break
            cut = min(rs.spec.replicas, can_remove)
            if cut > 0:
                self._scale(rs, rs.spec.replicas - cut)
                can_remove -= cut

    def _ready_count(self, rs: ReplicaSet) -> int:
        pods, _ = self.store.list(
            "pods",
            lambda p: p.metadata.namespace == rs.metadata.namespace and any(
                r.get("kind") == "ReplicaSet" and r.get("uid") == rs.metadata.uid
                for r in p.metadata.owner_references
            ) and p.status.phase == "Running",
        )
        return len(pods)

    def _update_status(self, dep, new_rs, old_rses) -> None:
        def mutate(obj: Deployment) -> Deployment:
            obj.status.replicas = new_rs.spec.replicas + sum(r.spec.replicas for r in old_rses)
            obj.status.updated_replicas = new_rs.spec.replicas
            obj.status.ready_replicas = self._ready_count(new_rs)
            obj.status.observed_generation = obj.metadata.generation
            return obj

        try:
            self.store.guaranteed_update("deployments", dep.key, mutate)
        except NotFoundError:
            pass

    def _delete_owned(self, key: str) -> None:
        ns, name = key.split("/", 1)
        rses, _ = self.store.list(
            "replicasets",
            lambda rs: rs.metadata.namespace == ns and any(
                r.get("kind") == "Deployment" and r.get("name") == name
                for r in rs.metadata.owner_references
            ),
        )
        for rs in rses:
            try:
                self.store.delete("replicasets", rs.key)
            except NotFoundError:
                pass
