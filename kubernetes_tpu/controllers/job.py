"""Job + CronJob controllers.

reference: pkg/controller/job/job_controller.go (syncJob: pod counting,
parallelism/completions, backoffLimit -> Failed condition) and
pkg/controller/cronjob/cronjob_controllerv2.go (syncCronJob: unmet schedule
times, concurrencyPolicy, history limits).
"""

from __future__ import annotations

from typing import List, Optional

from ..api import Pod
from ..api.workloads import CronJob, Job, JobSpec, PodTemplateSpec
from ..store import AlreadyExistsError, NotFoundError
from ..utils.cron import CronSchedule
from .base import Controller

JOB_NAME_LABEL = "job-name"
COMPLETION_INDEX_ANNOTATION = "batch.kubernetes.io/job-completion-index"


def pod_completion_index(pod: Pod) -> int:
    try:
        return int(pod.metadata.annotations.get(COMPLETION_INDEX_ANNOTATION, -1))
    except (TypeError, ValueError):  # null/garbage annotation: no index
        return -1


def compress_indexes(indexes) -> str:
    """{0,1,2,5} -> "0-2,5" (batch/v1 completedIndexes wire form)."""
    out = []
    run_start = prev = None
    for i in sorted(indexes):
        if prev is None:
            run_start = prev = i
            continue
        if i == prev + 1:
            prev = i
            continue
        out.append(str(run_start) if run_start == prev
                   else f"{run_start}-{prev}")
        run_start = prev = i
    if prev is not None:
        out.append(str(run_start) if run_start == prev
                   else f"{run_start}-{prev}")
    return ",".join(out)


def job_owner_ref(job: Job) -> dict:
    return {"apiVersion": "batch/v1", "kind": "Job", "name": job.metadata.name,
            "uid": job.metadata.uid, "controller": True}


def _owned_by_job(pod: Pod, job: Job) -> bool:
    return any(r.get("kind") == "Job" and r.get("uid") == job.metadata.uid
               for r in pod.metadata.owner_references)


class JobController(Controller):
    watch_kinds = ("jobs", "pods")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "jobs":
            return obj.key
        for ref in obj.metadata.owner_references:
            if ref.get("kind") == "Job":
                return f"{obj.metadata.namespace}/{ref['name']}"
        return None

    def sync(self, key: str) -> None:
        try:
            job: Job = self.store.get("jobs", key)
        except NotFoundError:
            self._delete_owned_pods(key)
            return
        pods, _ = self.store.list(
            "pods", lambda p: p.metadata.namespace == job.metadata.namespace
            and _owned_by_job(p, job))
        active = [p for p in pods if not p.is_terminal()
                  and p.metadata.deletion_timestamp is None]
        succeeded = sum(1 for p in pods if p.status.phase == "Succeeded")
        failed = sum(1 for p in pods if p.status.phase == "Failed")
        # nil completions = work-queue job (job_controller.go manageJob):
        # wantActive is parallelism, and the job completes when any pod
        # succeeds and no pods remain active (JobSpec's documented semantic).
        completions = job.spec.completions
        indexed = job.spec.completion_mode == "Indexed" and completions is not None
        completed_idx = set()
        if indexed:
            # per-index completion (indexed_job_utils.go): an index counts
            # once, however many retried pods succeeded for it
            completed_idx = {pod_completion_index(p) for p in pods
                             if p.status.phase == "Succeeded"}
            completed_idx = {i for i in completed_idx
                             if 0 <= i < completions}
            succeeded = len(completed_idx)

        condition = None
        want_active = len(active)
        if job.is_finished():
            pass  # terminal; pods are left for TTL/GC (job_controller.go)
        elif job.spec.completion_mode == "Indexed" and completions is None:
            # admission rejects this on the REST path; a direct store write
            # must fail loudly, not silently run as a work-queue job whose
            # pods carry no index identity
            condition = {"type": "Failed", "status": "True",
                         "reason": "InvalidSpec",
                         "message": "completions is required for Indexed jobs"}
            for p in active:
                self._try_delete_pod(p)
            want_active = 0
        elif failed > job.spec.backoff_limit:
            condition = {"type": "Failed", "status": "True", "reason": "BackoffLimitExceeded"}
            for p in active:
                self._try_delete_pod(p)
            want_active = 0
        elif (succeeded >= completions if completions is not None
              else succeeded >= 1 and not active):
            condition = {"type": "Complete", "status": "True"}
        elif job.spec.suspend:
            for p in active:
                self._try_delete_pod(p)
            want_active = 0
        elif indexed:
            # create pods for MISSING indexes: not completed, no active pod
            # holding the index (failed pods free their index for a retry)
            active_idx = {pod_completion_index(p) for p in active}
            missing = [i for i in range(completions)
                       if i not in completed_idx and i not in active_idx]
            want_active = min(job.spec.parallelism, completions - succeeded)
            for i in missing[:max(0, want_active - len(active))]:
                self._create_pod(job, index=i)
            if want_active < len(active):
                # scale-down: drop highest indexes first (reference prefers
                # keeping the lowest ones for stable completion)
                for p in sorted(active, key=pod_completion_index,
                                reverse=True)[: len(active) - want_active]:
                    self._try_delete_pod(p)
        else:
            # wantActive (job_controller.go manageJob): bounded by parallelism
            # and by the completions still owed; scales down as well as up
            if completions is None:
                # work-queue semantics: full parallelism until the first
                # success, then just let running pods drain — but always
                # capped by parallelism so lowering it scales down
                want_active = job.spec.parallelism if succeeded == 0 \
                    else min(len(active), job.spec.parallelism)
            else:
                want_active = min(job.spec.parallelism, completions - succeeded)
            for _ in range(max(0, want_active - len(active))):
                self._create_pod(job)
            for p in active[want_active:] if want_active < len(active) else []:
                self._try_delete_pod(p)

        def mutate(obj: Job) -> Job:
            obj.status.active = want_active
            obj.status.succeeded = succeeded
            obj.status.failed = failed
            if indexed:
                obj.status.completed_indexes = compress_indexes(completed_idx)
            if obj.status.start_time is None and not job.spec.suspend:
                obj.status.start_time = self.clock.now()
            if condition is not None and not obj.status.conditions:
                # both terminal conditions carry a transition time — the TTL
                # controller counts ttlSecondsAfterFinished from it
                condition["lastTransitionTime"] = self.clock.now()
                obj.status.conditions = [condition]
                if condition["type"] == "Complete":
                    obj.status.completion_time = self.clock.now()
            return obj

        try:
            self.store.guaranteed_update("jobs", key, mutate)
        except NotFoundError:
            pass

    def _create_pod(self, job: Job, index: Optional[int] = None) -> None:
        import uuid

        template = job.spec.template
        if index is not None:
            name = f"{job.metadata.name}-{index}-{uuid.uuid4().hex[:5]}"
        else:
            name = f"{job.metadata.name}-{uuid.uuid4().hex[:5]}"
        pod = template.make_pod(name, job.metadata.namespace, job_owner_ref(job))
        pod.metadata.labels[JOB_NAME_LABEL] = job.metadata.name
        if index is not None:
            # the index rides an annotation + label and the canonical env var
            # (job_controller.go podGenerator for Indexed mode) — a TPU
            # training pod reads JOB_COMPLETION_INDEX to pick its data shard
            pod.metadata.annotations[COMPLETION_INDEX_ANNOTATION] = str(index)
            pod.metadata.labels[COMPLETION_INDEX_ANNOTATION] = str(index)
            for c in pod.spec.containers:
                c.env = list(c.env) + [{"name": "JOB_COMPLETION_INDEX",
                                        "value": str(index)}]
        if pod.spec.restart_policy == "Always":
            # job pods may not be Always (batch/validation); default to Never
            pod.spec.restart_policy = "Never"
        try:
            self.store.create("pods", pod)
        except AlreadyExistsError:
            pass

    def _try_delete_pod(self, pod: Pod) -> None:
        try:
            self.store.delete("pods", pod.key)
        except NotFoundError:
            pass

    def _delete_owned_pods(self, key: str) -> None:
        ns, name = key.split("/", 1)
        pods, _ = self.store.list(
            "pods", lambda p: p.metadata.namespace == ns and any(
                r.get("kind") == "Job" and r.get("name") == name
                for r in p.metadata.owner_references))
        for p in pods:
            self._try_delete_pod(p)


def cronjob_owner_ref(cj: CronJob) -> dict:
    return {"apiVersion": "batch/v1", "kind": "CronJob", "name": cj.metadata.name,
            "uid": cj.metadata.uid, "controller": True}


def _owned_by_cronjob(job: Job, cj: CronJob) -> bool:
    return any(r.get("kind") == "CronJob" and r.get("uid") == cj.metadata.uid
               for r in job.metadata.owner_references)


class CronJobController(Controller):
    """Time-based Job creation. Time comes from the injected clock, so tests
    step a FakeClock through schedule boundaries (cronjob_controllerv2.go
    now()-injection)."""

    watch_kinds = ("cronjobs", "jobs")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "cronjobs":
            return obj.key
        for ref in obj.metadata.owner_references:
            if ref.get("kind") == "CronJob":
                return f"{obj.metadata.namespace}/{ref['name']}"
        return None

    def resync_due(self) -> None:
        """Mark every CronJob dirty (the reference requeues at next schedule
        time; the daemon loop calls this each tick)."""
        cjs, _ = self.store.list("cronjobs")
        for cj in cjs:
            self._mark(cj.key)

    def sync(self, key: str) -> None:
        try:
            cj: CronJob = self.store.get("cronjobs", key)
        except NotFoundError:
            return
        jobs, _ = self.store.list(
            "jobs", lambda j: j.metadata.namespace == cj.metadata.namespace
            and _owned_by_cronjob(j, cj))
        active = [j for j in jobs if not j.is_finished()]
        self._prune_history(cj, jobs)
        if cj.spec.suspend:
            return
        now = self.clock.now()
        try:
            schedule = CronSchedule(cj.spec.schedule, tz=cj.spec.time_zone)
        except ValueError:
            # admission rejects these on the REST path; a direct store write
            # with a bad schedule/timeZone must not hot-spin the controller
            # (the reference records UnknownTimeZone and skips the object)
            return
        # earliestTime: lastScheduleTime, else creationTimestamp (getRecentUnmet
        # ScheduleTimes); an object with no creation stamp starts counting now.
        since = cj.status.last_schedule_time
        if since is None:
            since = cj.metadata.creation_timestamp or now
        due = schedule.times_between(since, now)
        if not due:
            return
        scheduled_time = due[-1]
        if (cj.spec.starting_deadline_seconds is not None
                and now - scheduled_time > cj.spec.starting_deadline_seconds):
            return  # missed the window (syncCronJob tooLate)
        if active:
            if cj.spec.concurrency_policy == "Forbid":
                return
            if cj.spec.concurrency_policy == "Replace":
                for j in active:
                    self._delete_job(j)
        self._create_job(cj, scheduled_time)

        def mutate(obj: CronJob) -> CronJob:
            obj.status.last_schedule_time = scheduled_time
            return obj

        try:
            self.store.guaranteed_update("cronjobs", key, mutate)
        except NotFoundError:
            pass

    def _create_job(self, cj: CronJob, scheduled_time: float) -> None:
        import copy

        # deterministic name from the minute stamp (getJobName)
        name = f"{cj.metadata.name}-{int(scheduled_time) // 60}"
        spec: JobSpec = copy.deepcopy(cj.spec.job_template)
        job = Job(spec=spec)
        job.metadata.name = name
        job.metadata.namespace = cj.metadata.namespace
        job.metadata.owner_references = [cronjob_owner_ref(cj)]
        from ..api.types import new_uid

        job.metadata.uid = new_uid()
        job.metadata.creation_timestamp = self.clock.now()
        try:
            self.store.create("jobs", job)
        except AlreadyExistsError:
            pass  # already created for this schedule time

    def _delete_job(self, job: Job) -> None:
        # cascade: the JobController's NotFound path deletes the pods
        try:
            self.store.delete("jobs", job.key)
        except NotFoundError:
            pass

    def _prune_history(self, cj: CronJob, jobs: List[Job]) -> None:
        finished = [j for j in jobs if j.is_finished()]
        ok = sorted((j for j in finished if any(
            c.get("type") == "Complete" and c.get("status") == "True"
            for c in j.status.conditions)), key=lambda j: j.metadata.creation_timestamp)
        bad = sorted((j for j in finished if any(
            c.get("type") == "Failed" and c.get("status") == "True"
            for c in j.status.conditions)), key=lambda j: j.metadata.creation_timestamp)
        for j in ok[:max(0, len(ok) - cj.spec.successful_jobs_history_limit)]:
            self._delete_job(j)
        for j in bad[:max(0, len(bad) - cj.spec.failed_jobs_history_limit)]:
            self._delete_job(j)
