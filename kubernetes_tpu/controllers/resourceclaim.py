"""DRA resourceclaim controller: template → generated claims + orphan reap.

reference: pkg/controller/resourceclaim/controller.go — for every pod
resourceClaims entry naming a ResourceClaimTemplate, a ResourceClaim
`<pod>-<ref>` is generated (owned by the pod, stamped with the template's
device requests) and recorded in pod.status.resourceClaimStatuses; the
scheduler's DynamicResources plugin resolves template refs through that
status map. Generated claims whose owner pod is gone (or terminal) are
reaped — ownerReferences would let the GC collect them eventually, but the
reference's controller deletes deterministically and so does this one.
"""

from __future__ import annotations

from typing import Optional

from ..api.dra import ResourceClaim
from ..store import AlreadyExistsError, NotFoundError
from .base import Controller

# annotation marking a generated claim (reference:
# resourceclaim.PodClaimName annotation "resource.kubernetes.io/pod-claim-name")
POD_CLAIM_NAME = "resource.kubernetes.io/pod-claim-name"


def claim_name_for(pod_name: str, ref: str) -> str:
    return f"{pod_name}-{ref}"


class ResourceClaimController(Controller):
    watch_kinds = ("pods", "resourceclaims")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "pods":
            spec = getattr(obj, "spec", None)
            if spec is None or not spec.resource_claim_templates:
                return None
            # a (possibly DELETED/terminal) pod must resync its generated
            # claims — that's the reap path
            ns = obj.metadata.namespace
            for _ref, cn in obj.status.resource_claim_statuses.items():
                self._mark(f"claim:{ns}/{cn}")
            return f"pod:{ns}/{obj.metadata.name}"
        if POD_CLAIM_NAME in (obj.metadata.annotations or {}):
            return f"claim:{obj.metadata.namespace}/{obj.metadata.name}"
        return None

    def sync(self, key: str) -> None:
        kind, _, rest = key.partition(":")
        if kind == "pod":
            self._sync_pod(rest)
        else:
            self._sync_claim(rest)

    def _sync_pod(self, key: str) -> None:
        try:
            pod = self.store.get("pods", key)
        except NotFoundError:
            return
        if pod.is_terminal() or not pod.spec.resource_claim_templates:
            return
        ns = pod.metadata.namespace
        created = {}
        for ref, tmpl_name in pod.spec.resource_claim_templates:
            generated = pod.status.resource_claim_statuses.get(ref)
            if generated:
                try:
                    self.store.get("resourceclaims", f"{ns}/{generated}")
                    continue  # already generated and alive
                except NotFoundError:
                    pass  # stamped but deleted: regenerate
            try:
                tmpl = self.store.get("resourceclaimtemplates",
                                      f"{ns}/{tmpl_name}")
            except NotFoundError:
                continue  # template not created yet; retried on its ADDED
            claim = ResourceClaim(requests=list(tmpl.requests))
            claim.metadata.name = claim_name_for(pod.metadata.name, ref)
            claim.metadata.namespace = ns
            claim.metadata.annotations[POD_CLAIM_NAME] = ref
            claim.metadata.owner_references = [{
                "apiVersion": "v1", "kind": "Pod",
                "name": pod.metadata.name, "uid": pod.metadata.uid,
                "controller": True,
            }]
            try:
                self.store.create("resourceclaims", claim)
            except AlreadyExistsError:
                # adopt ONLY a claim this exact pod incarnation owns — a
                # stale same-name claim (recreated pod, cross-pod name
                # collision) must not be stamped into status; the reap
                # path deletes it and re-marks this pod to regenerate
                existing = self.store.get("resourceclaims",
                                          f"{ns}/{claim.metadata.name}")
                owner = next((o for o in existing.metadata.owner_references
                              if o.get("kind") == "Pod"), {})
                if owner.get("uid") != pod.metadata.uid:
                    self._mark(f"claim:{ns}/{claim.metadata.name}")
                    continue
            created[ref] = claim.metadata.name
        if created:
            def stamp(p):
                p.status.resource_claim_statuses.update(created)
                return p

            try:
                self.store.guaranteed_update("pods", key, stamp)
            except NotFoundError:
                pass

    def _sync_claim(self, key: str) -> None:
        """Reap generated claims whose owning pod is gone or terminal."""
        try:
            claim = self.store.get("resourceclaims", key)
        except NotFoundError:
            return
        owner = next((o for o in claim.metadata.owner_references
                      if o.get("kind") == "Pod"), None)
        if owner is None:
            return
        ns = claim.metadata.namespace
        try:
            pod = self.store.get("pods", f"{ns}/{owner.get('name', '')}")
        except NotFoundError:
            pod = None
        if pod is not None and pod.metadata.uid == owner.get("uid") \
                and not pod.is_terminal():
            return
        try:
            self.store.delete("resourceclaims", key)
        except NotFoundError:
            pass
        if pod is not None and not pod.is_terminal():
            # a same-name recreated pod was blocked by the stale claim:
            # regenerate for the new incarnation
            self._mark(f"pod:{ns}/{pod.metadata.name}")

    _RESYNC_EVERY = 200  # reconcile rounds between full sweeps (~10s idle)

    def reconcile_once(self) -> int:
        n = super().reconcile_once()
        self._resync_tick = getattr(self, "_resync_tick", 0) + 1
        if self._resync_tick >= self._RESYNC_EVERY:
            self._resync_tick = 0
            n += self.reap_orphans()
        return n

    def reap_orphans(self) -> int:
        """Full-store sweep (the controller's periodic resync, driven by
        reconcile_once every _RESYNC_EVERY rounds): every generated claim is
        re-checked against its owner — the backstop for DELETED events lost
        to a watch eviction."""
        claims, _ = self.store.list(
            "resourceclaims",
            lambda c: POD_CLAIM_NAME in (c.metadata.annotations or {}))
        for c in claims:
            self._mark(f"claim:{c.key}")
        return self.process()
