"""EndpointSlice controller: Service selector -> endpoint slices.

reference: pkg/controller/endpointslice/reconciler.go — one or more slices per
Service (capped at maxEndpointsPerSlice), endpoints from Running pods matching
the selector, ready = pod Running; target/port resolution from servicePorts.
Pod IPs are synthesized from the pod uid (this build has no real pod network).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional

from ..api import Pod
from ..api.networking import Endpoint, EndpointSlice, Service
from ..api.types import ObjectMeta, new_uid
from ..store import AlreadyExistsError, NotFoundError
from .base import Controller


def pod_ip(pod: Pod) -> str:
    """Deterministic synthetic 10.x.y.z address from the pod uid."""
    h = hashlib.sha1(pod.metadata.uid.encode()).digest()
    return f"10.{h[0]}.{h[1]}.{max(h[2], 1)}"


def svc_owner_ref(svc: Service) -> dict:
    return {"apiVersion": "v1", "kind": "Service", "name": svc.metadata.name,
            "uid": svc.metadata.uid, "controller": True}


class EndpointSliceController(Controller):
    watch_kinds = ("services", "pods")

    def __init__(self, store, clock=None,
                 max_endpoints_per_slice: int = EndpointSlice.MAX_ENDPOINTS):
        super().__init__(store, clock)
        self.max_endpoints = max_endpoints_per_slice

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "services":
            return obj.key
        # pod events resync every service in the namespace (the reference maps
        # pod -> services via a selector cache)
        return f"{obj.metadata.namespace}/*"

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        if name == "*":
            services, _ = self.store.list(
                "services", lambda s: s.metadata.namespace == ns)
            for svc in services:
                self._reconcile(svc)
            return
        try:
            svc: Service = self.store.get("services", key)
        except NotFoundError:
            self._delete_slices(ns, name)
            return
        self._reconcile(svc)

    def _reconcile(self, svc: Service) -> None:
        ns = svc.metadata.namespace
        want: List[Endpoint] = []
        if svc.spec.selector:
            pods, _ = self.store.list(
                "pods", lambda p: p.metadata.namespace == ns and not p.is_terminal()
                and all(p.metadata.labels.get(k) == v
                        for k, v in svc.spec.selector.items()))
            pods.sort(key=lambda p: p.metadata.name)
            for p in pods:
                if not p.spec.node_name:
                    continue  # unscheduled pods have no endpoint yet
                want.append(Endpoint(
                    addresses=[pod_ip(p)],
                    ready=p.status.phase == "Running",
                    node_name=p.spec.node_name,
                    target_ref=p.key,
                ))
        existing, _ = self.store.list(
            "endpointslices",
            lambda s: s.metadata.namespace == ns
            and s.metadata.labels.get(EndpointSlice.LABEL_SERVICE_NAME)
            == svc.metadata.name)
        by_name = {s.metadata.name: s for s in existing}
        chunks = [want[i:i + self.max_endpoints]
                  for i in range(0, len(want), self.max_endpoints)] or [[]]
        ports = list(svc.spec.ports)
        wanted_names = set()
        for i, chunk in enumerate(chunks):
            slice_name = f"{svc.metadata.name}-{i}"
            wanted_names.add(slice_name)
            if slice_name in by_name:
                def mutate(obj: EndpointSlice, chunk=chunk) -> EndpointSlice:
                    obj.endpoints = chunk
                    obj.ports = ports
                    return obj

                self.store.guaranteed_update(
                    "endpointslices", f"{ns}/{slice_name}", mutate)
            else:
                es = EndpointSlice(
                    metadata=ObjectMeta(
                        name=slice_name, namespace=ns, uid=new_uid(),
                        labels={EndpointSlice.LABEL_SERVICE_NAME: svc.metadata.name},
                        owner_references=[svc_owner_ref(svc)]),
                    endpoints=chunk, ports=ports)
                try:
                    self.store.create("endpointslices", es)
                except AlreadyExistsError:
                    self.store.guaranteed_update(
                        "endpointslices", f"{ns}/{slice_name}",
                        lambda obj, chunk=chunk: (setattr(obj, "endpoints", chunk),
                                                  setattr(obj, "ports", ports), obj)[-1])
        for s in existing:
            if s.metadata.name not in wanted_names:
                try:
                    self.store.delete("endpointslices", s.key)
                except NotFoundError:
                    pass

    def _delete_slices(self, ns: str, svc_name: str) -> None:
        slices, _ = self.store.list(
            "endpointslices",
            lambda s: s.metadata.namespace == ns
            and s.metadata.labels.get(EndpointSlice.LABEL_SERVICE_NAME) == svc_name)
        for s in slices:
            try:
                self.store.delete("endpointslices", s.key)
            except NotFoundError:
                pass
