"""StatefulSet controller: stable identity, ordinal-ordered rollout, PVC
retention.

reference: pkg/controller/statefulset/stateful_set_control.go
(UpdateStatefulSet: monotonic create 0..N-1 gated on readiness under
OrderedReady, scale-down from the highest ordinal, one PVC per
volumeClaimTemplate named <template>-<pod>).
"""

from __future__ import annotations

from typing import List, Optional

from ..api import Pod
from ..api.storage import PersistentVolumeClaim, PersistentVolumeClaimSpec
from ..api.types import ObjectMeta, Volume, new_uid
from ..api.workloads import StatefulSet
from ..store import AlreadyExistsError, NotFoundError
from .base import Controller


def sts_owner_ref(sts: StatefulSet) -> dict:
    return {"apiVersion": "apps/v1", "kind": "StatefulSet",
            "name": sts.metadata.name, "uid": sts.metadata.uid, "controller": True}


def _owned(pod: Pod, sts: StatefulSet) -> bool:
    return any(r.get("kind") == "StatefulSet" and r.get("uid") == sts.metadata.uid
               for r in pod.metadata.owner_references)


def _ordinal(pod_name: str, base: str) -> int:
    suffix = pod_name[len(base) + 1:]
    return int(suffix) if suffix.isdigit() else -1


from .revision import REVISION_LABEL  # noqa: F401  (shared fingerprint home)


def revision_hash(sts: StatefulSet) -> str:
    """Template fingerprint — the ControllerRevision name analog
    (pkg/controller/history). Pods carry it in controller-revision-hash."""
    from .revision import revision_name

    return revision_name(sts.metadata.name, sts.spec.template)


class StatefulSetController(Controller):
    watch_kinds = ("statefulsets", "pods")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "statefulsets":
            return obj.key
        for ref in obj.metadata.owner_references:
            if ref.get("kind") == "StatefulSet":
                return f"{obj.metadata.namespace}/{ref['name']}"
        return None

    def sync(self, key: str) -> None:
        try:
            sts: StatefulSet = self.store.get("statefulsets", key)
        except NotFoundError:
            self._delete_owned(key)
            return
        ns, base = sts.metadata.namespace, sts.metadata.name
        pods, _ = self.store.list(
            "pods", lambda p: p.metadata.namespace == ns and _owned(p, sts))
        by_ordinal = {_ordinal(p.metadata.name, base): p for p in pods}
        ordered = sts.spec.pod_management_policy == "OrderedReady"
        rev = revision_hash(sts)

        # scale up / replace missing, in ordinal order; OrderedReady gates each
        # ordinal on the previous one being Running (stateful_set_control.go)
        created_this_pass = False
        for i in range(sts.spec.replicas):
            pod = by_ordinal.get(i)
            if pod is not None and pod.is_terminal():
                # stateful pods are replaced in place, keeping identity
                try:
                    self.store.delete("pods", pod.key)
                except NotFoundError:
                    pass
                pod = None
            if pod is None:
                self._create_pod(sts, i, rev)
                created_this_pass = True
                if ordered:
                    break
            elif ordered and pod.status.phase != "Running":
                break  # wait for readiness before the next ordinal

        # scale down: highest ordinal first, one at a time when ordered
        extra = sorted((o for o in by_ordinal if o >= sts.spec.replicas), reverse=True)
        deleted_this_pass = False
        for o in extra[:1] if ordered else extra:
            try:
                self.store.delete("pods", by_ordinal[o].key)
                deleted_this_pass = True
            except NotFoundError:
                pass

        # rolling update (stateful_set_control.go updateStatefulSet): with
        # RollingUpdate, stale-revision pods at ordinals >= partition are
        # deleted HIGHEST ordinal first, one at a time, each gated on the
        # rest being Running; the replace-missing pass above recreates them
        # with the new template. OnDelete leaves stale pods for the operator.
        if sts.spec.update_strategy == "RollingUpdate":
            stale = sorted(
                (o for o, p in by_ordinal.items()
                 if o >= max(sts.spec.partition, 0) and o < sts.spec.replicas
                 and not p.is_terminal()
                 and p.metadata.labels.get(REVISION_LABEL) != rev),
                reverse=True)
            # every ordinal must exist AND be Running before the next update
            # step, and THIS sync must not have already deleted or created a
            # pod (scale-down or recreate in flight) — at most one member is
            # ever down at a time (OrderedReady's one-at-a-time guarantee)
            all_running = (not created_this_pass
                           and not deleted_this_pass
                           and all(o in by_ordinal for o in range(sts.spec.replicas))
                           and all(p.is_terminal() or p.status.phase == "Running"
                                   for o, p in by_ordinal.items()
                                   if o < sts.spec.replicas))
            if stale and all_running:
                try:
                    self.store.delete("pods", by_ordinal[stale[0]].key)
                except NotFoundError:
                    pass

        current = [p for p in pods if _ordinal(p.metadata.name, base) < sts.spec.replicas
                   and not p.is_terminal()]
        ready = sum(1 for p in current if p.status.phase == "Running")
        updated = sum(1 for p in current
                      if p.metadata.labels.get(REVISION_LABEL) == rev)

        def mutate(obj: StatefulSet) -> StatefulSet:
            obj.status.replicas = len(current)
            obj.status.current_replicas = len(current)
            obj.status.ready_replicas = ready
            obj.status.updated_replicas = updated
            obj.status.update_revision = rev
            obj.status.observed_generation = obj.metadata.generation
            return obj

        try:
            self.store.guaranteed_update("statefulsets", key, mutate)
        except NotFoundError:
            pass

    def _create_pod(self, sts: StatefulSet, ordinal: int, rev: str) -> None:
        name = f"{sts.metadata.name}-{ordinal}"
        pod = sts.spec.template.make_pod(name, sts.metadata.namespace, sts_owner_ref(sts))
        pod.metadata.labels["statefulset.kubernetes.io/pod-name"] = name
        pod.metadata.labels["apps.kubernetes.io/pod-index"] = str(ordinal)
        pod.metadata.labels[REVISION_LABEL] = rev
        # one PVC per volumeClaimTemplate, named <template>-<pod>; reused
        # across pod replacements (identity-preserving storage)
        for tpl in sts.spec.volume_claim_templates:
            tpl_name = (tpl.get("metadata") or {}).get("name", "data")
            claim_name = f"{tpl_name}-{name}"
            self._ensure_pvc(sts.metadata.namespace, claim_name, tpl)
            pod.spec.volumes.append(Volume(name=tpl_name, pvc_claim_name=claim_name))
        try:
            self.store.create("pods", pod)
        except AlreadyExistsError:
            pass

    def _ensure_pvc(self, namespace: str, claim_name: str, tpl: dict) -> None:
        try:
            self.store.get("persistentvolumeclaims", f"{namespace}/{claim_name}")
            return
        except NotFoundError:
            pass
        parsed = PersistentVolumeClaim.from_dict({"metadata": {"name": claim_name},
                                                  "spec": tpl.get("spec") or {}})
        pvc = PersistentVolumeClaim(
            metadata=ObjectMeta(name=claim_name, namespace=namespace, uid=new_uid()),
            spec=PersistentVolumeClaimSpec(
                access_modes=parsed.spec.access_modes or ["ReadWriteOnce"],
                request=parsed.spec.request,
                storage_class_name=parsed.spec.storage_class_name,
            ))
        try:
            self.store.create("persistentvolumeclaims", pvc)
        except AlreadyExistsError:
            pass

    def _delete_owned(self, key: str) -> None:
        ns, name = key.split("/", 1)
        pods, _ = self.store.list(
            "pods", lambda p: p.metadata.namespace == ns and any(
                r.get("kind") == "StatefulSet" and r.get("name") == name
                for r in p.metadata.owner_references))
        for p in pods:
            try:
                self.store.delete("pods", p.key)
            except NotFoundError:
                pass
