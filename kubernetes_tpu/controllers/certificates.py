"""Certificates controllers: approve, sign, and clean up CSRs.

reference: pkg/controller/certificates/{approver,signer,cleaner} and kubeadm's
TLS bootstrap — a joining node authenticates with a bootstrap token
(system:bootstrappers group), files a CSR for its node identity, the approver
auto-approves recognized node-bootstrap requests, the signer issues the
credential, and the node re-connects with its real system:node:<name>
identity (which NodeRestriction then scopes). The issued credential is an
HMAC-signed bearer token (server/auth.py SignedTokenAuthenticator) — the
cluster-CA analog for a bearer-token transport.
"""

from __future__ import annotations

from typing import Optional

from ..api.certificates import (
    APPROVED,
    CSRCondition,
    DENIED,
    FAILED,
    KUBE_APISERVER_CLIENT,
    KUBE_APISERVER_CLIENT_KUBELET,
)
from ..store import NotFoundError
from .base import Controller

BOOTSTRAP_GROUP = "system:bootstrappers"
NODE_GROUP = "system:nodes"
NODE_USER_PREFIX = "system:node:"

# default issued-credential lifetime (the reference kubelet rotates well
# before cert expiry; 1h mirrors its default client cert duration floor)
DEFAULT_EXPIRATION_SECONDS = 3600


def recognize_node_client(csr) -> Optional[str]:
    """The approver's recognizer for kubelet client CSRs
    (pkg/controller/certificates/approver/sarapprove.go): signer must be
    kube-apiserver-client-kubelet, the requested identity a system:node:<name>
    user in the system:nodes group, and the REQUESTOR a bootstrapper (or the
    node itself, for renewal). Returns the node name or None."""
    if csr.signer_name != KUBE_APISERVER_CLIENT_KUBELET:
        return None
    user = csr.request.get("user", "")
    groups = csr.request.get("groups") or []
    # groups must be EXACTLY [system:nodes] — membership alone would let a
    # bootstrap token smuggle system:masters into the issued credential
    # (sarapprove requires Organization == ["system:nodes"])
    if not user.startswith(NODE_USER_PREFIX) or set(groups) != {NODE_GROUP}:
        return None
    node = user[len(NODE_USER_PREFIX):]
    requestor_ok = (BOOTSTRAP_GROUP in csr.groups
                    or csr.username == user)  # renewal by the node itself
    return node if requestor_ok else None


class CSRApprovingController(Controller):
    """Auto-approves recognized node-bootstrap CSRs; denies kubelet-signer
    requests that ask for anything else (fail closed, like sarapprove's
    recognizer miss leaving the CSR pending — here made explicit so a bad
    request surfaces instead of hanging the join)."""

    watch_kinds = ("certificatesigningrequests",)

    def key_of_object(self, kind, obj):
        return obj.metadata.name

    def sync(self, name: str) -> None:
        try:
            csr = self.store.get("certificatesigningrequests", name)
        except NotFoundError:
            return
        if csr.approved or csr.denied or csr.signer_name != KUBE_APISERVER_CLIENT_KUBELET:
            return
        node = recognize_node_client(csr)

        def decide(obj):
            if obj.approved or obj.denied:
                return obj
            if node is not None:
                obj.conditions.append(CSRCondition(
                    type=APPROVED, reason="AutoApproved",
                    message="node client cert request recognized",
                    last_update_time=self.clock.now()))
            else:
                obj.conditions.append(CSRCondition(
                    type=DENIED, reason="Unrecognized",
                    message="not a recognized node client request",
                    last_update_time=self.clock.now()))
            return obj

        self.store.guaranteed_update("certificatesigningrequests", name, decide)


class CSRSigningController(Controller):
    """Issues the credential for approved CSRs
    (pkg/controller/certificates/signer). Holds the cluster signing key via a
    SignedTokenAuthenticator (mint + verify share one implementation)."""

    watch_kinds = ("certificatesigningrequests",)

    def __init__(self, store, signer, clock=None):
        super().__init__(store, clock)
        self.signer = signer

    def key_of_object(self, kind, obj):
        return obj.metadata.name

    def sync(self, name: str) -> None:
        try:
            csr = self.store.get("certificatesigningrequests", name)
        except NotFoundError:
            return
        if not csr.approved or csr.denied or csr.certificate:
            return
        if csr.signer_name not in (KUBE_APISERVER_CLIENT_KUBELET,
                                   KUBE_APISERVER_CLIENT):
            return  # foreign signerName: not ours to issue (signer.go filters)
        user = csr.request.get("user", "")
        groups = [g for g in (csr.request.get("groups") or [])
                  if g != "system:authenticated"]  # authn layer re-adds it
        ttl = csr.expiration_seconds or DEFAULT_EXPIRATION_SECONDS
        try:
            token = self.signer.mint(user, groups, expiration_seconds=ttl)
        except Exception as e:  # key unavailable etc. -> Failed condition
            def fail(obj):
                if not obj.condition(FAILED):
                    obj.conditions.append(CSRCondition(
                        type=FAILED, reason="SigningError", message=str(e),
                        last_update_time=self.clock.now()))
                return obj

            self.store.guaranteed_update("certificatesigningrequests", name, fail)
            return

        def fill(obj):
            if not obj.certificate:
                obj.certificate = token
            return obj

        self.store.guaranteed_update("certificatesigningrequests", name, fill)


class CSRCleanerController(Controller):
    """Deletes stale CSRs (pkg/controller/certificates/cleaner): denied/failed
    after 1h, issued after 1h, pending after 24h — drive via monitor()."""

    watch_kinds = ("certificatesigningrequests",)
    DENIED_TTL = 3600.0
    ISSUED_TTL = 3600.0
    PENDING_TTL = 86400.0
    SWEEP_INTERVAL = 60.0

    def __init__(self, store, clock=None):
        super().__init__(store, clock)
        self._last_sweep = float("-inf")

    def key_of_object(self, kind, obj):
        return obj.metadata.name

    def reconcile_once(self) -> int:
        # staleness is time-driven, not event-driven: the daemon loop must
        # re-examine quiet CSRs periodically or nothing ever ages out
        if self.clock.now() - self._last_sweep >= self.SWEEP_INTERVAL:
            self._last_sweep = self.clock.now()
            csrs, _ = self.store.list("certificatesigningrequests")
            for csr in csrs:
                self._mark(csr.metadata.name)
        return super().reconcile_once()

    def monitor(self) -> None:
        csrs, _ = self.store.list("certificatesigningrequests")
        for csr in csrs:
            self._mark(csr.metadata.name)
        self.process()

    def sync(self, name: str) -> None:
        try:
            csr = self.store.get("certificatesigningrequests", name)
        except NotFoundError:
            return
        age = self.clock.now() - csr.metadata.creation_timestamp
        stale = ((csr.denied or csr.condition(FAILED)) and age > self.DENIED_TTL
                 or csr.certificate and age > self.ISSUED_TTL
                 or not csr.conditions and age > self.PENDING_TTL)
        if stale:
            try:
                self.store.delete("certificatesigningrequests", name)
            except NotFoundError:
                pass
