"""DaemonSet controller: one pod per eligible node.

reference: pkg/controller/daemon/daemon_controller.go (syncDaemonSet ->
podsShouldBeOnNode; eligibility = nodeSelector/affinity match + taints
tolerated). The reference creates pods with node affinity and lets the
scheduler bind them; here the controller sets spec.nodeName directly (the
pre-1.12 daemon behavior) — the placement decision is the same because
eligibility is evaluated with the scheduler's own helpers.
"""

from __future__ import annotations

from typing import Optional

from ..api import Pod, find_matching_untolerated_taint
from ..api.types import TAINT_NO_EXECUTE, TAINT_NO_SCHEDULE, Toleration
from ..api.workloads import DaemonSet
from ..scheduler.plugins.helpers import node_matches_node_selector_and_affinity
from ..store import AlreadyExistsError, NotFoundError
from .base import Controller

# tolerations every daemon pod gets (daemon_controller.go AddOrUpdateDaemonPodTolerations)
_AUTO_TOLERATIONS = (
    Toleration(key="node.kubernetes.io/not-ready", operator="Exists", effect=TAINT_NO_EXECUTE),
    Toleration(key="node.kubernetes.io/unreachable", operator="Exists", effect=TAINT_NO_EXECUTE),
    Toleration(key="node.kubernetes.io/unschedulable", operator="Exists", effect=TAINT_NO_SCHEDULE),
)


from .revision import REVISION_LABEL  # noqa: F401  (shared fingerprint home)


def revision_hash(ds: DaemonSet) -> str:
    from .revision import revision_name

    return revision_name(ds.metadata.name, ds.spec.template)


def ds_owner_ref(ds: DaemonSet) -> dict:
    return {"apiVersion": "apps/v1", "kind": "DaemonSet", "name": ds.metadata.name,
            "uid": ds.metadata.uid, "controller": True}


def _owned(pod: Pod, ds: DaemonSet) -> bool:
    return any(r.get("kind") == "DaemonSet" and r.get("uid") == ds.metadata.uid
               for r in pod.metadata.owner_references)


class DaemonSetController(Controller):
    watch_kinds = ("daemonsets", "pods", "nodes")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "daemonsets":
            return obj.key
        if kind == "nodes":
            return "*"  # node churn resyncs every DaemonSet
        for ref in obj.metadata.owner_references:
            if ref.get("kind") == "DaemonSet":
                return f"{obj.metadata.namespace}/{ref['name']}"
        return None

    def sync(self, key: str) -> None:
        if key == "*":
            sets, _ = self.store.list("daemonsets")
            for ds in sets:
                self.sync(ds.key)
            return
        try:
            ds: DaemonSet = self.store.get("daemonsets", key)
        except NotFoundError:
            self._delete_owned(key)
            return
        nodes, _ = self.store.list("nodes")
        # the probe pod is node-independent: build it once per sync
        probe = ds.spec.template.make_pod("probe", ds.metadata.namespace)
        tolerations = list(probe.spec.tolerations) + list(_AUTO_TOLERATIONS)
        eligible = {n.metadata.name for n in nodes
                    if self._should_run(probe, tolerations, n)}
        pods, _ = self.store.list(
            "pods", lambda p: p.metadata.namespace == ds.metadata.namespace
            and _owned(p, ds))
        have = {}
        for p in pods:
            if p.is_terminal():
                try:
                    self.store.delete("pods", p.key)  # restart daemon pods
                except NotFoundError:
                    pass
                continue
            have.setdefault(p.spec.node_name, p)
        rev = revision_hash(ds)
        for node_name in eligible - set(have):
            self._create_pod(ds, node_name, rev)
        misscheduled = 0
        for node_name, pod in have.items():
            if node_name not in eligible:
                misscheduled += 1
                try:
                    self.store.delete("pods", pod.key)
                except NotFoundError:
                    pass

        # rolling update (daemon/update.go rollingUpdate): delete up to
        # maxUnavailable stale-revision pods per sync. Unavailable counts
        # every ELIGIBLE node without a Running pod — including nodes whose
        # replacement was just created (absent from the pre-sync `have`) —
        # or the budget would double-spend across syncs.
        if ds.spec.update_strategy == "RollingUpdate":
            on_node = {n: p for n, p in have.items() if n in eligible}
            stale = [p for p in on_node.values()
                     if p.metadata.labels.get(REVISION_LABEL) != rev]
            # already-down stale pods are deleted WITHOUT charging the budget
            # (daemon/update.go deletes unavailable old pods first): a pod
            # stuck Pending/CrashLoop on the old template must not stall the
            # very rollout that would fix it
            stale_down = [p for p in stale if p.status.phase != "Running"]
            stale_up = [p for p in stale if p.status.phase == "Running"]
            for p in stale_down:
                try:
                    self.store.delete("pods", p.key)
                except NotFoundError:
                    pass
            unavailable = sum(
                1 for n in eligible
                if n not in have or have[n].status.phase != "Running")
            budget = max(0, ds.spec.max_unavailable - unavailable)
            for p in sorted(stale_up, key=lambda p: p.spec.node_name)[:budget]:
                try:
                    self.store.delete("pods", p.key)
                except NotFoundError:
                    pass
        ready = sum(1 for n, p in have.items()
                    if n in eligible and p.status.phase == "Running")
        updated = sum(1 for n, p in have.items()
                      if n in eligible
                      and p.metadata.labels.get(REVISION_LABEL) == rev)

        def mutate(obj: DaemonSet) -> DaemonSet:
            obj.status.desired_number_scheduled = len(eligible)
            obj.status.current_number_scheduled = len(eligible & set(have))
            obj.status.number_ready = ready
            obj.status.number_misscheduled = misscheduled
            obj.status.updated_number_scheduled = updated
            obj.status.observed_generation = obj.metadata.generation
            return obj

        try:
            self.store.guaranteed_update("daemonsets", key, mutate)
        except NotFoundError:
            pass

    @staticmethod
    def _should_run(probe: Pod, tolerations, node) -> bool:
        """nodeShouldRunDaemonPod: selector/affinity + tolerated taints."""
        if not node_matches_node_selector_and_affinity(probe, node):
            return False
        return find_matching_untolerated_taint(node.spec.taints, tolerations) is None

    def _create_pod(self, ds: DaemonSet, node_name: str, rev: str) -> None:
        name = f"{ds.metadata.name}-{node_name}"
        pod = ds.spec.template.make_pod(name, ds.metadata.namespace, ds_owner_ref(ds))
        pod.metadata.labels[REVISION_LABEL] = rev
        pod.spec.tolerations.extend(_AUTO_TOLERATIONS)
        pod.spec.node_name = node_name
        try:
            self.store.create("pods", pod)
        except AlreadyExistsError:
            pass

    def _delete_owned(self, key: str) -> None:
        ns, name = key.split("/", 1)
        pods, _ = self.store.list(
            "pods", lambda p: p.metadata.namespace == ns and any(
                r.get("kind") == "DaemonSet" and r.get("name") == name
                for r in p.metadata.owner_references))
        for p in pods:
            try:
                self.store.delete("pods", p.key)
            except NotFoundError:
                pass
