"""Horizontal pod autoscaler controller.

reference: pkg/controller/podautoscaler/horizontal.go — desiredReplicas =
ceil(currentReplicas * currentUtilization / targetUtilization), clamped to
[minReplicas, maxReplicas], with a scale-down stabilization window. Metrics
come from an injected usage function (the metrics-server boundary): by default
pod CPU usage is read from the `metrics.k8s.io/cpu-usage` annotation (millis),
which the hollow kubelet can stamp.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

from ..api import Pod
from ..api.policy import HorizontalPodAutoscaler
from ..api.resources import quantity_milli_value
from ..store import NotFoundError
from .base import Controller

USAGE_ANNOTATION = "metrics.k8s.io/cpu-usage"
TOLERANCE = 0.1  # horizontal.go defaultTolerance

TARGET_RESOURCE = {"Deployment": "deployments", "ReplicaSet": "replicasets",
                   "StatefulSet": "statefulsets"}


def annotation_usage(pod: Pod) -> Optional[int]:
    raw = pod.metadata.annotations.get(USAGE_ANNOTATION)
    return quantity_milli_value(raw) if raw is not None else None


class HorizontalPodAutoscalerController(Controller):
    watch_kinds = ("horizontalpodautoscalers",)

    def __init__(self, store, clock=None,
                 usage_fn: Callable[[Pod], Optional[int]] = annotation_usage,
                 downscale_stabilization: float = 300.0):
        super().__init__(store, clock)
        self.usage_fn = usage_fn
        self.downscale_stabilization = downscale_stabilization

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        return obj.key

    def resync(self) -> None:
        """Periodic metric sweep (the reference reconciles every 15s)."""
        hpas, _ = self.store.list("horizontalpodautoscalers")
        for h in hpas:
            self._mark(h.key)
        self.process()

    def sync(self, key: str) -> None:
        try:
            hpa: HorizontalPodAutoscaler = self.store.get(
                "horizontalpodautoscalers", key)
        except NotFoundError:
            return
        resource = TARGET_RESOURCE.get(hpa.target_kind)
        if resource is None:
            return
        target_key = f"{hpa.metadata.namespace}/{hpa.target_name}"
        try:
            target = self.store.get(resource, target_key)
        except NotFoundError:
            return
        selector = target.spec.selector
        pods, _ = self.store.list(
            "pods", lambda p: p.metadata.namespace == hpa.metadata.namespace
            and not p.is_terminal()
            and (selector.matches(p.metadata.labels) if selector is not None
                 else all(p.metadata.labels.get(k) == v
                          for k, v in target.spec.template.metadata.labels.items())))
        current = target.spec.replicas
        desired = self._desired_replicas(hpa, pods, current)
        if desired != current:
            if desired < current:
                # scale-down stabilization (horizontal.go stabilizeRecommendation)
                last = hpa.last_scale_time or 0.0
                if self.clock.now() - last < self.downscale_stabilization:
                    desired = current
            if desired != current:
                def scale(obj):
                    obj.spec.replicas = desired
                    return obj

                try:
                    self.store.guaranteed_update(resource, target_key, scale)
                except NotFoundError:
                    return

        def mutate(obj: HorizontalPodAutoscaler) -> HorizontalPodAutoscaler:
            obj.current_replicas = current
            obj.desired_replicas = desired
            if desired != current:
                obj.last_scale_time = self.clock.now()
            return obj

        try:
            self.store.guaranteed_update("horizontalpodautoscalers", key, mutate)
        except NotFoundError:
            pass

    def _desired_replicas(self, hpa: HorizontalPodAutoscaler, pods, current: int) -> int:
        usages, requests = [], []
        for p in pods:
            u = self.usage_fn(p)
            if u is None:
                continue
            req = sum(quantity_milli_value(
                (c.resources.get("requests") or {}).get("cpu", 0))
                for c in p.spec.containers)
            if req <= 0:
                continue
            usages.append(u)
            requests.append(req)
        if not usages:
            return max(hpa.min_replicas, min(current, hpa.max_replicas))
        utilization = sum(usages) / sum(requests)  # fraction of requested
        target = hpa.target_cpu_utilization / 100.0
        ratio = utilization / target
        if abs(ratio - 1.0) <= TOLERANCE:
            desired = current
        else:
            desired = math.ceil(len(usages) * ratio)
        return max(hpa.min_replicas, min(desired, hpa.max_replicas))
