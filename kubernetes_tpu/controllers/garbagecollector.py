"""Garbage collector: ownerReference-based cascading deletion.

reference: pkg/controller/garbagecollector/garbagecollector.go — builds a
dependency graph from ownerReferences and deletes dependents whose controller
owner is gone (background cascading deletion). This implementation rescans the
store's object graph per sync round instead of maintaining the graph
incrementally; same observable behavior on delete.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..store import NotFoundError
from .base import Controller

# kinds that carry ownerReferences worth scanning, and where their owners live
KIND_OF = {
    "ReplicaSet": "replicasets",
    "Deployment": "deployments",
    "StatefulSet": "statefulsets",
    "DaemonSet": "daemonsets",
    "Job": "jobs",
    "CronJob": "cronjobs",
    "Pod": "pods",
    "Service": "services",
}


class GarbageCollector(Controller):
    watch_kinds = ("pods", "replicasets", "jobs", "endpointslices",
                   "persistentvolumeclaims")

    SWEEP_INTERVAL = 30.0

    def __init__(self, store, clock=None):
        super().__init__(store, clock)
        self._last_sweep = float("-inf")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if obj.metadata.owner_references:
            return f"{kind}|{self.store.object_key(obj)}"
        return None

    def reconcile_once(self) -> int:
        """Event-driven marks plus a periodic full-store sweep: owner DELETION
        does not emit events on the dependents (podlogs, orphaned pods), so
        only the graph resync catches them (the reference GC's absentOwnerCache
        + monitor resync)."""
        n = super().reconcile_once()
        if self.clock.now() - self._last_sweep >= self.SWEEP_INTERVAL:
            self._last_sweep = self.clock.now()
            n += self.sweep()
        return n

    def sweep(self) -> int:
        """Full-store orphan scan (the GC's graph resync). Returns #deleted."""
        deleted = 0
        for kind in list(self.store.kinds()):
            objs, _ = self.store.list(kind)
            for obj in objs:
                if self._is_orphan(obj):
                    if self._delete(kind, self.store.object_key(obj)):
                        deleted += 1
        return deleted

    def sync(self, key: str) -> None:
        kind, _, obj_key = key.partition("|")
        try:
            obj = self.store.get(kind, obj_key)
        except NotFoundError:
            return
        if self._is_orphan(obj):
            self._delete(kind, obj_key)

    def _owner_exists(self, namespace: str, ref: Dict) -> bool:
        owner_kind = KIND_OF.get(ref.get("kind", ""))
        if owner_kind is None:
            return True  # unknown owner kinds are left alone (virtual nodes)
        key = f"{namespace}/{ref['name']}" if namespace else ref["name"]
        try:
            owner = self.store.get(owner_kind, key)
        except NotFoundError:
            return False
        # uid must match: a recreated same-name owner does not adopt (gc graph)
        return not ref.get("uid") or owner.metadata.uid == ref["uid"]

    def _is_orphan(self, obj) -> bool:
        refs = obj.metadata.owner_references
        if not refs:
            return False
        controller_refs = [r for r in refs if r.get("controller")] or refs
        return not any(self._owner_exists(obj.metadata.namespace, r)
                       for r in controller_refs)

    def _delete(self, kind: str, key: str) -> bool:
        try:
            self.store.delete(kind, key)
            return True
        except NotFoundError:
            return False
