"""Node lifecycle + taint eviction — the failure-detection loop.

reference: pkg/controller/nodelifecycle/node_lifecycle_controller.go:262-289
(NotReady after nodeMonitorGracePeriod, NoExecute taints) and
pkg/controller/tainteviction (evict pods that don't tolerate NoExecute taints).

Health signal: each node agent renews a coordination Lease named after the node
(kubelet's Lease heartbeat). A lease older than the grace period marks the node
NotReady and taints it; recovery clears both. The eviction half deletes pods on
NoExecute-tainted nodes (honoring tolerations + tolerationSeconds is left to
tolerationSeconds=0 semantics this round: tolerating pods stay indefinitely).
"""

from __future__ import annotations

from typing import Optional

from ..api import Node, Taint
from ..api.types import NodeCondition, TAINT_NO_EXECUTE, TAINT_NO_SCHEDULE
from ..store import NotFoundError
from .base import Controller

NOT_READY_TAINT = "node.kubernetes.io/not-ready"
DEFAULT_GRACE_PERIOD = 40.0  # nodeMonitorGracePeriod default


class NodeLifecycleController(Controller):
    watch_kinds = ("nodes", "leases")

    def __init__(self, store, clock=None, grace_period: float = DEFAULT_GRACE_PERIOD):
        super().__init__(store, clock)
        self.grace_period = grace_period

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        # leases are named after their node, so both kinds key by object name
        return obj.metadata.name

    def monitor(self) -> None:
        """Periodic health sweep (the controller's 5s monitor loop)."""
        nodes, _ = self.store.list("nodes")
        for n in nodes:
            self._mark(n.metadata.name)
        self.process()

    def sync(self, name: str) -> None:
        try:
            node: Node = self.store.get("nodes", name)
        except NotFoundError:
            return
        ready = self._node_healthy(name)
        # match by (key, effect): the TaintNodesByCondition admission plugin
        # seeds new nodes with a NoSchedule not-ready taint, which must not
        # suppress this controller's NoExecute escalation for unhealthy nodes
        has_noexec = any(t.key == NOT_READY_TAINT and t.effect == TAINT_NO_EXECUTE
                         for t in node.spec.taints)
        has_nosched = any(t.key == NOT_READY_TAINT and t.effect == TAINT_NO_SCHEDULE
                          for t in node.spec.taints)
        has_any = any(t.key == NOT_READY_TAINT for t in node.spec.taints)
        if ready and has_any:
            def clear(obj: Node) -> Node:
                obj.spec.taints = [t for t in obj.spec.taints if t.key != NOT_READY_TAINT]
                self._set_ready_condition(obj, True)
                return obj

            self.store.guaranteed_update("nodes", name, clear)
        elif not ready and not (has_noexec and has_nosched):
            def taint(obj: Node) -> Node:
                # BOTH effects, like the reference controller: NoExecute
                # drives the eviction chain, while NoSchedule keeps the
                # scheduler off the dead node — without it, replacements
                # that tolerate not-ready:NoExecute (the admission-defaulted
                # 300s toleration) would land right back on the corpse and
                # churn through eviction again (ISSUE 6 node-death chain)
                effects = {t.effect for t in obj.spec.taints
                           if t.key == NOT_READY_TAINT}
                for eff in (TAINT_NO_EXECUTE, TAINT_NO_SCHEDULE):
                    if eff not in effects:
                        obj.spec.taints.append(
                            Taint(key=NOT_READY_TAINT, effect=eff))
                self._set_ready_condition(obj, False)
                return obj

            self.store.guaranteed_update("nodes", name, taint)
        if not ready:
            self._evict(name)

    def _node_healthy(self, name: str) -> bool:
        try:
            lease = self.store.get("leases", f"kube-node-lease/{name}")
        except NotFoundError:
            return False  # no heartbeat ever observed
        return (self.clock.now() - lease.renew_time) <= self.grace_period

    def _set_ready_condition(self, node: Node, ready: bool) -> None:
        node.status.conditions = [c for c in node.status.conditions if c.type != "Ready"]
        node.status.conditions.append(NodeCondition(
            type="Ready",
            status="True" if ready else "False",
            reason="KubeletReady" if ready else "NodeStatusUnknown",
            last_transition_time=self.clock.now(),
        ))

    # -- taint eviction (pkg/controller/tainteviction) -------------------------

    def _evict(self, node_name: str) -> None:
        pods, _ = self.store.list("pods", lambda p: p.spec.node_name == node_name)
        for p in pods:
            tolerates = any(
                t.tolerates(Taint(key=NOT_READY_TAINT, effect=TAINT_NO_EXECUTE))
                for t in p.spec.tolerations
            )
            if not tolerates:
                try:
                    self.store.delete("pods", p.key)
                except NotFoundError:
                    pass
