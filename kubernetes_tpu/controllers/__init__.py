"""L4 — reconciling control loops."""
