"""L4 — reconciling control loops (reference: pkg/controller)."""

from .base import Controller  # noqa: F401
from .deployment import DeploymentController  # noqa: F401
from .node_lifecycle import NodeLifecycleController  # noqa: F401
from .replicaset import ReplicaSetController  # noqa: F401
