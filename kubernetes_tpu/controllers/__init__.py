"""L4 — reconciling control loops (reference: pkg/controller)."""

from .certificates import (  # noqa: F401
    CSRApprovingController,
    CSRCleanerController,
    CSRSigningController,
)
from .apiservice import APIServiceAvailabilityController  # noqa: F401
from .base import Controller  # noqa: F401
from .daemonset import DaemonSetController  # noqa: F401
from .deployment import DeploymentController  # noqa: F401
from .disruption import DisruptionController  # noqa: F401
from .endpointslice import EndpointSliceController  # noqa: F401
from .garbagecollector import GarbageCollector  # noqa: F401
from .job import CronJobController, JobController  # noqa: F401
from .namespace import NamespaceController  # noqa: F401
from .node_lifecycle import NodeLifecycleController  # noqa: F401
from .podgc import PodGCController  # noqa: F401
from .podautoscaler import HorizontalPodAutoscalerController  # noqa: F401
from .replicaset import ReplicaSetController  # noqa: F401
from .resourceclaim import ResourceClaimController  # noqa: F401
from .resourcequota import ResourceQuotaController  # noqa: F401
from .serviceaccount import (  # noqa: F401
    EventTTLController,
    ServiceAccountController,
    TTLAfterFinishedController,
)
from .statefulset import StatefulSetController  # noqa: F401
from .volume import AttachDetachController, PersistentVolumeBinder  # noqa: F401
from .tainteviction import TaintEvictionController  # noqa: F401
