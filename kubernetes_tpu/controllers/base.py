"""Controller base: the informer -> workqueue -> sync(key) reconcile pattern.

reference: pkg/controller (e.g. replicaset/replica_set.go:116,150,677) and
client-go's SharedIndexInformer + rate-limited workqueue. One reconcile loop
per resource kind; level-triggered: sync() reads desired+actual from the store
and converges, so replays and missed events are harmless.

Reconcile-loop telemetry (ISSUE 9): every subclass inherits a
ReconcileRecorder (obs/reconcile.py — the flight recorder's ring/stage
machinery) with per-LOOP spans: one histogram observation per pump that
ingested events, one record per process() drain, requeue/error counters, and
workqueue depth/oldest-age. Instrumentation is per LOOP, never per key or
per event inside the drain loops (schedlint HP001 now covers this file);
first-marked timestamps use ONE shared clock read per pump, and the
oldest-age scan is throttled to 1/s (the PR 7 queue-telemetry idiom).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Dict, Optional

from ..obs import tracebuf as _tracebuf
from ..obs.reconcile import ReconcileRecorder, register_controller
from ..store import APIStore
from ..utils import Clock


class Controller:
    """Subclasses define `watch_kinds`, `key_of(event) -> sync key or None`,
    and `sync(key)`. Drive with pump()+process() (tests) or start() (daemon)."""

    watch_kinds: tuple = ()

    def __init__(self, store: APIStore, clock: Optional[Clock] = None,
                 telemetry: bool = True):
        self.store = store
        self.clock = clock or Clock()
        self._watch = None
        # dirty key -> first-marked timestamp (the workqueue; the timestamp
        # feeds the oldest-age gauge and costs a dict slot, not a clock
        # read — markers pass ONE shared per-drain timestamp)
        self._dirty: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sync_errors = 0
        # per-loop reconcile recorder (ISSUE 9). telemetry=False keeps the
        # recorder fully inert AND unregistered — the parity oracle for the
        # recorder-on/off byte-identical tests.
        self.recorder = ReconcileRecorder(type(self).__name__,
                                          enabled=telemetry)
        # oldest-dirty-age scan throttle (O(depth) under the lock)
        self._age_next = 0.0
        self._age_last = 0.0
        if telemetry:
            register_controller(type(self).__name__, self)

    # -- event intake ----------------------------------------------------------

    def sync_all(self) -> None:
        """Initial LIST: mark every existing object of the primary kind dirty."""
        lists, rv = self.store.list_many(self.watch_kinds)
        now = self.clock.now()  # ONE shared first-marked stamp for the seed
        for kind in self.watch_kinds:
            for obj in lists[kind]:
                key = self.key_of_object(kind, obj)
                if key:
                    self._mark(key, now)
        # kind-filtered subscription: high-volume kinds this controller
        # ignores (e.g. events) never consume its watch buffer
        self._watch = self.store.watch(kind=set(self.watch_kinds), since_rv=rv)

    def pump(self, max_events: int = 10_000) -> int:
        if self._watch is None:
            return 0
        if self._watch.terminated:
            # evicted as a slow watcher: relist + rewatch (Reflector contract)
            self._watch.stop()
            self.sync_all()
            return 0
        t0 = time.perf_counter()
        n = 0
        now = self.clock.now()  # shared first-marked stamp for this drain
        # bounded drain: events beyond the cap stay buffered for the next
        # pump (breaking out of a full drain() would DISCARD them — the bug
        # that truncated the scheduler's 100k backlog)
        for ev in self._watch.drain(max_events):
            if ev.kind in self.watch_kinds:
                key = self.key_of_object(ev.kind, ev.obj)
                if key:
                    self._mark(key, now)
                n += 1
        self.recorder.pump(n, time.perf_counter() - t0)
        return n

    def _mark(self, key: str, ts: Optional[float] = None) -> None:
        with self._lock:
            # first-marked time sticks across re-marks: the age gauge
            # measures how long the oldest key has been waiting, and a
            # retry re-mark must not reset the meter
            self._dirty.setdefault(
                key, ts if ts is not None else self.clock.now())

    # -- processing ------------------------------------------------------------

    def process(self, max_keys: int = 10_000) -> int:
        """Drain the dirty set through sync(). Returns #keys processed.
        Instrumented per LOOP (never per key): two perf_counter reads and
        one recorder.loop() around the whole drain."""
        now = self.clock.now()
        with self._lock:
            keys = list(self._dirty)[:max_keys]
            for k in keys:
                self._dirty.pop(k, None)
        if not keys:
            return 0
        t0 = time.perf_counter()
        errors0 = self.sync_errors
        for key in keys:
            try:
                self.sync(key)
            except Exception:
                self.sync_errors += 1
                traceback.print_exc()
                self._mark(key, now)  # retry (rate limiting elided)
        errs = self.sync_errors - errors0
        t1 = time.perf_counter()
        self.recorder.loop(keys=len(keys), errors=errs, requeues=errs,
                           seconds=t1 - t0, depth=len(self._dirty))
        # trace timeline (ISSUE 18): one slice per reconcile DRAIN (never
        # per key) on this controller's track
        if _tracebuf.ACTIVE is not None:
            _tracebuf.ACTIVE.note_span(
                "ctl-%s" % type(self).__name__, "reconcile", t0, t1,
                cat="reconcile", args={"keys": len(keys), "errors": errs})
        return len(keys)

    def reconcile_once(self) -> int:
        self.pump()
        return self.process()

    def run_until_stable(self, max_rounds: int = 50) -> None:
        for _ in range(max_rounds):
            if self.reconcile_once() == 0:
                return

    # -- telemetry (ISSUE 9) ---------------------------------------------------

    def workqueue_depth(self) -> int:
        return len(self._dirty)  # len() is atomic; a gauge read, not a sync

    def oldest_dirty_age_s(self) -> float:
        """Age of the oldest still-dirty key. The scan is O(depth) under the
        workqueue lock, so it is throttled to 1/s with a cached value — a
        dashboard read, not a control input."""
        now = self.clock.now()
        if now < self._age_next:
            return self._age_last
        self._age_next = now + 1.0
        with self._lock:
            oldest = min(self._dirty.values(), default=None)
        self._age_last = (now - oldest) if oldest is not None else 0.0
        return self._age_last

    def reconcile_stats(self) -> Dict:
        """The /debug/controlstats payload for this controller."""
        out = self.recorder.snapshot()
        out["depth"] = self.workqueue_depth()
        out["oldest_dirty_age_s"] = round(self.oldest_dirty_age_s(), 3)
        out["watch_kinds"] = list(self.watch_kinds)
        return out

    # -- daemon mode -----------------------------------------------------------

    def start(self, interval: float = 0.05) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if self.reconcile_once() == 0:
                    self.clock.sleep(interval)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._watch is not None:
            self._watch.stop()
            self._watch = None

    # -- to implement ----------------------------------------------------------

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        raise NotImplementedError

    def sync(self, key: str) -> None:
        raise NotImplementedError
