"""Controller base: the informer -> workqueue -> sync(key) reconcile pattern.

reference: pkg/controller (e.g. replicaset/replica_set.go:116,150,677) and
client-go's SharedIndexInformer + rate-limited workqueue. One reconcile loop
per resource kind; level-triggered: sync() reads desired+actual from the store
and converges, so replays and missed events are harmless.
"""

from __future__ import annotations

import threading
import traceback
from typing import Optional, Set

from ..store import APIStore
from ..utils import Clock


class Controller:
    """Subclasses define `watch_kinds`, `key_of(event) -> sync key or None`,
    and `sync(key)`. Drive with pump()+process() (tests) or start() (daemon)."""

    watch_kinds: tuple = ()

    def __init__(self, store: APIStore, clock: Optional[Clock] = None):
        self.store = store
        self.clock = clock or Clock()
        self._watch = None
        self._dirty: Set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sync_errors = 0

    # -- event intake ----------------------------------------------------------

    def sync_all(self) -> None:
        """Initial LIST: mark every existing object of the primary kind dirty."""
        lists, rv = self.store.list_many(self.watch_kinds)
        for kind in self.watch_kinds:
            for obj in lists[kind]:
                key = self.key_of_object(kind, obj)
                if key:
                    self._mark(key)
        # kind-filtered subscription: high-volume kinds this controller
        # ignores (e.g. events) never consume its watch buffer
        self._watch = self.store.watch(kind=set(self.watch_kinds), since_rv=rv)

    def pump(self, max_events: int = 10_000) -> int:
        if self._watch is None:
            return 0
        if self._watch.terminated:
            # evicted as a slow watcher: relist + rewatch (Reflector contract)
            self._watch.stop()
            self.sync_all()
            return 0
        n = 0
        # bounded drain: events beyond the cap stay buffered for the next
        # pump (breaking out of a full drain() would DISCARD them — the bug
        # that truncated the scheduler's 100k backlog)
        for ev in self._watch.drain(max_events):
            if ev.kind in self.watch_kinds:
                key = self.key_of_object(ev.kind, ev.obj)
                if key:
                    self._mark(key)
                n += 1
        return n

    def _mark(self, key: str) -> None:
        with self._lock:
            self._dirty.add(key)

    # -- processing ------------------------------------------------------------

    def process(self, max_keys: int = 10_000) -> int:
        """Drain the dirty set through sync(). Returns #keys processed."""
        with self._lock:
            keys = list(self._dirty)[:max_keys]
            for k in keys:
                self._dirty.discard(k)
        for key in keys:
            try:
                self.sync(key)
            except Exception:
                self.sync_errors += 1
                traceback.print_exc()
                self._mark(key)  # retry (rate limiting elided)
        return len(keys)

    def reconcile_once(self) -> int:
        self.pump()
        return self.process()

    def run_until_stable(self, max_rounds: int = 50) -> None:
        for _ in range(max_rounds):
            if self.reconcile_once() == 0:
                return

    # -- daemon mode -----------------------------------------------------------

    def start(self, interval: float = 0.05) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if self.reconcile_once() == 0:
                    self.clock.sleep(interval)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._watch is not None:
            self._watch.stop()
            self._watch = None

    # -- to implement ----------------------------------------------------------

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        raise NotImplementedError

    def sync(self, key: str) -> None:
        raise NotImplementedError
