"""Taint-based eviction: pods on NoExecute-tainted nodes are evicted, honoring
tolerations and tolerationSeconds.

reference: pkg/controller/tainteviction/taint_eviction.go — per-pod timed
eviction queue: an untolerated NoExecute taint evicts immediately; a toleration
with tolerationSeconds delays eviction by that long; tolerations without
tolerationSeconds keep the pod indefinitely.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api import Pod
from ..api.types import TAINT_NO_EXECUTE
from ..store import NotFoundError
from .base import Controller


class TaintEvictionController(Controller):
    watch_kinds = ("nodes", "pods")

    def __init__(self, store, clock=None):
        super().__init__(store, clock)
        # pod key -> (eviction deadline, taint-set signature that produced it).
        # The signature lets a taint-set change cancel+reschedule the timed
        # eviction (TimedWorkerQueue semantics) in either direction — a new
        # tighter taint shortens the deadline, removing the tight taint
        # restores the longer one — without the deadline sliding forward on
        # every no-change resync.
        self._deadlines: Dict[str, tuple] = {}

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "nodes":
            return obj.metadata.name
        return f"pod|{obj.key}" if obj.spec.node_name else None

    def tick(self) -> None:
        """Fire due timed evictions (the reference's TimedWorkerQueue)."""
        now = self.clock.now()
        for pod_key, (deadline, _sig) in list(self._deadlines.items()):
            if deadline <= now:
                self._deadlines.pop(pod_key, None)
                self._evict(pod_key)
        # re-examine all tainted nodes so new pods get queued
        nodes, _ = self.store.list("nodes",
                                   lambda n: any(t.effect == TAINT_NO_EXECUTE
                                                 for t in n.spec.taints))
        for n in nodes:
            self._mark(n.metadata.name)
        self.process()

    def sync(self, key: str) -> None:
        if key.startswith("pod|"):
            pod_key = key[4:]
            try:
                pod: Pod = self.store.get("pods", pod_key)
            except NotFoundError:
                self._deadlines.pop(pod_key, None)
                return
            self._check_pod(pod)
            return
        # node key: examine every pod bound to it
        try:
            node = self.store.get("nodes", key)
        except NotFoundError:
            return
        taints = [t for t in node.spec.taints if t.effect == TAINT_NO_EXECUTE]
        pods, _ = self.store.list("pods", lambda p: p.spec.node_name == key
                                  and not p.is_terminal())
        if not taints:
            for p in pods:
                self._deadlines.pop(p.key, None)
            return
        for p in pods:
            self._check_pod(p, node=node)

    def _check_pod(self, pod: Pod, node=None) -> None:
        if node is None:
            try:
                node = self.store.get("nodes", pod.spec.node_name)
            except NotFoundError:
                return
        taints = [t for t in node.spec.taints if t.effect == TAINT_NO_EXECUTE]
        if not taints:
            self._deadlines.pop(pod.key, None)
            return
        # minTolerationSeconds over all taints (getMinTolerationTime): every
        # taint must be tolerated; the tightest tolerationSeconds wins
        min_seconds: Optional[float] = None
        for taint in taints:
            matching = [t for t in pod.spec.tolerations if t.tolerates(taint)]
            if not matching:
                self._deadlines.pop(pod.key, None)
                self._evict(pod.key)
                return
            secs = [t.toleration_seconds for t in matching
                    if t.toleration_seconds is not None]
            if secs:
                s = min(secs)
                min_seconds = s if min_seconds is None else min(min_seconds, s)
        if min_seconds is None:
            self._deadlines.pop(pod.key, None)  # tolerated forever
        else:
            sig = tuple(sorted((t.key, t.value, t.effect) for t in taints))
            existing = self._deadlines.get(pod.key)
            if existing is None or existing[1] != sig:
                # new countdown, or the taint set changed: cancel + reschedule
                # from now with the recomputed minimum (may tighten or loosen)
                self._deadlines[pod.key] = (self.clock.now() + min_seconds, sig)

    def _evict(self, pod_key: str) -> None:
        try:
            self.store.delete("pods", pod_key)
        except NotFoundError:
            pass
