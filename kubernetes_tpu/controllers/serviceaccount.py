"""ServiceAccount + TTL-after-finished controllers.

reference: pkg/controller/serviceaccount/serviceaccounts_controller.go (every
namespace gets a 'default' ServiceAccount) and
pkg/controller/ttlafterfinished/ttlafterfinished_controller.go (finished Jobs
with ttlSecondsAfterFinished are deleted once the TTL elapses).
"""

from __future__ import annotations

from typing import Optional

from ..api.policy import ServiceAccount
from ..api.types import ObjectMeta, new_uid
from ..store import AlreadyExistsError, NotFoundError
from .base import Controller

# namespaces the apiserver treats as always-existing (admission.py) — the
# controller materializes their default SA too
from ..server.admission import BOOTSTRAP_NAMESPACES


class ServiceAccountController(Controller):
    """Ensures every (non-terminating) namespace has a 'default' SA."""

    watch_kinds = ("namespaces", "serviceaccounts")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "namespaces":
            return obj.metadata.name
        return obj.metadata.namespace  # SA deleted -> recheck its namespace

    def sync_all(self) -> None:
        super().sync_all()
        for ns in BOOTSTRAP_NAMESPACES:
            self._mark(ns)

    def sync(self, name: str) -> None:
        if name not in BOOTSTRAP_NAMESPACES:
            try:
                ns = self.store.get("namespaces", name)
            except NotFoundError:
                return
            if ns.metadata.deletion_timestamp is not None:
                return
        try:
            self.store.get("serviceaccounts", f"{name}/default")
        except NotFoundError:
            try:
                self.store.create("serviceaccounts", ServiceAccount(
                    metadata=ObjectMeta(name="default", namespace=name,
                                        uid=new_uid())))
            except AlreadyExistsError:
                pass


class TTLAfterFinishedController(Controller):
    """Deletes finished Jobs whose ttlSecondsAfterFinished has elapsed.
    Unexpired jobs park in a local timer map instead of re-marking themselves
    (the reference's workqueue AddAfter), so the loop stays idle between
    expiries."""

    watch_kinds = ("jobs",)

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._pending_ttl = {}  # job key -> expiry timestamp

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        return obj.key

    def reconcile_once(self) -> int:
        now = self.clock.now()
        for key, exp in list(self._pending_ttl.items()):
            if now >= exp:
                # keep the entry: sync() consults it for the legacy
                # (timestamp-less) path and pops it on deletion
                self._mark(key)
        return super().reconcile_once()

    def _finished_at(self, job) -> Optional[float]:
        for c in job.status.conditions:  # dicts (workloads.JobStatus)
            if c.get("type") in ("Complete", "Failed") and c.get("status") == "True":
                return (job.status.completion_time
                        or c.get("lastTransitionTime") or 0.0)
        return None

    def sync(self, key: str) -> None:
        try:
            job = self.store.get("jobs", key)
        except NotFoundError:
            self._pending_ttl.pop(key, None)
            return
        ttl = job.spec.ttl_seconds_after_finished
        if ttl is None:
            self._pending_ttl.pop(key, None)
            return
        finished = self._finished_at(job)
        if finished is None:
            self._pending_ttl.pop(key, None)  # condition cleared: stop timing
            return
        if not finished:
            # a terminal condition without a timestamp (legacy object): count
            # the TTL from first observation instead of deleting immediately
            expire = self._pending_ttl.get(key)
            if expire is None:
                self._pending_ttl[key] = self.clock.now() + ttl
                return
        else:
            expire = finished + ttl
        if self.clock.now() >= expire:
            self._pending_ttl.pop(key, None)
            try:
                self.store.delete("jobs", key)
            except NotFoundError:
                pass
        else:
            self._pending_ttl[key] = expire  # AddAfter analog


class EventTTLController(Controller):
    """Expires Event objects after event_ttl (reference: kube-apiserver's
    --event-ttl, default 1h, enforced by etcd leases; here a sweep controller
    since the store has no per-object TTLs). Same timer-map pattern as
    TTLAfterFinished — no busy loops between expiries."""

    watch_kinds = ("events",)

    def __init__(self, *a, event_ttl: float = 3600.0, **kw):
        super().__init__(*a, **kw)
        self.event_ttl = event_ttl
        self._pending: dict = {}  # event key -> expiry

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"

    def reconcile_once(self) -> int:
        now = self.clock.now()
        for key, exp in list(self._pending.items()):
            if now >= exp:
                self._mark(key)
        return super().reconcile_once()

    def sync(self, key: str) -> None:
        try:
            ev = self.store.get("events", key)
        except NotFoundError:
            self._pending.pop(key, None)
            return
        expire = (ev.last_timestamp or self.clock.now()) + self.event_ttl
        if self.clock.now() >= expire:
            self._pending.pop(key, None)
            try:
                self.store.delete("events", key)
            except NotFoundError:
                pass
        else:
            self._pending[key] = expire
