"""Template fingerprinting shared by workload controllers.

reference: pkg/controller/history (ControllerRevision hashing) and the
pod-template-hash / controller-revision-hash labels. One canonical formula:
the template's WIRE FORM serialized with sorted keys — so labels,
annotations (rollout restart patches only an annotation), and every spec
field participate, and dict key order in the manifest cannot produce
spurious rollouts.
"""

from __future__ import annotations

import hashlib
import json


REVISION_LABEL = "controller-revision-hash"


def template_fingerprint(template) -> str:
    """Stable 10-hex-char digest of a PodTemplateSpec."""
    from ..api.serialize import _template_to_dict

    canon = json.dumps(_template_to_dict(template), sort_keys=True)
    return hashlib.sha1(canon.encode()).hexdigest()[:10]


def revision_name(owner_name: str, template) -> str:
    """<owner>-<fingerprint> — the value pods carry in REVISION_LABEL."""
    return f"{owner_name}-{template_fingerprint(template)}"
