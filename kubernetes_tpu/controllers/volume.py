"""Volume controllers: PV↔PVC binding and attach/detach, OUTSIDE the
scheduling cycle.

reference:
  - pkg/controller/volume/persistentvolume/pv_controller.go — the binder:
    an unbound PVC finds its smallest satisfying Available PV and both
    sides commit together; a user-pre-bound PV completes its claim; a
    deleted claim releases its PV (Released phase, reclaim policy Delete
    deletes it). WaitForFirstConsumer classes are left to the scheduler's
    VolumeBinding plugin (plugins/volume.py Reserve/PreBind), exactly as
    the reference's binder skips un-annotated WFFC claims.
  - pkg/controller/volume/attachdetach/attach_detach_controller.go — the
    attach/detach reconciler: desired state = every (PV, node) pair some
    scheduled pod's bound PVC points at; actual state = VolumeAttachment
    objects. Missing attachments are created (and attached synchronously —
    this controller IS the attach backend for the fake runtime), stale
    ones detached.
"""

from __future__ import annotations

from typing import Optional

from ..api.storage import (
    BINDING_WAIT_FOR_FIRST_CONSUMER,
    CLAIM_BOUND,
    VOLUME_AVAILABLE,
    VOLUME_BOUND,
    VOLUME_RELEASED,
    VolumeAttachment,
)
from ..store import AlreadyExistsError, NotFoundError
from .base import Controller


class PersistentVolumeBinder(Controller):
    """pv_controller.go's ClaimWorker + VolumeWorker collapsed into one
    level-triggered reconciler (keys: "pvc:ns/name" / "pv:name")."""

    watch_kinds = ("persistentvolumeclaims", "persistentvolumes",
                   "storageclasses")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "persistentvolumeclaims":
            if obj.spec.volume_name:
                # a (possibly DELETED) bound claim must resync its PV —
                # that's the release path; the claim key alone would
                # dead-end on NotFound
                self._mark(f"pv:{obj.spec.volume_name}")
            return f"pvc:{obj.metadata.namespace}/{obj.metadata.name}"
        if kind == "persistentvolumes":
            return f"pv:{obj.metadata.name}"
        # a StorageClass change can unblock any pending claim
        self._mark_all_pending_claims()
        return None

    def _mark_all_pending_claims(self) -> None:
        claims, _ = self.store.list(
            "persistentvolumeclaims", lambda c: not c.spec.volume_name)
        for c in claims:
            self._mark(f"pvc:{c.key}")

    def _class_of(self, claim):
        """Resolve the claim's StorageClass (None = no class semantics)."""
        name = claim.spec.storage_class_name
        if name is None:
            classes, _ = self.store.list("storageclasses",
                                         lambda c: c.is_default)
            if not classes:
                return None
            return max(classes, key=lambda c: c.metadata.creation_timestamp)
        if name == "":
            return None
        try:
            return self.store.get("storageclasses", name)
        except NotFoundError:
            return None

    def sync(self, key: str) -> None:
        kind, _, rest = key.partition(":")
        if kind == "pvc":
            self._sync_claim(rest)
        else:
            self._sync_volume(rest)

    def _sync_claim(self, key: str) -> None:
        try:
            claim = self.store.get("persistentvolumeclaims", key)
        except NotFoundError:
            return
        if claim.spec.volume_name:
            # Bound only when the named PV exists and isn't taken by another
            # claim (a claim naming a missing volume stays Pending — the
            # reference keeps it Pending/Lost, never usable)
            try:
                pv = self.store.get("persistentvolumes",
                                    claim.spec.volume_name)
            except NotFoundError:
                return
            if pv.spec.claim_ref and pv.spec.claim_ref != claim.key:
                return
            if not pv.spec.claim_ref or pv.phase != VOLUME_BOUND:
                def bind_pv(p):
                    p.spec.claim_ref = claim.key
                    p.phase = VOLUME_BOUND
                    return p

                self.store.guaranteed_update(
                    "persistentvolumes", claim.spec.volume_name, bind_pv)
            if claim.phase != CLAIM_BOUND:
                def mark_bound(c):
                    c.phase = CLAIM_BOUND
                    return c

                self.store.guaranteed_update("persistentvolumeclaims", key,
                                             mark_bound)
            return
        sc = self._class_of(claim)
        if sc is not None and \
                sc.volume_binding_mode == BINDING_WAIT_FOR_FIRST_CONSUMER:
            return  # the scheduler's VolumeBinding plugin owns WFFC claims
        wanted_class = sc.metadata.name if sc is not None else \
            (claim.spec.storage_class_name or "")
        modes = set(claim.spec.access_modes)

        def matches(pv):
            if pv.phase != VOLUME_AVAILABLE:
                return False
            if pv.spec.claim_ref and pv.spec.claim_ref != claim.key:
                return False
            if pv.spec.storage_class_name != wanted_class:
                return False
            if not modes.issubset(set(pv.spec.access_modes)):
                return False
            return pv.spec.capacity >= claim.spec.request

        pvs, _ = self.store.list("persistentvolumes", matches)
        if not pvs:
            return
        # user-pre-bound volume wins; otherwise smallest satisfying fit
        # (pv_controller's findBestMatchForClaim order)
        pre = [pv for pv in pvs if pv.spec.claim_ref == claim.key]
        chosen = pre[0] if pre else min(pvs, key=lambda p: p.spec.capacity)
        with self.store.transaction():
            def bind_pv(pv):
                if pv.spec.claim_ref and pv.spec.claim_ref != claim.key:
                    raise NotFoundError("pv was bound concurrently")
                pv.spec.claim_ref = claim.key
                pv.phase = VOLUME_BOUND
                return pv

            def bind_claim(c):
                c.spec.volume_name = chosen.metadata.name
                c.phase = CLAIM_BOUND
                return c

            try:
                self.store.guaranteed_update("persistentvolumes",
                                             chosen.metadata.name, bind_pv)
            except NotFoundError:
                self._mark(f"pvc:{key}")  # raced; retry with a fresh list
                return
            self.store.guaranteed_update("persistentvolumeclaims", key,
                                         bind_claim)

    def _sync_volume(self, name: str) -> None:
        try:
            pv = self.store.get("persistentvolumes", name)
        except NotFoundError:
            return
        if not pv.spec.claim_ref:
            # a newly-appeared PV may be the one a user-prebound claim names
            claims, _ = self.store.list(
                "persistentvolumeclaims",
                lambda c: c.spec.volume_name == name
                and c.phase != CLAIM_BOUND)
            for c in claims:
                self._mark(f"pvc:{c.key}")
            return
        try:
            claim = self.store.get("persistentvolumeclaims",
                                   pv.spec.claim_ref)
        except NotFoundError:
            claim = None
        if claim is None:
            if pv.phase == VOLUME_AVAILABLE:
                # user-pre-bound PV whose claim does not exist YET: stays
                # Available waiting for it (pv_controller keeps a
                # claimRef-with-empty-UID volume Available, not Released)
                return
            # released: the claim is gone (pv_controller reclaimVolume)
            if pv.spec.reclaim_policy == "Delete":
                try:
                    self.store.delete("persistentvolumes", name)
                except NotFoundError:
                    pass
                return
            if pv.phase != VOLUME_RELEASED:
                def release(p):
                    p.phase = VOLUME_RELEASED
                    return p

                self.store.guaranteed_update("persistentvolumes", name,
                                             release)
            return
        if not claim.spec.volume_name:
            # user pre-bound this PV to the claim: complete the other side
            self._mark(f"pvc:{claim.key}")
        elif pv.phase != VOLUME_BOUND and claim.spec.volume_name == name:
            def mark_bound(p):
                p.phase = VOLUME_BOUND
                return p

            self.store.guaranteed_update("persistentvolumes", name,
                                         mark_bound)


def attachment_name(pv_name: str, node_name: str) -> str:
    return f"va-{pv_name}-{node_name}"


class AttachDetachController(Controller):
    """Whole-state reconcile (the reference's DesiredStateOfWorld vs
    ActualStateOfWorld populators + reconciler, collapsed): one sync pass
    diffs desired (PV, node) pairs against live VolumeAttachments."""

    watch_kinds = ("pods", "persistentvolumeclaims", "volumeattachments")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        return "sync"

    def sync(self, key: str) -> None:
        # predicate pre-filters BEFORE the store's list-copy: only
        # volume-bearing pods pay the copy, not the whole running population
        pods, _ = self.store.list(
            "pods", lambda p: bool(p.spec.node_name) and not p.is_terminal()
            and any(v.pvc_claim_name for v in p.spec.volumes))
        desired = {}
        for pod in pods:
            for vol in pod.spec.volumes:
                if not vol.pvc_claim_name:
                    continue
                try:
                    claim = self.store.get(
                        "persistentvolumeclaims",
                        f"{pod.metadata.namespace}/{vol.pvc_claim_name}")
                except NotFoundError:
                    continue
                if not claim.spec.volume_name:
                    continue
                desired[(claim.spec.volume_name, pod.spec.node_name)] = True
        attachments, _ = self.store.list("volumeattachments")
        actual = {(va.pv_name, va.node_name): va for va in attachments}
        for (pv_name, node), _w in desired.items():
            if (pv_name, node) in actual:
                continue
            try:
                pv = self.store.get("persistentvolumes", pv_name)
                attacher = pv.spec.csi_driver or "kubernetes.io/in-tree"
            except NotFoundError:
                attacher = "kubernetes.io/in-tree"
            va = VolumeAttachment(attacher=attacher, node_name=node,
                                  pv_name=pv_name, attached=True)
            va.metadata.name = attachment_name(pv_name, node)
            try:
                self.store.create("volumeattachments", va)
            except AlreadyExistsError:
                pass  # another pass won the race; anything else propagates
                # to process()'s retry path
        for (pv_name, node), va in actual.items():
            if (pv_name, node) not in desired:
                try:
                    self.store.delete("volumeattachments", va.metadata.name)
                except NotFoundError:
                    pass
