"""ReplicaSet controller.

reference: pkg/controller/replicaset/replica_set.go:677 syncReplicaSet —
level-triggered convergence of matching-pod count to spec.replicas, with
ownerReference adoption and surplus deletion (youngest first).
"""

from __future__ import annotations

from typing import List, Optional

from ..api import Pod
from ..api.workloads import ReplicaSet
from ..store import NotFoundError
from .base import Controller


def owner_ref(rs: ReplicaSet) -> dict:
    return {
        "kind": "ReplicaSet",
        "name": rs.metadata.name,
        "uid": rs.metadata.uid,
        "controller": True,
    }


def is_owned_by(pod: Pod, rs: ReplicaSet) -> bool:
    return any(
        ref.get("kind") == "ReplicaSet" and ref.get("uid") == rs.metadata.uid
        for ref in pod.metadata.owner_references
    )


class ReplicaSetController(Controller):
    watch_kinds = ("replicasets", "pods")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "replicasets":
            return obj.key
        # pod events resolve to their owning ReplicaSet (resolveControllerRef)
        for ref in obj.metadata.owner_references:
            if ref.get("kind") == "ReplicaSet":
                return f"{obj.metadata.namespace}/{ref['name']}"
        return None

    def sync(self, key: str) -> None:
        try:
            rs: ReplicaSet = self.store.get("replicasets", key)
        except NotFoundError:
            self._delete_orphans(key)
            return
        ns = rs.metadata.namespace
        pods, _ = self.store.list(
            "pods",
            lambda p: p.metadata.namespace == ns
            and is_owned_by(p, rs)
            and not p.is_terminal()
            and p.metadata.deletion_timestamp is None,
        )
        diff = rs.spec.replicas - len(pods)
        if diff > 0:
            self._create_pods(rs, diff)
        elif diff < 0:
            self._delete_pods(rs, pods, -diff)
        # status update (observedGeneration + replica counts)
        ready = sum(1 for p in pods if p.status.phase == "Running")

        def mutate(obj: ReplicaSet) -> ReplicaSet:
            obj.status.replicas = len(pods) + max(diff, 0)
            obj.status.ready_replicas = ready
            obj.status.observed_generation = obj.metadata.generation
            return obj

        try:
            self.store.guaranteed_update("replicasets", key, mutate)
        except NotFoundError:
            pass

    def _create_pods(self, rs: ReplicaSet, n: int) -> None:
        from ..store import AlreadyExistsError

        base = rs.metadata.name
        i = 0
        created = 0
        while created < n:
            name = f"{base}-{rs.metadata.uid[-5:]}-{i}"
            i += 1
            pod = rs.spec.template.make_pod(name, rs.metadata.namespace, owner_ref(rs))
            try:
                self.store.create("pods", pod)
                created += 1
            except AlreadyExistsError:
                continue  # name taken (e.g. terminal pod not yet GC'd): next index

    def _delete_pods(self, rs: ReplicaSet, pods: List[Pod], n: int) -> None:
        # delete unscheduled first, then youngest (getPodsToDelete ranking, simplified)
        ranked = sorted(pods, key=lambda p: (bool(p.spec.node_name), -p.metadata.creation_timestamp,
                                             -p.metadata.resource_version))
        for p in ranked[:n]:
            try:
                self.store.delete("pods", p.key)
            except NotFoundError:
                pass

    def _delete_orphans(self, key: str) -> None:
        """RS deleted: cascade-delete its pods (GC's ownerReference cleanup)."""
        ns, name = key.split("/", 1)
        pods, _ = self.store.list(
            "pods",
            lambda p: p.metadata.namespace == ns and any(
                r.get("kind") == "ReplicaSet" and r.get("name") == name
                for r in p.metadata.owner_references
            ),
        )
        for p in pods:
            try:
                self.store.delete("pods", p.key)
            except NotFoundError:
                pass
