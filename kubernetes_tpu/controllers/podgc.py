"""PodGC: reaps terminated, orphaned, and unscheduled-terminating pods.

reference: pkg/controller/podgc/gc_controller.go — three sweeps:
gcTerminated (terminated pods beyond --terminated-pod-gc-threshold, oldest
first), gcOrphaned (pods bound to nodes that no longer exist), and
gcUnscheduledTerminating (deleting pods that never got a node). Time-driven
like the reference's 20s resync.
"""

from __future__ import annotations

from ..store import NotFoundError
from .base import Controller

DEFAULT_TERMINATED_THRESHOLD = 12500


class PodGCController(Controller):
    watch_kinds = ("pods", "nodes")
    SWEEP_INTERVAL = 20.0

    def __init__(self, store, clock=None,
                 terminated_threshold: int = DEFAULT_TERMINATED_THRESHOLD):
        super().__init__(store, clock)
        self.terminated_threshold = terminated_threshold
        self._last_sweep = float("-inf")

    def key_of_object(self, kind, obj):
        # purely time-driven (the reference's 20s gcCheckPeriod): reacting to
        # every pod/node event would run a full-store sweep per phase write.
        # No keys -> base sync() is never invoked; sweep() is the only path.
        return None

    def reconcile_once(self) -> int:
        n = super().reconcile_once()
        if self.clock.now() - self._last_sweep >= self.SWEEP_INTERVAL:
            self._last_sweep = self.clock.now()
            n += self.sweep()
        return n

    def sweep(self) -> int:
        deleted = 0
        pods, _ = self.store.list("pods")
        node_names = {n.metadata.name
                      for n in self.store.list("nodes")[0]}

        # orphaned: bound to a node that is gone (gcOrphaned) — the kubelet
        # that would run them no longer exists, so nothing else reaps them
        for p in pods:
            if p.spec.node_name and p.spec.node_name not in node_names:
                deleted += self._delete(p)

        # unscheduled terminating: deletionTimestamp set, never placed
        for p in pods:
            if (p.metadata.deletion_timestamp is not None
                    and not p.spec.node_name):
                deleted += self._delete(p)

        # terminated beyond threshold, oldest first (gcTerminated)
        terminated = sorted(
            (p for p in pods if p.is_terminal()),
            key=lambda p: p.metadata.creation_timestamp)
        excess = len(terminated) - self.terminated_threshold
        for p in terminated[:max(excess, 0)]:
            deleted += self._delete(p)
        return deleted

    def _delete(self, pod) -> int:
        try:
            self.store.delete("pods", self.store.object_key(pod))
            return 1
        except NotFoundError:
            return 0
