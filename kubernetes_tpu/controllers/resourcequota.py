"""ResourceQuota controller: recompute per-namespace usage into quota status.

reference: pkg/controller/resourcequota/resource_quota_controller.go (usage
recalculation; the enforcement half lives in apiserver admission). Tracked
resources: requests.cpu/memory, cpu/memory aliases, pods count, and
count/<resource> object counts.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api.policy import ResourceQuota
from ..api.resources import quantity_milli_value, quantity_value
from ..store import NotFoundError
from .base import Controller


def pod_request_totals(pods) -> Dict[str, int]:
    """Sum of container requests over non-terminal pods; cpu in millis,
    memory in bytes (quota usage math)."""
    cpu_m = 0
    mem = 0
    for p in pods:
        if p.is_terminal():
            continue
        for c in list(p.spec.containers) + list(p.spec.init_containers):
            req = (c.resources or {}).get("requests") or {}
            cpu_m += quantity_milli_value(req.get("cpu", 0))
            mem += quantity_value(req.get("memory", 0))
    return {"cpu_milli": cpu_m, "memory": mem}


class ResourceQuotaController(Controller):
    watch_kinds = ("resourcequotas", "pods", "persistentvolumeclaims",
                   "services", "replicasets")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "resourcequotas":
            return obj.key
        ns = getattr(obj.metadata, "namespace", "")
        return f"{ns}/*" if ns else None

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        if name == "*":
            quotas, _ = self.store.list(
                "resourcequotas", lambda q: q.metadata.namespace == ns)
            for q in quotas:
                self._recalculate(q)
            return
        try:
            quota: ResourceQuota = self.store.get("resourcequotas", key)
        except NotFoundError:
            return
        self._recalculate(quota)

    def _recalculate(self, quota: ResourceQuota) -> None:
        ns = quota.metadata.namespace
        used: Dict[str, object] = {}
        pods, _ = self.store.list(
            "pods", lambda p: p.metadata.namespace == ns and not p.is_terminal())
        totals = pod_request_totals(pods)
        for key in quota.hard:
            if key in ("requests.cpu", "cpu"):
                used[key] = f"{totals['cpu_milli']}m"
            elif key in ("requests.memory", "memory"):
                used[key] = str(totals["memory"])
            elif key == "pods":
                used[key] = str(len(pods))
            elif key.startswith("count/"):
                resource = key.split("/", 1)[1]
                objs, _ = self.store.list(
                    resource, lambda o: getattr(o.metadata, "namespace", "") == ns)
                used[key] = str(len(objs))

        def mutate(obj: ResourceQuota) -> ResourceQuota:
            obj.used = used
            return obj

        try:
            self.store.guaranteed_update("resourcequotas", quota.key, mutate)
        except NotFoundError:
            pass
