"""Namespace lifecycle controller: terminating namespaces drain their contents.

reference: pkg/controller/namespace/deletion/namespaced_resources_deleter.go —
a namespace with a deletionTimestamp is swept: every namespaced object in it is
deleted; once empty, the namespace itself is removed (finalizer semantics
collapsed to the observable behavior).
"""

from __future__ import annotations

from typing import Optional

from ..store import NotFoundError
from .base import Controller


class NamespaceController(Controller):
    watch_kinds = ("namespaces",)

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        return obj.metadata.name

    def sync(self, name: str) -> None:
        try:
            ns = self.store.get("namespaces", name)
        except NotFoundError:
            return
        if ns.metadata.deletion_timestamp is None:
            return
        remaining = 0
        for kind in list(self.store.kinds()):
            if kind == "namespaces":
                continue
            objs, _ = self.store.list(
                kind, lambda o: getattr(o.metadata, "namespace", "") == name)
            for obj in objs:
                try:
                    self.store.delete(kind, self.store.object_key(obj))
                except NotFoundError:
                    pass
                else:
                    remaining += 1
        if remaining == 0:
            try:
                self.store.delete("namespaces", name)
            except NotFoundError:
                pass
        else:
            self._mark(name)  # requeue until empty

    def mark_terminating(self, name: str) -> None:
        """kubectl delete namespace equivalent: stamp deletionTimestamp."""
        def mutate(ns):
            if ns.metadata.deletion_timestamp is None:
                ns.metadata.deletion_timestamp = self.clock.now()
            return ns

        self.store.guaranteed_update("namespaces", name, mutate)
        self._mark(name)
