"""Disruption controller: maintains PodDisruptionBudget status.

reference: pkg/controller/disruption/disruption.go — trySync computes
currentHealthy / desiredHealthy over the pods the PDB selects and writes
status.disruptionsAllowed = max(0, currentHealthy - desiredHealthy); the
scheduler's preemption engine consumes disruptionsAllowed when counting PDB
violations (preemption.go filterPodsWithPDBViolation).

minAvailable and maxUnavailable accept absolute ints or "N%" strings
(disruption.go getExpectedPodCount; percentages resolve against the expected
count, here the matched-pod count since we don't track controller scale).
"""

from __future__ import annotations

from typing import Optional

from ..api.policy import PodDisruptionBudget
from ..store import NotFoundError
from .base import Controller


def _resolve(value, total: int) -> int:
    """IntOrString: ints pass through, 'N%' rounds up for minAvailable-style
    semantics (intstr.GetScaledValueFromIntOrPercent roundUp=true)."""
    if isinstance(value, str) and value.endswith("%"):
        pct = int(value[:-1] or 0)
        return -(-pct * total // 100)
    return int(value)


class DisruptionController(Controller):
    watch_kinds = ("poddisruptionbudgets", "pods")

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        if kind == "poddisruptionbudgets":
            return obj.key
        # any pod event re-evaluates the PDBs of its namespace (the reference
        # maps pod -> PDBs via label matching; one namespace sweep is our scale)
        return f"ns|{obj.metadata.namespace}"

    def sync(self, key: str) -> None:
        if key.startswith("ns|"):
            ns = key[3:]
            pdbs, _ = self.store.list(
                "poddisruptionbudgets", lambda b: b.metadata.namespace == ns)
            for b in pdbs:
                self._sync_pdb(b.key)
            return
        self._sync_pdb(key)

    def _sync_pdb(self, key: str) -> None:
        try:
            pdb: PodDisruptionBudget = self.store.get("poddisruptionbudgets", key)
        except NotFoundError:
            return
        sel = pdb.selector
        pods, _ = self.store.list(
            "pods", lambda p: p.metadata.namespace == pdb.metadata.namespace
            and p.metadata.deletion_timestamp is None
            and (sel.matches(p.metadata.labels) if sel is not None else False))
        # healthy = bound, non-terminal (the reference requires Ready condition;
        # the hollow runtime marks bound pods Running)
        healthy = sum(1 for p in pods if p.spec.node_name and not p.is_terminal())
        total = len(pods)
        if pdb.min_available is not None:
            desired = _resolve(pdb.min_available, total)
        elif pdb.max_unavailable is not None:
            desired = total - _resolve(pdb.max_unavailable, total)
        else:
            desired = total
        allowed = max(0, healthy - desired)

        def mutate(b: PodDisruptionBudget) -> PodDisruptionBudget:
            b.disruptions_allowed = allowed
            return b

        try:
            if pdb.disruptions_allowed != allowed:
                self.store.guaranteed_update("poddisruptionbudgets", key, mutate)
        except NotFoundError:
            pass
