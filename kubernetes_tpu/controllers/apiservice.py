"""APIService availability controller (kube-aggregator's
available_controller): probes each extension apiserver and flips the
Available condition the proxy gates on — an unreachable backend turns
requests into clean 503s instead of hanging proxies."""

from __future__ import annotations

import urllib.error
import urllib.request
from typing import Optional

from ..store import NotFoundError
from .base import Controller


class APIServiceAvailabilityController(Controller):
    watch_kinds = ("apiservices",)
    _RESYNC_EVERY = 100  # reconcile rounds between full re-probes (~5s idle)

    def key_of_object(self, kind: str, obj) -> Optional[str]:
        return obj.metadata.name

    def reconcile_once(self) -> int:
        n = super().reconcile_once()
        self._tick = getattr(self, "_tick", 0) + 1
        if self._tick >= self._RESYNC_EVERY:
            self._tick = 0
            svcs, _ = self.store.list("apiservices")
            for s in svcs:
                self._mark(s.metadata.name)
            n += self.process()
        return n

    def _probe(self, url: str) -> Optional[str]:
        """None = healthy; else the failure message."""
        try:
            with urllib.request.urlopen(url.rstrip("/") + "/healthz",
                                        timeout=3) as resp:
                if 200 <= resp.status < 300:
                    return None
                return f"healthz returned {resp.status}"
        except urllib.error.HTTPError as e:
            # a 404 healthz on a live server still proves reachability
            return None if e.code == 404 else f"healthz returned {e.code}"
        except (urllib.error.URLError, OSError) as e:
            return f"unreachable: {e}"

    def sync(self, key: str) -> None:
        try:
            svc = self.store.get("apiservices", key)
        except NotFoundError:
            return
        if svc.local:
            want, msg = True, "Local"
        else:
            failure = self._probe(svc.service_url)
            want, msg = failure is None, failure or ""
        if svc.available == want and svc.available_message == msg:
            return

        def flip(s):
            s.available = want
            s.available_message = msg
            return s

        self.store.guaranteed_update("apiservices", key, flip)
