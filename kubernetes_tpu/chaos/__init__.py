"""Chaos / failure-domain tooling: the deterministic fault-injection harness
(faultinject.py) behind the chaos tests and the ChaosChurn bench rung."""

from .faultinject import (FaultInjected, FaultKill, FaultPlan, Injector,
                          arm, disarm, enabled)

__all__ = ["FaultInjected", "FaultKill", "FaultPlan", "Injector", "arm",
           "disarm", "enabled"]
