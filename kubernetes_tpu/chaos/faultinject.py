"""Deterministic fault-injection harness for the failure-domain tests.

Kubernetes' defining property is self-healing: every component assumes its
peers fail mid-decision and reconciles from the store (PAPER.md "watch,
reconcile, write status"). Nothing in tree could *prove* that until now —
this module is the registry of named injection sites the chaos tests and the
`ChaosChurn_20k` bench rung drive, with programmable per-site plans:

  fail-next-N     the next N fires at the site raise FaultInjected
  fail-rate       each fire raises with probability `rate` (seeded RNG, so a
                  chaos run is exactly reproducible)
  delay           each fire sleeps `delay_s` before proceeding
  kill            ONE fire raises FaultKill — a BaseException, so it escapes
                  `except Exception` supervisors by design (a hard thread
                  death, not a handled fault)

Hot-path contract: every instrumented site guards with the single falsy
module-level check

    if faultinject.ACTIVE is not None:
        faultinject.ACTIVE.fire("site.name")

so a disabled injector costs one module-attribute load per *batch/chunk/
event* (never per pod) and nothing else — schedlint HP001 stays clean and
the NorthStar rung pays <1% (asserted by tests/test_bench_quick.py via the
measured `disabled_check_ns`).

Two firing forms, split by lock discipline (schedlint LK002):

  fire(site, key=None)        may raise FaultInjected/FaultKill or SLEEP
                              (delay plans) — only legal at sites that hold
                              no store/scheduler lock (store.bind_many entry,
                              solver.solve, bind.worker).
  should_drop(site, key=None) returns True when the fire should be dropped;
                              NEVER blocks — the only form legal under a lock
                              (watch.deliver runs inside the store's emit
                              path, kubelet.heartbeat inside agent loops).

Sites (the registry below documents where each is wired):

  store.bind_many    APIStore.bind_many entry — transient store failure
  solver.solve       BatchScheduler._solve_device — solver crash mid-batch
  watch.deliver      Watch._deliver/_deliver_coalesced — dropped delivery
  bind.worker        BatchScheduler._bind_cycle — worker fault / hard kill
  kubelet.heartbeat  HollowKubelet.heartbeat — missed lease renewal
  native.commit      bind_many/delete_pods native commit boundary (ISSUE 11)

Arming: programmatic `arm([FaultPlan(...), ...])` (tests/bench), or the
FAULT_INJECT env var at import time, e.g.

  FAULT_INJECT="solver.solve=fail:count=3;store.bind_many=rate:rate=0.1,seed=7"

`key` scopes a fire to one object (a node name, a pod key); plans with a
`match` only act on fires whose key contains that substring — how a chaos
test kills ONE kubelet's heartbeat while its siblings keep renewing.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

from ..obs import tracebuf as _tracebuf

# The injection-site registry: site name -> where it is wired. Unknown sites
# in a plan are a hard arm() error — a typo'd site would otherwise silently
# inject nothing and the chaos test would pass vacuously.
SITES: Dict[str, str] = {
    "store.bind_many": "store/store.py APIStore.bind_many entry (no lock held)",
    "solver.solve": "scheduler/batch.py BatchScheduler._solve_device",
    "watch.deliver": "store/store.py Watch._deliver* (drop-only: store lock)",
    "bind.worker": "scheduler/batch.py BatchScheduler._bind_cycle",
    "kubelet.heartbeat": "agent/hollow.py HollowKubelet.heartbeat (drop-only)",
    # the native commit boundary (ISSUE 11): fires only when the C-API
    # commit engine is taking the write — for bind_many in the gap between
    # the validate/clone phase and the commit phase (clones made, nothing
    # committed, no lock held), for delete_pods before the critical section.
    # A mid-chunk native failure therefore leaves the store untouched and
    # must be fully absorbed by the caller's retry/requeue machinery
    # (supervised bind worker), conserving every pod — ChaosChurn_20k's
    # native leg proves it.
    "native.commit": "store/store.py bind_many/delete_pods native phase gap "
                     "(no lock held)",
    # the partitioned dispatch layer (ISSUE 12): fires once per pipeline
    # drive cycle in PartitionedScheduler._drive_pipeline (no lock held;
    # key = "partition-<i>", so `match=` scopes a plan to one partition).
    # fail/rate plans are absorbed dispatch hiccups (the cycle retries and
    # the coordinator counts them); a kill plan is that partition's HARD
    # death — the coordinator's absorb path remaps the shard and resyncs
    # the survivors (ChaosChurn_20k's partition-kill leg proves pod
    # conservation across it).
    "partition.dispatch": "scheduler/partition.py "
                          "PartitionedScheduler._drive_pipeline (no lock)",
    # the background rebalancer (ISSUE 17): fires in
    # scheduler/rebalance.py Rebalancer.cycle at cycle start
    # (key="cycle"), at every migration-wave boundary (key="wave-<i>"),
    # and MID-WAVE between replacement create_many and victim delete_pods
    # (key="midwave") — the conservation-critical gap: an injected fault
    # there rolls the wave's replacements back, a kill plan leaves a
    # transient duplicate but never a lost or double-bound pod
    # (tests/test_rebalance.py chaos case). No lock held at any fire.
    "rebalance.cycle": "scheduler/rebalance.py Rebalancer.cycle / wave "
                       "boundaries + midwave gap (no lock held)",
    # the multi-process scheduler (ISSUE 19): fires in the OWNER process,
    # once per worker per round before that worker's round is dispatched
    # (scheduler/mpsched.py MPScheduler._dispatch_round; no lock held;
    # key = "worker-<i>", so `match=` scopes a plan to one worker slot).
    # fail/rate plans skip that worker's round (its pods stay pending and
    # re-offer next round — counted in dispatch_faults); a kill plan
    # SIGKILLs the real worker PROCESS — the supervisor detects the death,
    # remaps the slot to survivors, respawns, and reconciles via
    # resync_from_store (ChaosChurn_20k's mp_worker_kill leg proves pod
    # conservation across it).
    "process.worker": "scheduler/mpsched.py MPScheduler._dispatch_round "
                      "(owner side, no lock held; kill = SIGKILL the "
                      "worker process)",
}

# sites that fire under a lock (or inside a loop that must not stall): only
# should_drop() consults them, so delay plans there are an arm()-time error
DROP_ONLY_SITES = frozenset({"watch.deliver", "kubelet.heartbeat"})

MODES = ("fail", "rate", "delay", "kill")


class FaultInjected(RuntimeError):
    """An injected (handled) fault: the site's failure-domain machinery is
    expected to catch, retry, or requeue."""

    def __init__(self, site: str, message: str = ""):
        super().__init__(message or f"injected fault at {site}")
        self.site = site


class FaultKill(BaseException):
    """An injected HARD death (bind.worker kill plans): deliberately a
    BaseException so supervisor `except Exception` blocks do not absorb it —
    the thread dies and the liveness check must recover."""

    def __init__(self, site: str):
        super().__init__(f"injected kill at {site}")
        self.site = site


@dataclass
class FaultPlan:
    """One site's programmed behavior. Counting starts after `after` fires
    (a mid-run kill is `FaultPlan("bind.worker", "kill", after=2)`); `count`
    bounds fail/delay plans (None = unbounded); `match` scopes to fires
    whose key contains the substring."""

    site: str
    mode: str  # fail | rate | delay | kill
    count: Optional[int] = 1
    rate: float = 0.0
    seed: int = 0
    delay_s: float = 0.0
    after: int = 0
    match: Optional[str] = None
    message: str = ""
    # runtime state (owned by the Injector, under its lock)
    _fired: int = field(default=0, repr=False)
    _injected: int = field(default=0, repr=False)
    _rng: Optional[random.Random] = field(default=None, repr=False)

    def validate(self) -> None:
        if self.site not in SITES:
            raise ValueError(
                f"unknown injection site {self.site!r}; known: "
                f"{sorted(SITES)}")
        if self.mode not in MODES:
            raise ValueError(f"unknown fault mode {self.mode!r}; "
                             f"known: {MODES}")
        if self.mode == "delay" and self.site in DROP_ONLY_SITES:
            raise ValueError(
                f"site {self.site} fires under a lock (should_drop form): "
                "delay plans are forbidden there — schedlint LK002")
        if self.mode == "kill" and self.site in DROP_ONLY_SITES:
            raise ValueError(
                f"site {self.site} is drop-only; kill plans need a raising "
                "site (bind.worker)")

    def _decide(self, key: Optional[str]) -> Optional[str]:
        """Returns the action ('fail'/'delay'/'kill') for this fire, or None.
        Caller holds the injector lock."""
        if self.match is not None and (key is None or self.match not in key):
            return None
        self._fired += 1
        if self._fired <= self.after:
            return None
        if self.mode == "rate":
            if self._rng is None:
                self._rng = random.Random(self.seed)
            if self._rng.random() < self.rate:
                self._injected += 1
                return "fail"
            return None
        past_after = self._fired - self.after
        if self.count is not None and self._injected >= self.count:
            return None
        if self.mode == "kill" and past_after >= 1:
            self._injected += 1
            return "kill"
        if self.mode in ("fail", "delay"):
            self._injected += 1
            return self.mode
        return None


class Injector:
    """The armed plan set. Thread-safe: fires arrive from the scheduling
    thread, the bind worker, kubelet loops, and the store's emit path."""

    def __init__(self, plans: Iterable[FaultPlan]):
        self._lock = threading.Lock()
        self._plans: Dict[str, List[FaultPlan]] = {}
        for p in plans:
            p.validate()
            self._plans.setdefault(p.site, []).append(p)

    def fire(self, site: str, key: Optional[str] = None) -> None:
        """The raising/sleeping form — ONLY for sites that hold no lock.
        Raises FaultInjected (handled-fault contract) or FaultKill (hard
        death), or sleeps for a delay plan, or returns untouched."""
        delay = 0.0
        action = None
        plan = None
        with self._lock:
            for p in self._plans.get(site, ()):
                act = p._decide(key)
                if act is not None:
                    action, plan = act, p
                    if act == "delay":
                        delay = p.delay_s
                    break
        # trace timeline (ISSUE 18): an INJECTED action lands as an instant
        # on the chaos track (per fire decision, outside the injector lock)
        if action is not None and _tracebuf.ACTIVE is not None:
            _tracebuf.ACTIVE.instant(
                "chaos", "fault:%s" % site, cat="chaos",
                args={"action": action, "key": key or ""})
        if action == "delay" and delay > 0:
            time.sleep(delay)  # outside the injector lock
        elif action == "kill":
            raise FaultKill(site)
        elif action == "fail":
            raise FaultInjected(site, plan.message)

    def should_drop(self, site: str, key: Optional[str] = None) -> bool:
        """The non-blocking form for lock-held sites: True when the armed
        plan says this fire is dropped. Never raises, never sleeps."""
        hit = False
        with self._lock:
            for p in self._plans.get(site, ()):
                if p._decide(key) in ("fail", "kill"):
                    hit = True
                    break
        if hit and _tracebuf.ACTIVE is not None:
            # outside the injector lock; the trace ring is a leaf lock so
            # lock-held caller sites stay LK002-clean
            _tracebuf.ACTIVE.instant(
                "chaos", "fault:%s" % site, cat="chaos",
                args={"action": "drop", "key": key or ""})
        return hit

    def stats(self) -> Dict[str, Dict[str, int]]:
        """{site: {fired, injected}} — what the chaos rung reports."""
        out: Dict[str, Dict[str, int]] = {}
        with self._lock:
            for site, plans in self._plans.items():
                out[site] = {
                    "fired": sum(p._fired for p in plans),
                    "injected": sum(p._injected for p in plans),
                }
        return out


# THE hot-path flag: None when disabled. Every instrumented site guards with
# `if faultinject.ACTIVE is not None:` — one attribute load, no call.
ACTIVE: Optional[Injector] = None


def arm(plans: Iterable[FaultPlan]) -> Injector:
    """Install an injector (replacing any armed one) and return it."""
    global ACTIVE
    ACTIVE = Injector(plans)
    return ACTIVE


def disarm() -> None:
    global ACTIVE
    ACTIVE = None


def enabled() -> bool:
    return ACTIVE is not None


def disabled_check_cost_ns(n: int = 50_000, passes: int = 5) -> float:
    """Measured per-check cost of the disabled-injector guard (the exact
    expression hot paths use), in nanoseconds — the number the bench rung
    publishes so the <1% NorthStar overhead budget is asserted from a
    measurement instead of differencing two noisy runs. Best-of-`passes`:
    the minimum filters harness co-scheduling spikes on a contended rig."""
    best = float("inf")
    hits = 0
    for _ in range(passes):
        t0 = time.perf_counter()
        for _ in range(n):
            if ACTIVE is not None:  # the hot-path guard, verbatim
                hits += 1
        best = min(best, time.perf_counter() - t0)
    assert hits == 0 or ACTIVE is not None
    return best / n * 1e9


def parse_env(spec: str) -> List[FaultPlan]:
    """FAULT_INJECT grammar: `site=mode[:k=v[,k=v...]];site2=...`.
    Example: solver.solve=fail:count=3;store.bind_many=rate:rate=0.1,seed=7
    """
    plans: List[FaultPlan] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, rest = part.partition("=")
        mode, _, argstr = rest.partition(":")
        kwargs: Dict[str, object] = {}
        for kv in argstr.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            if k in ("count", "seed", "after"):
                kwargs[k] = int(v)
            elif k in ("rate", "delay_s"):
                kwargs[k] = float(v)
            elif k in ("match", "message"):
                kwargs[k] = v
            else:
                raise ValueError(f"unknown FAULT_INJECT arg {k!r} in {part!r}")
        if "count" not in kwargs and mode.strip() in ("fail", "kill"):
            kwargs["count"] = 1
        plan = FaultPlan(site=site.strip(), mode=mode.strip(), **kwargs)
        plan.validate()
        plans.append(plan)
    return plans


_env_spec = os.environ.get("FAULT_INJECT", "")
if _env_spec:
    ACTIVE = Injector(parse_env(_env_spec))
