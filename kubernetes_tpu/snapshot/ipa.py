"""InterPodAffinity -> dense tensors for the device solver.

The quadratic hard part of the north star (SURVEY.md §7 hard part 1): the
reference builds three topologyPair->count maps per pod
(interpodaffinity/filtering.go:44-110) and a weighted pair map for scoring
(scoring.go). The TPU reframing replaces the maps with per-node count tensors
segment-summed over topology domains:

  selector-class counts  selcls_count[SC, N] — "pods matching predicate sc on
      node n" — serve the incoming pod's own terms (affinity / anti-affinity /
      preferred). Shared with PodTopologySpread.
  holder-group counts    grp_count[G, N] — "pods ON node n that themselves
      carry term-group g" — serve the symmetric rules: existing pods' required
      anti-affinity (filtering.go satisfyExistingPodsAntiAffinity) and
      existing pods' preferred/hard terms in scoring (scoring.go
      processExistingPod).

Both tensors are dynamic in the scan solver: committing a pod of class c adds
class_matches_selcls[c] and class_holds_grp[c] at the chosen node, which is
exactly the serial semantics where each bind feeds the next pod's PreFilter.

Term groups are keyed by (kind, topologyKey, namespace-semantics, effective
selector[, weight]); any (term, source-pod) pair in a group matches the same
set of target pods, so one representative per group decides per-class matching.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Mapping, Sequence, Tuple

import numpy as np

from ..api import Pod
from ..scheduler.plugins.helpers import (
    effective_selector,
    term_matches_pod,
    term_namespaces_match,
)

# holder-group kinds
_KIND_REQ_ANTI = "rn"  # required anti-affinity (filter rule 1)
_KIND_PREF_AFF = "pa"  # preferred affinity (+w, symmetric score)
_KIND_PREF_ANTI = "pn"  # preferred anti-affinity (-w, symmetric score)
_KIND_REQ_AFF = "ra"  # required affinity (+hardPodAffinityWeight, score)


def _term_ns_canon(term, source_ns: str) -> tuple:
    """Canonical namespace-semantics key: two (term, source) pairs with equal
    keys match the same target namespaces (helpers.term_namespaces_match)."""
    default_ns = source_ns if (not term.namespaces
                               and term.namespace_selector is None) else ""
    return (tuple(sorted(term.namespaces)), repr(term.namespace_selector), default_ns)


def _term_matcher(term, source_pod, ns_labels) -> Callable[[Pod], bool]:
    """Pod predicate for an affinity term (AffinityTerm.Matches, types.go).
    Unlike PTS counting, terminating pods are NOT excluded — the reference
    counts every pod in NodeInfo.Pods (filtering.go:processExistingPod)."""
    eff = effective_selector(term, source_pod)
    src_ns = source_pod.metadata.namespace

    def match(p: Pod) -> bool:
        if eff is None:
            return False
        if not term_namespaces_match(term, src_ns, p.metadata.namespace, ns_labels):
            return False
        return eff.matches(p.metadata.labels)

    return match


@dataclass
class IPATensors:
    """Batch-scoped InterPodAffinity tensors (numpy; ops/ uploads).

    All term tables are PER-CLASS padded rows (-1 = inactive slot): the scan
    solver gathers one class row per pod step, so per-step device cost scales
    with the max term count of a single class, not the batch-wide total —
    the difference between O(C·N) and O(terms·N) per pod at bench scale.
    """

    # incoming pod's terms per class; *_sel indexes the shared selector-class
    # count tensor, *_key the topo_id rows; -1 pads
    ra_key: np.ndarray  # [C, RAm] — required affinity
    ra_sel: np.ndarray
    rn_key: np.ndarray  # [C, RNm] — required anti-affinity
    rn_sel: np.ndarray
    pp_key: np.ndarray  # [C, PPm] — preferred terms
    pp_sel: np.ndarray
    pp_weight: np.ndarray  # [C, PPm] signed; 0 on pads

    # holder groups
    grp_key: np.ndarray  # [G] int32 — topo_id row per group
    grp_count: np.ndarray  # [G, N] int32 — existing holders per node
    class_holds_grp: np.ndarray  # [C, G] int32 — terms of class c in group g

    # filter rule 1: required-anti groups matching each class (-1 pads)
    ea_grp: np.ndarray  # [C, Em] int32 (index into G)

    # symmetric score: groups whose terms match each class + signed weight
    sym_grp: np.ndarray  # [C, Sm] int32 (-1 pads)
    sym_weight: np.ndarray  # [C, Sm] int32 (0 on pads)

    class_self_ok: np.ndarray  # [C] bool — pod matches all own required terms
    class_has_ra: np.ndarray  # [C] bool
    # constraint-compilation metadata for the propose-and-repair solver
    # (models/repair.py): a class whose OWN required anti-affinity term
    # matches its own rep pod can place at most ONE member per topology
    # domain — the propose step caps it at one per node (the host-port cap
    # mechanism) and the repair loop resolves coarser-domain collisions
    class_rn_self: np.ndarray = None  # [C] bool

    @property
    def has_any(self) -> bool:
        return bool((self.ra_key >= 0).any() or (self.rn_key >= 0).any()
                    or (self.pp_key >= 0).any() or (self.ea_grp >= 0).any()
                    or (self.sym_grp >= 0).any())


def compile_ipa(
    rep_pods: Sequence[Pod],
    snapshot,
    topo_row: Callable[[str], int],
    selcls_row: Callable[[tuple, Callable[[Pod], bool]], int],
    ns_labels: Mapping[str, Mapping[str, str]],
    hard_pod_affinity_weight: int,
    node_name_to_idx: Dict[str, int],
    n_nodes: int,
) -> IPATensors:
    """Build the IPA tensors for one batch.

    topo_row registers a topology key on the cluster tensors and returns its
    row; selcls_row registers a (key, matcher) selector-class and returns its
    row in the shared count tensor.
    """
    c = len(rep_pods)

    # ---- incoming-term rows, grouped per class -----------------------------
    ra_rows: List[List[Tuple[int, int]]] = [[] for _ in range(c)]
    rn_rows: List[List[Tuple[int, int]]] = [[] for _ in range(c)]
    pp_rows: List[List[Tuple[int, int, int]]] = [[] for _ in range(c)]
    class_self_ok = np.zeros(c, dtype=bool)
    class_has_ra = np.zeros(c, dtype=bool)
    class_rn_self = np.zeros(c, dtype=bool)

    def _sel_row_for(term, source_pod) -> int:
        eff = effective_selector(term, source_pod)
        key = ("ipa", term.topology_key, _term_ns_canon(term, source_pod.metadata.namespace),
               repr(eff))
        return selcls_row(key, _term_matcher(term, source_pod, ns_labels))

    for ci, pod in enumerate(rep_pods):
        aff = pod.spec.affinity
        if aff is None:
            continue
        required = tuple(aff.pod_affinity_required)
        if required:
            class_has_ra[ci] = True
            class_self_ok[ci] = all(
                term_matches_pod(t, pod, pod, ns_labels) for t in required)
        for term in required:
            ra_rows[ci].append((topo_row(term.topology_key), _sel_row_for(term, pod)))
        for term in aff.pod_anti_affinity_required:
            rn_rows[ci].append((topo_row(term.topology_key), _sel_row_for(term, pod)))
            if term_matches_pod(term, pod, pod, ns_labels):
                class_rn_self[ci] = True
        for wt in aff.pod_affinity_preferred:
            pp_rows[ci].append((topo_row(wt.term.topology_key),
                                _sel_row_for(wt.term, pod), wt.weight))
        for wt in aff.pod_anti_affinity_preferred:
            pp_rows[ci].append((topo_row(wt.term.topology_key),
                                _sel_row_for(wt.term, pod), -wt.weight))

    # ---- holder groups -----------------------------------------------------
    # group key -> (index, representative (term, source_pod))
    grp_idx: Dict[tuple, int] = {}
    grp_reps: List[Tuple[object, Pod]] = []
    grp_kinds: List[str] = []
    grp_weights: List[int] = []
    grp_topo: List[int] = []
    count_rows: List[Dict[int, int]] = []  # node idx -> count, per group

    def group_row(kind: str, term, source_pod: Pod, weight: int) -> int:
        eff = effective_selector(term, source_pod)
        key = (kind, term.topology_key,
               _term_ns_canon(term, source_pod.metadata.namespace), repr(eff), weight)
        gi = grp_idx.get(key)
        if gi is None:
            gi = len(grp_reps)
            grp_idx[key] = gi
            grp_reps.append((term, source_pod))
            grp_kinds.append(kind)
            grp_weights.append(weight)
            grp_topo.append(topo_row(term.topology_key))
            count_rows.append({})
        return gi

    def pod_groups(pod_info_or_pod, get) -> List[int]:
        """Group rows for one pod's own terms (existing holder or batch class)."""
        out = []
        req_aff, req_anti, pref_aff, pref_anti = get(pod_info_or_pod)
        src = pod_info_or_pod if isinstance(pod_info_or_pod, Pod) else pod_info_or_pod.pod
        for t in req_anti:
            out.append(group_row(_KIND_REQ_ANTI, t, src, 0))
        for wt in pref_aff:
            out.append(group_row(_KIND_PREF_AFF, wt.term, src, wt.weight))
        for wt in pref_anti:
            out.append(group_row(_KIND_PREF_ANTI, wt.term, src, -wt.weight))
        if hard_pod_affinity_weight > 0:
            for t in req_aff:
                out.append(group_row(_KIND_REQ_AFF, t, src, hard_pod_affinity_weight))
        return out

    def _pi_terms(pi):
        return (pi.required_affinity_terms, pi.required_anti_affinity_terms,
                pi.preferred_affinity_terms, pi.preferred_anti_affinity_terms)

    def _pod_terms(p: Pod):
        aff = p.spec.affinity
        if aff is None:
            return ((), (), (), ())
        return (tuple(aff.pod_affinity_required), tuple(aff.pod_anti_affinity_required),
                tuple(aff.pod_affinity_preferred), tuple(aff.pod_anti_affinity_preferred))

    # existing pods with any affinity term seed the counts
    for ni in snapshot.node_info_list:
        nidx = node_name_to_idx[ni.node.metadata.name]
        for pi in ni.pods_with_affinity:
            for gi in pod_groups(pi, _pi_terms):
                count_rows[gi][nidx] = count_rows[gi].get(nidx, 0) + 1

    # batch classes register their groups (zero-seeded) for in-batch dynamics
    class_grp_rows: List[List[int]] = []
    for pod in rep_pods:
        class_grp_rows.append(pod_groups(pod, _pod_terms))

    g = len(grp_reps)
    grp_count = np.zeros((g, n_nodes), dtype=np.int32)
    for gi, row in enumerate(count_rows):
        for nidx, cnt in row.items():
            grp_count[gi, nidx] = cnt
    class_holds_grp = np.zeros((c, max(g, 1)), dtype=np.int32)
    for ci, rows in enumerate(class_grp_rows):
        for gi in rows:
            class_holds_grp[ci, gi] += 1

    # ---- per-class matching against group representatives ------------------
    # a group is relevant to class c only if its representative term matches
    # the class's rep pod; per-class index lists keep the device tables tight
    ea_lists: List[List[int]] = [[] for _ in range(c)]
    sym_lists: List[List[Tuple[int, int]]] = [[] for _ in range(c)]
    for gi in range(g):
        term, src = grp_reps[gi]
        for ci, pod in enumerate(rep_pods):
            if term_matches_pod(term, src, pod, ns_labels):
                if grp_kinds[gi] == _KIND_REQ_ANTI:
                    ea_lists[ci].append(gi)
                else:
                    sym_lists[ci].append((gi, grp_weights[gi]))

    def pad2(rows_per_class, width):
        """[[tuple...]] -> `width` arrays [C, m], -1/0-padded."""
        m = max((len(r) for r in rows_per_class), default=0)
        m = max(m, 1)
        out = [np.full((c, m), -1 if i < max(width - 1, 1) else 0, dtype=np.int32)
               for i in range(width)]
        # weights (last column of width-3 tables) pad with 0; keys/sels with -1
        for ci, rows in enumerate(rows_per_class):
            for j, row in enumerate(rows):
                vals = row if isinstance(row, tuple) else (row,)
                for i, v in enumerate(vals):
                    out[i][ci, j] = v
        return out

    ra_key_c, ra_sel_c = pad2(ra_rows, 2)
    rn_key_c, rn_sel_c = pad2(rn_rows, 2)
    pp_key_c, pp_sel_c, pp_w_c = pad2(pp_rows, 3)
    (ea_grp_c,) = pad2(ea_lists, 1)
    sym_grp_c, sym_w_c = pad2(sym_lists, 2)

    return IPATensors(
        ra_key=ra_key_c, ra_sel=ra_sel_c,
        rn_key=rn_key_c, rn_sel=rn_sel_c,
        pp_key=pp_key_c, pp_sel=pp_sel_c, pp_weight=pp_w_c,
        grp_key=np.array(grp_topo, dtype=np.int32),
        grp_count=grp_count,
        class_holds_grp=class_holds_grp,
        ea_grp=ea_grp_c,
        sym_grp=sym_grp_c, sym_weight=sym_w_c,
        class_self_ok=class_self_ok,
        class_has_ra=class_has_ra,
        class_rn_self=class_rn_self,
    )
