"""Compile pod equivalence classes x nodes into dense numpy predicate/score tables.

The TPU reframing of the per-pod plugin loop (SURVEY.md §7 step 4): pods sharing
scheduling-relevant spec (selectors, affinity, tolerations, ports, images,
namespace) form an *equivalence class*; every class x node predicate that does
not depend on batch dynamics is evaluated once, vectorized over the node axis
with dictionary-encoded label columns. The per-pod x node device kernel then
just gathers class rows.

Static per class x node (this module, host numpy):
  - filter_ok: NodeName + NodeUnschedulable + NodeAffinity/selector +
    TaintToleration + NodePorts (reference filter semantics of
    nodename/node_name.go, nodeunschedulable, nodeaffinity, tainttoleration,
    nodeports — see scheduler/plugins for the per-formula citations)
  - node-affinity preferred raw weights (nodeaffinity Score)
  - intolerable PreferNoSchedule taint counts (tainttoleration Score)
  - ImageLocality final score (static: image states don't change intra-batch)

Dynamic (device, ops/): resource fit, least-allocated/balanced scores,
topology-spread counts, inter-pod affinity counts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import Pod
from ..api.labels import DOES_NOT_EXIST, EXISTS, GT, IN, LT, NOT_IN
from ..api.podgroup import POD_GROUP_RANK_LABEL
from ..api.types import TAINT_NO_EXECUTE, TAINT_NO_SCHEDULE, TAINT_PREFER_NO_SCHEDULE
from ..scheduler.framework import MAX_NODE_SCORE, NodeInfo


class NodeColumns:
    """Columnar, dictionary-encoded node attributes (the L0->tensor bridge)."""

    def __init__(self, node_infos: Sequence[NodeInfo]):
        self.node_infos = list(node_infos)
        self.names = [ni.node.metadata.name for ni in self.node_infos]
        self.n = len(self.names)
        self.name_to_idx = {nm: i for i, nm in enumerate(self.names)}
        # per-label-key value ids: key -> (value_vocab dict, int32[N] ids, -1 absent)
        self._val_ids: Dict[str, Tuple[Dict[str, int], np.ndarray]] = {}
        self._numeric: Dict[str, np.ndarray] = {}
        self.unschedulable = np.array(
            [ni.node.spec.unschedulable for ni in self.node_infos], dtype=bool
        )
        # taint vocab: (key, value, effect) -> id
        self.taint_vocab: Dict[Tuple[str, str, str], int] = {}
        taints_per_node = []
        for ni in self.node_infos:
            ids = []
            for t in ni.node.spec.taints:
                k = (t.key, t.value, t.effect)
                if k not in self.taint_vocab:
                    self.taint_vocab[k] = len(self.taint_vocab)
                ids.append(self.taint_vocab[k])
            taints_per_node.append(ids)
        self.taint_matrix = np.zeros((self.n, max(len(self.taint_vocab), 1)), dtype=bool)
        for i, ids in enumerate(taints_per_node):
            for t in ids:
                self.taint_matrix[i, t] = True
        # port vocab: (proto, port) -> id (hostIP-specific matching is collapsed:
        # any same proto+port conflicts — conservative vs nodeports' hostIP rule)
        self.port_vocab: Dict[Tuple[str, int], int] = {}
        port_rows = []
        for ni in self.node_infos:
            row = set()
            for (ip, proto, port) in ni.used_ports:
                k = (proto, port)
                if k not in self.port_vocab:
                    self.port_vocab[k] = len(self.port_vocab)
                row.add(self.port_vocab[k])
            port_rows.append(row)
        self.port_matrix = np.zeros((self.n, max(len(self.port_vocab), 1)), dtype=bool)
        for i, row in enumerate(port_rows):
            for p in row:
                self.port_matrix[i, p] = True
        # image vocab
        self.image_vocab: Dict[str, int] = {}
        entries = {}
        for ni in self.node_infos:
            for nm, st in ni.image_states.items():
                if nm not in self.image_vocab:
                    self.image_vocab[nm] = len(self.image_vocab)
                entries[nm] = st
        ni_count = max(len(self.image_vocab), 1)
        self.image_matrix = np.zeros((self.n, ni_count), dtype=bool)
        self.image_value = np.zeros(ni_count, dtype=np.int64)
        for nm, idx in self.image_vocab.items():
            st = entries[nm]
            # scaledImageScore: int64(size * numNodes/totalNodes) (image_locality.go:111)
            self.image_value[idx] = int(st.size * st.num_nodes / self.n) if self.n else 0
        for i, ni in enumerate(self.node_infos):
            for nm in ni.image_states:
                self.image_matrix[i, self.image_vocab[nm]] = True

    def val_ids(self, key: str) -> Tuple[Dict[str, int], np.ndarray]:
        got = self._val_ids.get(key)
        if got is None:
            vocab: Dict[str, int] = {}
            ids = np.full(self.n, -1, dtype=np.int32)
            for i, ni in enumerate(self.node_infos):
                v = ni.node.metadata.labels.get(key)
                if v is not None:
                    if v not in vocab:
                        vocab[v] = len(vocab)
                    ids[i] = vocab[v]
            got = (vocab, ids)
            self._val_ids[key] = got
        return got

    def numeric(self, key: str) -> np.ndarray:
        got = self._numeric.get(key)
        if got is None:
            vals = np.full(self.n, np.nan)
            for i, ni in enumerate(self.node_infos):
                v = ni.node.metadata.labels.get(key)
                if v is not None:
                    try:
                        vals[i] = int(v)
                    except ValueError:
                        pass
            got = vals
            self._numeric[key] = got
        return got

    # -- requirement/selector vectorization ------------------------------------

    def match_requirement(self, req) -> np.ndarray:
        """Vectorized Requirement.matches over all nodes' labels."""
        if req.op in (IN, NOT_IN):
            vocab, ids = self.val_ids(req.key)
            wanted = np.array([vocab[v] for v in req.values if v in vocab], dtype=np.int32)
            hit = np.isin(ids, wanted) if wanted.size else np.zeros(self.n, dtype=bool)
            return hit if req.op == IN else ~hit  # NotIn matches absent keys too
        if req.op == EXISTS:
            _, ids = self.val_ids(req.key)
            return ids != -1
        if req.op == DOES_NOT_EXIST:
            _, ids = self.val_ids(req.key)
            return ids == -1
        if req.op in (GT, LT):
            if len(req.values) != 1:
                return np.zeros(self.n, dtype=bool)
            try:
                rhs = int(req.values[0])
            except ValueError:
                return np.zeros(self.n, dtype=bool)
            vals = self.numeric(req.key)
            with np.errstate(invalid="ignore"):
                return (vals > rhs) if req.op == GT else (vals < rhs)
        raise ValueError(f"unknown op {req.op}")

    def match_field_requirement(self, req) -> np.ndarray:
        if req.key != "metadata.name":
            return np.zeros(self.n, dtype=bool)
        hit = np.isin(np.array(self.names), np.array(list(req.values) or [""]))
        return hit if req.op == IN else ~hit if req.op == NOT_IN else np.zeros(self.n, dtype=bool)

    def match_node_selector_term(self, term) -> np.ndarray:
        if not term.match_expressions and not term.match_fields:
            return np.zeros(self.n, dtype=bool)  # empty term matches nothing
        ok = np.ones(self.n, dtype=bool)
        for r in term.match_expressions:
            ok &= self.match_requirement(r)
        for r in term.match_fields:
            ok &= self.match_field_requirement(r)
        return ok

    def match_node_selector(self, selector) -> np.ndarray:
        ok = np.zeros(self.n, dtype=bool)
        for term in selector.terms:
            ok |= self.match_node_selector_term(term)
        return ok

    def match_required_node_affinity(self, pod: Pod) -> np.ndarray:
        """spec.nodeSelector AND nodeAffinity.required (GetRequiredNodeAffinity)."""
        ok = np.ones(self.n, dtype=bool)
        for k, v in pod.spec.node_selector.items():
            vocab, ids = self.val_ids(k)
            ok &= (ids == vocab[v]) if v in vocab else np.zeros(self.n, dtype=bool)
        aff = pod.spec.affinity
        if aff and aff.node_affinity_required is not None:
            ok &= self.match_node_selector(aff.node_affinity_required)
        return ok


def pod_class_signature(pod: Pod) -> tuple:
    """Scheduling-relevant spec signature; pods with equal signatures schedule
    identically given equal resource requests (the equivalence-class dedupe).

    Hot: called once per pod per batch (100k at north-star scale), so the
    common empty cases (no labels/selector/affinity/constraints) short-circuit
    before any sort/repr work.

    Memoized on the pod (the ~6µs/pod build_pod_batch lever from the ROADMAP
    stage table): the tuple build runs once per pod LIFETIME instead of once
    per batch — re-solves of a churning backlog and requeued gangs hit the
    cache. The entry is keyed by the live spec/labels container identities:
    a spec replacement (queue.update parses a NEW Pod), a clone that swaps
    spec (bind/assume clones), or a labels rebuild all miss and recompute, so
    staleness cannot survive any mutation path the store contract allows."""
    cached = pod.__dict__.get("_class_sig")
    if (cached is not None and cached[0] is pod.spec
            and cached[1] is pod.metadata.labels):
        return cached[2]
    sig = _pod_class_signature(pod)
    pod.__dict__["_class_sig"] = (pod.spec, pod.metadata.labels, sig)
    return sig


def _pod_class_signature(pod: Pod) -> tuple:
    spec = pod.spec
    aff = spec.affinity
    labels = pod.metadata.labels
    any_ports = any(c.ports for c in spec.containers)
    ports = tuple(sorted(
        (p.protocol or "TCP", p.host_port)
        for c in spec.containers for p in c.ports if p.host_port > 0
    )) if any_ports else ()
    any_images = any(c.image for c in spec.containers) or any(
        c.image for c in spec.init_containers)
    images = tuple(sorted(
        c.image for c in list(spec.init_containers) + list(spec.containers) if c.image
    )) if any_images else ()
    # the gang RANK label is positional metadata, not a scheduling
    # constraint (api/podgroup.py POD_GROUP_RANK_LABEL): excluding it keeps
    # a 250-rank gang ONE equivalence class (one filter row, one solver
    # dispatch) — selectors keying on it are unsupported on the batched path
    if labels and POD_GROUP_RANK_LABEL in labels:
        label_sig = tuple(sorted(kv for kv in labels.items()
                                 if kv[0] != POD_GROUP_RANK_LABEL))
    else:
        label_sig = tuple(sorted(labels.items())) if labels else ()
    return (
        pod.metadata.namespace,
        label_sig,
        spec.node_name,
        tuple(sorted(spec.node_selector.items())) if spec.node_selector else (),
        repr(aff) if aff else "",
        tuple(spec.tolerations) if spec.tolerations else (),
        tuple(spec.topology_spread_constraints) if spec.topology_spread_constraints else (),
        ports,
        images,
        len(spec.containers) + len(spec.init_containers),
        tuple(spec.volumes) if spec.volumes else (),
        tuple(spec.resource_claims) if spec.resource_claims else (),
        tuple(spec.resource_claim_templates)
        if spec.resource_claim_templates else (),
    )


@dataclass
class ClassTables:
    """Static class x node tables (numpy, ready for device upload)."""

    rep_pods: List[Pod]  # one representative per class
    filter_ok: np.ndarray  # [C, N] bool
    aff_ok: np.ndarray  # [C, N] bool (nodeSelector+required affinity only — the
    #   PTS counting-eligibility set under the default Honor policy)
    napref_raw: np.ndarray  # [C, N] int32 (node-affinity preferred weight sums)
    has_napref: np.ndarray  # [C] bool
    taint_cnt: np.ndarray  # [C, N] int32 (intolerable PreferNoSchedule counts)
    img_score: np.ndarray  # [C, N] int32 (final ImageLocality score 0..100)
    # host-port state (dynamic on device: in-batch placements claim ports too)
    class_ports: np.ndarray  # [C, Pt] bool — ports each class requests
    node_ports: np.ndarray  # [N, Pt] bool — ports in use by existing pods


def compile_class_tables(rep_pods: Sequence[Pod], cols: NodeColumns) -> ClassTables:
    c, n = len(rep_pods), cols.n
    filter_ok = np.ones((c, n), dtype=bool)
    aff_ok = np.ones((c, n), dtype=bool)
    napref = np.zeros((c, n), dtype=np.int32)
    has_napref = np.zeros(c, dtype=bool)
    taint_cnt = np.zeros((c, n), dtype=np.int32)
    img_score = np.zeros((c, n), dtype=np.int32)

    taint_list = [None] * len(cols.taint_vocab)
    for (k, v, e), i in cols.taint_vocab.items():
        taint_list[i] = (k, v, e)
    hard_taints = np.array(
        [t is not None and t[2] in (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE) for t in taint_list],
        dtype=bool,
    ) if taint_list else np.zeros(0, dtype=bool)
    prefer_taints = np.array(
        [t is not None and t[2] == TAINT_PREFER_NO_SCHEDULE for t in taint_list], dtype=bool
    ) if taint_list else np.zeros(0, dtype=bool)

    from ..api import Taint

    for ci, pod in enumerate(rep_pods):
        # NodeName (node_name.go)
        if pod.spec.node_name:
            filter_ok[ci] &= np.array(cols.names) == pod.spec.node_name
        # NodeUnschedulable (node_unschedulable.go)
        fake = Taint(key="node.kubernetes.io/unschedulable", effect=TAINT_NO_SCHEDULE)
        if not any(t.tolerates(fake) for t in pod.spec.tolerations):
            filter_ok[ci] &= ~cols.unschedulable
        # NodeAffinity + nodeSelector
        aff_ok[ci] = cols.match_required_node_affinity(pod)
        filter_ok[ci] &= aff_ok[ci]
        # TaintToleration filter + score
        if len(taint_list):
            tolerated = np.array(
                [t is not None and any(tol.tolerates(Taint(*t)) for tol in pod.spec.tolerations)
                 for t in taint_list],
                dtype=bool,
            )
            untol_hard = cols.taint_matrix[:, hard_taints & ~tolerated]
            filter_ok[ci] &= ~untol_hard.any(axis=1)
            # Score tolerations: only empty-effect or PreferNoSchedule tolerations
            # count (taint_toleration.go:133)
            score_tolerated = np.array(
                [t is not None and any(
                    tol.tolerates(Taint(*t)) for tol in pod.spec.tolerations
                    if tol.effect in ("", TAINT_PREFER_NO_SCHEDULE))
                 for t in taint_list],
                dtype=bool,
            )
            taint_cnt[ci] = cols.taint_matrix[:, prefer_taints & ~score_tolerated].sum(axis=1)
        # NodePorts: vocab registration only — conflicts are checked dynamically
        # on device (in-batch placements claim ports), seeded from existing usage.
        for p_ in {(p.protocol or "TCP", p.host_port)
                   for ctr in pod.spec.containers for p in ctr.ports if p.host_port > 0}:
            if p_ not in cols.port_vocab:
                cols.port_vocab[p_] = len(cols.port_vocab)
        # NodeAffinity preferred score (raw weights; normalized on device per pod)
        aff = pod.spec.affinity
        if aff and aff.node_affinity_preferred:
            has_napref[ci] = True
            acc = np.zeros(n, dtype=np.int32)
            for pref in aff.node_affinity_preferred:
                acc += pref.weight * cols.match_node_selector_term(pref.term).astype(np.int32)
            napref[ci] = acc
        # ImageLocality (static final score, image_locality.go:78)
        images = [c_.image for c_ in list(pod.spec.init_containers) + list(pod.spec.containers)
                  if c_.image]
        num_containers = len(pod.spec.containers) + len(pod.spec.init_containers)
        if images and num_containers and len(cols.image_vocab):
            from ..scheduler.plugins.node_plugins import ImageLocality, _normalized_image_name

            ids = [cols.image_vocab[_normalized_image_name(im)] for im in images
                   if _normalized_image_name(im) in cols.image_vocab]
            sums = cols.image_matrix[:, ids].astype(np.int64) @ cols.image_value[ids] \
                if ids else np.zeros(n, dtype=np.int64)
            lo = ImageLocality.MIN_THRESHOLD
            hi = ImageLocality.MAX_CONTAINER_THRESHOLD * num_containers
            sums = np.clip(sums, lo, hi)
            img_score[ci] = (MAX_NODE_SCORE * (sums - lo) // (hi - lo)).astype(np.int32)

    # port tensors sized to the final (nodes + classes) vocab
    pt = max(len(cols.port_vocab), 1)
    class_ports = np.zeros((c, pt), dtype=bool)
    for ci, pod in enumerate(rep_pods):
        for p_ in {(p.protocol or "TCP", p.host_port)
                   for ctr in pod.spec.containers for p in ctr.ports if p.host_port > 0}:
            class_ports[ci, cols.port_vocab[p_]] = True
    node_ports = np.zeros((n, pt), dtype=bool)
    for i, ni in enumerate(cols.node_infos):
        for (ip, proto, port) in ni.used_ports:
            node_ports[i, cols.port_vocab[(proto, port)]] = True

    return ClassTables(
        rep_pods=list(rep_pods),
        filter_ok=filter_ok,
        aff_ok=aff_ok,
        napref_raw=napref,
        has_napref=has_napref,
        taint_cnt=taint_cnt,
        img_score=img_score,
        class_ports=class_ports,
        node_ports=node_ports,
    )
