"""Cluster snapshot -> struct-of-arrays tensors for the TPU solver.

SURVEY.md §7 step 2: NodeInfo-equivalent struct-of-arrays (allocatable/requested
[N,R], dictionary-encoded labels, topology-value ids, per-constraint count
tensors), mirroring the generation-diff stream of cache.go:186.

Quantization (device int32 everywhere — exact, no float rounding at feasibility
boundaries):
  cpu               -> millicores
  memory, ephemeral -> MiB; allocatable floors, requests ceil, so the device
                       view is conservative: it never admits a pod the byte-
                       exact oracle would reject (it may rarely reject one the
                       oracle admits, by < 1MiB).
  scalar resources  -> raw integer counts
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import Pod, Resource, compute_pod_resource_request
from ..api.resources import CPU, EPHEMERAL_STORAGE, MEMORY
from ..scheduler.framework import Snapshot
from .class_compiler import (
    ClassTables,
    NodeColumns,
    compile_class_tables,
    pod_class_signature,
)
from .ipa import IPATensors, compile_ipa

# The pod-carried memo slots this module owns (single source of truth —
# ISSUE 15): `_class_sig` is the admission-primed class-signature memo
# (pod_class_signature), `_req_sig` the spec-identity request-signature memo
# (_req_entry below), `_req_cache` the seeded PodInfo request pair. They
# live in pod.__dict__, so every structural/bind clone (which copies the
# dict at the C level) carries them for free — including the columnar
# store's lazily materialized rows (store/columnar.py captures the first
# two as its signature-ref column and relies on exactly this contract).
SIG_MEMO_KEYS = ("_class_sig", "_req_sig", "_req_cache")

MI = 1024 * 1024


def _quantize(r: Resource, resource_dims: Sequence[str], is_request: bool) -> List[int]:
    out = []
    for name in resource_dims:
        if name == CPU:
            out.append(r.milli_cpu)
        elif name == MEMORY:
            v = r.memory
            out.append(-(-v // MI) if is_request else v // MI)
        elif name == EPHEMERAL_STORAGE:
            v = r.ephemeral_storage
            out.append(-(-v // MI) if is_request else v // MI)
        else:
            out.append(r.scalar.get(name, 0))
    return out


def _raw_vec(r: Resource, resource_dims: Sequence[str]) -> List[int]:
    """Unquantized resource vector (milli-CPU, bytes, bytes, scalar counts).
    The columnar accounting path accumulates THESE and quantizes the totals,
    so its rows stay bit-identical to quantizing NodeInfo.requested (sum of
    per-pod MiB ceilings != ceiling of the byte sum)."""
    out = []
    for name in resource_dims:
        if name == CPU:
            out.append(r.milli_cpu)
        elif name == MEMORY:
            out.append(r.memory)
        elif name == EPHEMERAL_STORAGE:
            out.append(r.ephemeral_storage)
        else:
            out.append(r.scalar.get(name, 0))
    return out


def _quantize_raw_rows(raw: np.ndarray, resource_dims: Sequence[str]) -> np.ndarray:
    """Vectorized request-side quantization of raw [K, R] rows — the columnar
    equivalent of _quantize(..., is_request=True) per node."""
    out = raw.astype(np.int64, copy=True)
    for di, name in enumerate(resource_dims):
        if name in (MEMORY, EPHEMERAL_STORAGE):
            out[:, di] = -(-out[:, di] // MI)
    return out.astype(np.int32)


@dataclass
class ClusterTensors:
    """Node-axis tensors + class tables + topology-spread tensors (all numpy;
    ops/ moves them to device)."""

    node_names: List[str]
    resource_dims: List[str]  # dim meaning; [cpu, memory, ephemeral-storage, *extended]
    alloc: np.ndarray  # [N, R] int32
    used: np.ndarray  # [N, R] int32 (Requested)
    used_nz: np.ndarray  # [N, R] int32 (NonZeroRequested)
    pod_count: np.ndarray  # [N] int32
    max_pods: np.ndarray  # [N] int32
    cols: NodeColumns

    # topology keys in use: key -> row in topo_id
    topo_keys: List[str]
    topo_id: np.ndarray  # [Kk, N] int32 domain id per node (-1 = label missing)
    num_domains: np.ndarray  # [Kk] int32

    # selector-classes for PTS counting: (namespace, selector) -> row
    selcls_count: np.ndarray  # [SC, N] int32 existing matching pods per node

    @property
    def n(self) -> int:
        return len(self.node_names)


@dataclass
class PodBatchTensors:
    """Pod-axis tensors for one batch + the class tables they index into."""

    pods: List[Pod]
    class_of_pod: np.ndarray  # [P] int32
    req: np.ndarray  # [P, R] int32
    req_nz: np.ndarray  # [P, R] int32
    # balanced-allocation activity: all-zero plain request => skip (Skip status)
    balanced_active: np.ndarray  # [P] bool
    tables: ClassTables

    # flattened DoNotSchedule topology-spread constraints across classes:
    ct_class: np.ndarray  # [CT] int32 (owning class)
    ct_key: np.ndarray  # [CT] int32 (row into topo_id)
    ct_sel: np.ndarray  # [CT] int32 (row into selcls_count)
    ct_max_skew: np.ndarray  # [CT] int32
    ct_min_domains: np.ndarray  # [CT] int32 (0 = unset)
    ct_self_match: np.ndarray  # [CT] int32 (pod matches own constraint selector)
    # ScheduleAnyway constraints (scored), same layout:
    st_class: np.ndarray
    st_key: np.ndarray
    st_sel: np.ndarray
    st_max_skew: np.ndarray
    st_self_match: np.ndarray
    # cross-matching: does a pod of class c match selector-class sc?
    class_matches_selcls: np.ndarray  # [C, SC] int32

    # inter-pod affinity tensors (snapshot/ipa.py)
    ipa: IPATensors

    # classes whose pods cannot be batch-solved (unsupported features) — the
    # batch driver routes these to the serial fallback
    fallback_class: np.ndarray  # [C] bool

    # columnar accounting inputs (see _raw_vec): unquantized per-pod request
    # vectors and the per-class host-port flag that gates the tensor-cache
    # assume fast path (host-port pods need a port-row recompute)
    raw_req: Optional[np.ndarray] = None  # [P, R] int64
    raw_req_nz: Optional[np.ndarray] = None  # [P, R] int64
    class_has_host_ports: Optional[np.ndarray] = None  # [C] bool

    # gang rows (scheduler/gang.py): group id per pod (-1 = not a member),
    # the group keys those ids index, and the per-(class, node) slice-packing
    # score bonus. All None when the batch has no gang members — the solvers
    # compile their gang-free variants (pay-for-what-you-use).
    gang_of_pod: Optional[np.ndarray] = None  # [P] int32
    gang_keys: Optional[List[str]] = None  # [G]
    gang_bonus: Optional[np.ndarray] = None  # [C, N] int32
    # positional rank per gang member (pod-group.scheduling/rank, -1 absent);
    # None when no member carries one — the rank-alignment pass (ISSUE 14)
    # is then never invoked, keeping rank-less gang batches byte-identical
    gang_rank: Optional[np.ndarray] = None  # [P] int32

    @property
    def p(self) -> int:
        return len(self.pods)

    @property
    def c(self) -> int:
        return len(self.tables.rep_pods)

    @property
    def has_constraints(self) -> bool:
        """Any topology-spread or inter-pod-affinity term in the batch — the
        routing predicate shared by the batch driver and bench: False keeps
        the constraint-free fast path byte-identical (no repair, no scan
        gathers), True routes fast/auto modes to the propose-and-repair
        solver (models/repair.py) with the scan as residual oracle."""
        return bool(self.ct_class.size or self.st_class.size
                    or self.ipa.has_any)


class TensorCache:
    """Cross-batch incremental tensorization (VERDICT r3 #2; reference:
    cache.go:186 UpdateSnapshot's generation diff).

    `Cache.update_snapshot` reuses the SAME NodeInfo object for nodes whose
    generation didn't change, so identity comparison against the previous
    snapshot is exactly the generation diff. This cache exploits it twice:

      cluster rows   — alloc/used/used_nz/pod_count/max_pods/port rows are
                       recomputed only for changed nodes (same node set);
      count columns  — the PTS/IPA per-(selector-class, node) count tensor is
                       recomputed only for changed nodes when the batch
                       registers the same selector classes AND the namespace
                       label table is unchanged (IPA namespaceSelector
                       matchers resolve against it live). IPA holder-group
                       counts are NOT incremental — compile_ipa rebuilds them
                       per batch (group registration dominates there anyway).

    Anything structural (node set/order, label/taint/image/vocab changes,
    different class registry, namespace relabels) falls back to a full
    rebuild — correctness first, the fast path is an optimization the parity
    tests pin."""

    # cluster-level tensors that live in HBM across batches; changed rows are
    # scatter-updated on device instead of re-uploading the full array
    DEVICE_FIELDS = ("alloc", "used", "used_nz", "pod_count", "max_pods")

    def __init__(self):
        self.snap: Optional[Snapshot] = None
        self.node_infos: Optional[list] = None  # aligned NodeInfo identities
        self.cluster: Optional[ClusterTensors] = None
        # batch-level artifacts for count-column reuse
        self.selcls_keys: Optional[tuple] = None
        self.selcls_count: Optional[np.ndarray] = None
        self.ns_fingerprint: Optional[tuple] = None
        # persistent device (HBM) mirrors of the cluster tensors; dirty rows
        # accumulate across passes (a pass may skip the device upload — e.g.
        # native solver or all-fallback batches — and the rows it changed must
        # still reach HBM on the next upload)
        self._device: dict = {}
        self._device_selcls = None
        self._device_selcls_host = None  # the host array the mirror tracks
        self._dirty_rows: set = set()
        self._dirty_all = True
        # previous PodBatchTensors (pod-axis reuse for same-backlog re-solves)
        self._last_batch = None
        # columnar assume state: raw (unquantized) per-node request totals,
        # the cache generation the current tensors are consistent with, and
        # the rows + generation a pending apply_assume_deltas covers
        self._raw_used: Optional[np.ndarray] = None  # [N, R] int64
        self._raw_used_nz: Optional[np.ndarray] = None
        self._tensorized_gen: Optional[int] = None
        self._assume_gen: Optional[int] = None
        self._assume_rows: Optional[set] = None

    # -- cluster tensors -------------------------------------------------------

    def cluster_tensors(self, snapshot: Snapshot) -> Tuple[ClusterTensors, Optional[List[int]]]:
        """Returns (cluster, changed_node_indices). changed is None on a full
        rebuild (meaning: treat every node as changed)."""
        nis = snapshot.node_info_list
        prev_nis = self.node_infos
        if (self.cluster is None or prev_nis is None or len(prev_nis) != len(nis)):
            return self._full(snapshot)
        if (self._assume_gen is not None
                and snapshot.generation == self._assume_gen):
            # columnar fast path: every cache mutation since the last
            # tensorize was our own assume batch, whose deltas are already
            # applied to used/used_nz/pod_count (apply_assume_deltas) — no
            # per-node requantize, no label/taint/port re-checks (assumes
            # touch existing nodes' accounting only). The rows still go back
            # as `changed` so selector-class counts recount them when a
            # constrained batch follows.
            changed = sorted(self._assume_rows)
            self._assume_gen = None
            self._assume_rows = None
            cluster = self.cluster
            for i in changed:
                cluster.cols.node_infos[i] = nis[i]
            self.snap = snapshot
            self.node_infos = list(nis)
            self._tensorized_gen = snapshot.generation
            return cluster, changed
        self._assume_gen = None
        self._assume_rows = None
        if (snapshot.changed_names is not None and self.snap is not None
                and snapshot.changed_from_gen == self.snap.generation):
            # The snapshot itself carries the diff (cache dirty-name tracking,
            # Snapshot.from_prev) relative to exactly the snapshot we last
            # tensorized — diff by the named set instead of identity-walking
            # every node. Same rows the identity walk would find (from_prev
            # replaces precisely the named positions, order unchanged).
            name_index = snapshot._name_index
            changed = sorted(name_index[nm] for nm in snapshot.changed_names)
        else:
            changed = [i for i in range(len(nis)) if nis[i] is not prev_nis[i]]
        cluster = self.cluster
        for i in changed:
            ni, old = nis[i], prev_nis[i]
            if (ni.node is None or old.node is None
                    or ni.node.metadata.name != cluster.node_names[i]
                    or ni.node.metadata.labels != old.node.metadata.labels
                    or ni.node.spec.taints != old.node.spec.taints
                    or ni.node.spec.unschedulable != old.node.spec.unschedulable
                    or ni.image_states.keys() != old.image_states.keys()):
                # label-churn batches COULD be patched in place, but vocab
                # growth / topo-id rewrites make it structural: full rebuild
                return self._full(snapshot)
        if not changed:
            self.snap = snapshot
            self.node_infos = list(nis)
            self._tensorized_gen = snapshot.generation
            return cluster, []
        self._dirty_rows.update(changed)
        dims = cluster.resource_dims
        for i in changed:
            ni = nis[i]
            if set(ni.allocatable.scalar.keys()) - set(dims):
                return self._full(snapshot)  # new extended resource dim
            cluster.alloc[i] = np.array(
                _quantize(ni.allocatable, dims, is_request=False), dtype=np.int32)
            cluster.used[i] = np.array(
                _quantize(ni.requested, dims, is_request=True), dtype=np.int32)
            cluster.used_nz[i] = np.array(
                _quantize(ni.non_zero_requested, dims, is_request=True), dtype=np.int32)
            cluster.pod_count[i] = len(ni.pods) + ni.col_count
            cluster.max_pods[i] = ni.allocatable.allowed_pod_number
            if self._raw_used is not None:
                self._raw_used[i] = _raw_vec(ni.requested, dims)
                self._raw_used_nz[i] = _raw_vec(ni.non_zero_requested, dims)
        # port usage rows (NodeColumns caches them for class table compile)
        cols = cluster.cols
        for i in changed:
            cols.node_infos[i] = nis[i]
            row = np.zeros(cols.port_matrix.shape[1], dtype=bool)
            ok = True
            for (_ip, proto, port) in nis[i].used_ports:
                pi = cols.port_vocab.get((proto, port))
                if pi is None:
                    ok = False  # new port vocab entry: structural
                    break
                row[pi] = True
            if not ok:
                return self._full(snapshot)
            cols.port_matrix[i] = row
        self.snap = snapshot
        self.node_infos = list(nis)
        self._tensorized_gen = snapshot.generation
        return cluster, changed

    def _full(self, snapshot: Snapshot) -> Tuple[ClusterTensors, None]:
        self.cluster = build_cluster_tensors(snapshot)
        self.snap = snapshot
        self.node_infos = list(snapshot.node_info_list)
        self.selcls_keys = self.selcls_count = None
        self.ns_fingerprint = None
        self._device = {}
        self._device_selcls = None
        self._device_selcls_host = None
        self._dirty_rows.clear()
        self._dirty_all = True
        dims = self.cluster.resource_dims
        self._raw_used = np.array(
            [_raw_vec(ni.requested, dims) for ni in self.node_infos],
            dtype=np.int64).reshape(len(self.node_infos), len(dims))
        self._raw_used_nz = np.array(
            [_raw_vec(ni.non_zero_requested, dims) for ni in self.node_infos],
            dtype=np.int64).reshape(len(self.node_infos), len(dims))
        self._tensorized_gen = snapshot.generation
        self._assume_gen = None
        self._assume_rows = None
        return self.cluster, None

    def apply_assume_deltas(self, rows: np.ndarray, d_raw_used: np.ndarray,
                            d_raw_used_nz: np.ndarray, d_count: np.ndarray,
                            tensorized_gen: int, assume_gen: int) -> bool:
        """Columnar assume accounting: fold a solved batch's per-node raw
        request deltas (numpy scatter-adds keyed by the tensorizer's node
        index, computed by the batch scheduler) straight into the cluster
        tensors, then requantize only the touched rows — vectorized. Records
        assume_gen (the cache generation after the matching
        Cache.apply_node_resource_deltas) so the next cluster_tensors can
        prove the snapshot diff is fully explained by this batch and skip the
        per-node walk entirely. Returns False (no-op) when the current
        tensors aren't at tensorized_gen — a foreign mutation slipped in and
        the normal incremental path must requantize instead."""
        if (self.cluster is None or self._raw_used is None
                or self._tensorized_gen != tensorized_gen):
            return False
        rows = np.asarray(rows)
        dims = self.cluster.resource_dims
        self._raw_used[rows] += d_raw_used
        self._raw_used_nz[rows] += d_raw_used_nz
        self.cluster.used[rows] = _quantize_raw_rows(self._raw_used[rows], dims)
        self.cluster.used_nz[rows] = _quantize_raw_rows(self._raw_used_nz[rows], dims)
        self.cluster.pod_count[rows] = (
            self.cluster.pod_count[rows]
            + d_count.astype(self.cluster.pod_count.dtype))
        self._dirty_rows.update(int(i) for i in rows)
        if self._assume_rows is None:
            self._assume_rows = set()
        self._assume_rows.update(int(i) for i in rows)
        self._assume_gen = assume_gen
        return True

    # -- persistent HBM mirrors (the diff -> device stream of cache.go:186) ----

    def device_views(self, cluster: ClusterTensors) -> dict:
        """Device-resident cluster tensors, updated incrementally: a full
        rebuild uploads once; afterwards only dirty node rows (accumulated
        across passes, including ones that skipped the device path) are
        scattered into HBM with `.at[rows].set`, so per-batch host->device
        traffic scales with the diff, not the cluster. Returns
        {field: jnp.ndarray} for make_inputs(device=...)."""
        import jax.numpy as jnp

        dirty = sorted(self._dirty_rows)
        if self._dirty_all or not self._device:
            self._device = {f: jnp.asarray(getattr(cluster, f))
                            for f in self.DEVICE_FIELDS}
            full_upload = True
        elif dirty:
            rows = np.asarray(dirty)
            for f in self.DEVICE_FIELDS:
                host = getattr(cluster, f)
                self._device[f] = self._device[f].at[rows].set(host[rows])
            full_upload = False
        else:
            full_upload = False
        out = dict(self._device)
        # selector-class counts: same treatment, keyed by host-array identity
        # (build_pod_batch reuses the array in place on the incremental path)
        sc = cluster.selcls_count
        if sc.size:
            if (self._device_selcls is None
                    or self._device_selcls_host is not sc
                    or self._device_selcls.shape != sc.shape
                    or full_upload):
                self._device_selcls = jnp.asarray(sc)
                self._device_selcls_host = sc
            elif dirty:
                cols = np.asarray(dirty)
                self._device_selcls = self._device_selcls.at[:, cols].set(sc[:, cols])
            out["selcls_count"] = self._device_selcls
        self._dirty_rows.clear()
        self._dirty_all = False
        return out


def build_cluster_tensors(snapshot: Snapshot, extra_resource_dims: Sequence[str] = ()) -> ClusterTensors:
    node_infos = snapshot.node_info_list
    n = len(node_infos)
    # resource dims: core three + every extended resource present in allocatable
    extended = set(extra_resource_dims)
    for ni in node_infos:
        extended.update(ni.allocatable.scalar.keys())
    resource_dims = [CPU, MEMORY, EPHEMERAL_STORAGE] + sorted(extended)
    r = len(resource_dims)

    alloc = np.zeros((n, r), dtype=np.int64)
    used = np.zeros((n, r), dtype=np.int64)
    used_nz = np.zeros((n, r), dtype=np.int64)
    pod_count = np.zeros(n, dtype=np.int32)
    max_pods = np.zeros(n, dtype=np.int32)
    for i, ni in enumerate(node_infos):
        alloc[i] = _quantize(ni.allocatable, resource_dims, is_request=False)
        used[i] = _quantize(ni.requested, resource_dims, is_request=True)
        used_nz[i] = _quantize(ni.non_zero_requested, resource_dims, is_request=True)
        # columnar cache rows count toward the node's pod population without
        # being materialized as PodInfo objects (scheduler/cachecols.py)
        pod_count[i] = len(ni.pods) + ni.col_count
        max_pods[i] = ni.allocatable.allowed_pod_number

    cols = NodeColumns(node_infos)
    return ClusterTensors(
        node_names=[ni.node.metadata.name for ni in node_infos],
        resource_dims=resource_dims,
        alloc=alloc.astype(np.int32),
        used=used.astype(np.int32),
        used_nz=used_nz.astype(np.int32),
        pod_count=pod_count,
        max_pods=max_pods,
        cols=cols,
        topo_keys=[],
        topo_id=np.zeros((0, n), dtype=np.int32),
        num_domains=np.zeros(0, dtype=np.int32),
        selcls_count=np.zeros((0, n), dtype=np.int32),
    )


def build_pod_batch(pods: Sequence[Pod], snapshot: Snapshot,
                    cluster: ClusterTensors, ns_labels=None,
                    hard_pod_affinity_weight: int = 1,
                    reuse: Optional[TensorCache] = None,
                    changed_nodes: Optional[List[int]] = None,
                    gangs=None, store_cols=None) -> PodBatchTensors:
    """Group pods into classes, compile class tables, build PTS + IPA tensors.

    reuse + changed_nodes (from TensorCache.cluster_tensors) enable the
    incremental count path: when this batch registers the same selector
    classes as the previous one, per-node match counts are recomputed only
    for changed nodes instead of scanning every bound pod.

    gangs (a scheduler.gang.GangDirectory) threads group-id rows through the
    batch: each pod's PodGroup index plus the per-class slice-packing bonus.
    Skipped entirely while the directory is inactive (no PodGroups).

    store_cols (a store PodColumnsView) feeds the per-pod signature loops
    from the store's interned sig COLUMN instead of recomputing: pods freshly
    parsed by the watch ingest carry no `_class_sig`/`_req_sig` memos, but the
    columnar store captured the previous parse's memo refs at sync — when the
    column entry's identity anchors (spec, labels) still match this pod
    object, the memos are re-seeded from the column and both the native fused
    loop and the Python fallback hit instead of re-deriving the signatures.
    Zero-copy read of the MU001-tainted view; never required for
    correctness."""
    ns_labels = ns_labels or {}
    gang_of_pod = gang_keys = gang_bonus = gang_rank = None
    if gangs is not None and gangs.active:
        gang_of_pod, gang_keys, gang_rank = gangs.batch_rows(pods)
    # pod-axis reuse: re-solving the SAME pending backlog after cluster churn
    # (the incremental re-solve of BASELINE.json's ladder) skips the per-pod
    # signature/quantization loops — identity comparison against the previous
    # batch's pod list
    prev = getattr(reuse, "_last_batch", None) if reuse is not None else None
    pod_axis = None
    if (prev is not None and len(prev.pods) == len(pods)
            and all(a is b for a, b in zip(prev.pods, pods))):
        pod_axis = prev
    r = len(cluster.resource_dims)
    # memoize by container-resources signature: template-stamped pods (the
    # overwhelmingly common case) compute their request vectors exactly once
    # (entry index, (Resource, non-zero Resource) for PodInfo seeding)
    req_cache: Dict[tuple, tuple] = {}
    req_entries: List[tuple] = []  # (quant, quant_nz, active, raw, raw_nz)

    def _res_sig(res: dict) -> tuple:
        # {"requests": {...}, "limits": {...}, "claims": [...]} -> hashable
        # value key (cheaper than repr at 100k-pod scale); non-dict values
        # (resources.claims is a list) degrade to repr
        if not res:
            return ()
        return tuple(
            (k, tuple(sorted(v.items())) if isinstance(v, dict) else repr(v))
            for k, v in sorted(res.items()))

    def _req_entry(pod) -> tuple:
        # request-signature memo, keyed by spec identity like _class_sig
        # (resources live under spec; any change parses a NEW Pod/spec):
        # the tuple build runs once per pod LIFETIME, and the native fused
        # loop (hostcommit.batch_rows) reads the same memo — parity by
        # construction
        rs = pod.__dict__.get("_req_sig")
        if rs is not None and rs[0] is pod.spec:
            sig = rs[1]
        else:
            sig = (
                tuple(_res_sig(c.resources) for c in pod.spec.containers),
                tuple(_res_sig(c.resources) for c in pod.spec.init_containers),
                repr(pod.spec.overhead) if pod.spec.overhead else "",
            )
            pod.__dict__["_req_sig"] = (pod.spec, sig)
        got = req_cache.get(sig)
        if got is None:
            pr = compute_pod_resource_request(pod)
            prnz = compute_pod_resource_request(pod, non_zero=True)
            req_entries.append((
                _quantize(pr, cluster.resource_dims, is_request=True),
                _quantize(prnz, cluster.resource_dims, is_request=True),
                # BalancedAllocation PreScore skip rule: best-effort over the
                # configured resources (balanced_allocation.go PreScore)
                pr.milli_cpu != 0 or pr.memory != 0,
                _raw_vec(pr, cluster.resource_dims),
                _raw_vec(prnz, cluster.resource_dims),
            ))
            got = (len(req_entries) - 1, (pr, prnz))
            req_cache[sig] = got
        # Seed PodInfo's memoized request pair so a later cache assume of
        # this pod (or its structural clones — they share __dict__) costs
        # dict lookups instead of recomputing both Resource sums. The shared
        # Resource objects are read-only by PodInfo's existing contract.
        if "_req_cache" not in pod.__dict__:
            pod.__dict__["_req_cache"] = got[1]
        return got

    seed_memos = None
    if store_cols is not None and getattr(store_cols, "sig", None) is not None:
        _key2row = store_cols.key2row
        _sig_col = store_cols.sig

        def seed_memos(pod):
            # Re-seed the pod's signature memos from the store's sig COLUMN
            # (captured refs from a previous parse's __dict__ at sync) when
            # the identity anchors still hold — the fused loop / fallback
            # then take their memo-hit path instead of re-deriving. A miss
            # (fresh spec, no row) is harmless: the normal derivation runs.
            # Returns True when anything was seeded (the sweep's dry-out
            # signal).
            d = pod.__dict__
            row = _key2row.get(pod.key)
            if row is None:
                return False
            ent = _sig_col[row]
            if ent is None:
                return False
            cs, rs = ent
            seeded = False
            if (cs is not None and "_class_sig" not in d and len(cs) == 3
                    and cs[0] is pod.spec and cs[1] is pod.metadata.labels):
                d["_class_sig"] = cs
                seeded = True
            if (rs is not None and "_req_sig" not in d and len(rs) == 2
                    and rs[0] is pod.spec):
                d["_req_sig"] = rs
                seeded = True
            return seeded

    entry_rows: List[int] = []
    if pod_axis is not None:
        rep_pods = list(pod_axis.tables.rep_pods)
        class_of_pod = pod_axis.class_of_pod
        if getattr(pod_axis, "_resource_dims", None) == tuple(cluster.resource_dims):
            req = pod_axis.req  # already int32; passed through copy-free below
            req_nz = pod_axis.req_nz
            balanced_active = pod_axis.balanced_active
            raw_req = pod_axis.raw_req
            raw_req_nz = pod_axis.raw_req_nz
        else:
            for pod in pods:
                entry_rows.append(_req_entry(pod)[0])
    else:
        # ONE fused pass per pod: class signature + request-memo row (two
        # separate 100k-pod loops were measurable); per-pod array writes are
        # replaced by a vectorized gather over the unique memo entries below.
        # The loop body is memo dict hits in the steady state, so it ports to
        # the native commit engine (ISSUE 11) verbatim — same dicts, same
        # append order, misses call back into the Python helpers.
        sig_to_class: Dict[tuple, int] = {}
        rep_pods = []
        from ..native import hostcommit as _hostcommit

        if seed_memos is not None:
            # PRE-PASS, not a per-callback ride-along: seeded pods take the
            # fused loop's pure C-side memo-hit path with zero Python
            # callbacks. Adaptive dry-out: a batch whose first 64 memo-less
            # pods find nothing in the column (the create→schedule lifecycle
            # syncs rows before any memo exists) stops consulting it — the
            # seed path must never cost more than the derivation it saves.
            probed = hits = 0
            for pod in pods:
                d = pod.__dict__
                if "_class_sig" in d and "_req_sig" in d:
                    continue
                if seed_memos(pod):
                    hits += 1
                probed += 1
                if probed >= 64 and not hits:
                    break
        if pods and _hostcommit.available():
            def _entry_cb(pod):
                return _req_entry(pod)[0]
            class_of_pod, entry_rows = _hostcommit.batch_rows(
                pods, sig_to_class, rep_pods, req_cache,
                pod_class_signature, _entry_cb)
        else:
            class_rows: List[int] = []
            for pod in pods:
                sig = pod_class_signature(pod)
                ci = sig_to_class.get(sig)
                if ci is None:
                    ci = len(rep_pods)
                    sig_to_class[sig] = ci
                    rep_pods.append(pod)
                class_rows.append(ci)
                entry_rows.append(_req_entry(pod)[0])
            class_of_pod = np.asarray(class_rows, dtype=np.int32)

    if len(entry_rows):
        eidx = np.asarray(entry_rows)
        ne = len(req_entries)
        req = np.array([e[0] for e in req_entries],
                       dtype=np.int64).reshape(ne, r)[eidx]
        req_nz = np.array([e[1] for e in req_entries],
                          dtype=np.int64).reshape(ne, r)[eidx]
        balanced_active = np.array([e[2] for e in req_entries],
                                   dtype=bool)[eidx]
        raw_req = np.array([e[3] for e in req_entries],
                           dtype=np.int64).reshape(ne, r)[eidx]
        raw_req_nz = np.array([e[4] for e in req_entries],
                              dtype=np.int64).reshape(ne, r)[eidx]
    elif pod_axis is None:
        req = np.zeros((0, r), dtype=np.int64)
        req_nz = np.zeros((0, r), dtype=np.int64)
        raw_req = np.zeros((0, r), dtype=np.int64)
        raw_req_nz = np.zeros((0, r), dtype=np.int64)
        balanced_active = np.zeros(0, dtype=bool)

    tables = compile_class_tables(rep_pods, cluster.cols)

    if gang_of_pod is not None:
        # per-(class, node) topology-packing bonus: classes are gang-
        # exclusive (the gang label is part of pod_class_signature), so the
        # bias can ride the class axis like every other static score table
        from ..scheduler.gang import gang_slice_bonus

        gang_bonus = gang_slice_bonus(
            cluster, class_of_pod, np.asarray(req, dtype=np.int64),
            tables.filter_ok, gang_of_pod, len(rep_pods))

    # -- topology keys + selector classes (shared by PTS + IPA) ----------------
    topo_key_idx: Dict[str, int] = {k: i for i, k in enumerate(cluster.topo_keys)}
    selcls_idx: Dict[tuple, int] = {}
    selcls_matchers: List = []  # pod -> bool predicates, one per row

    def topo_row(key: str) -> int:
        if key not in topo_key_idx:
            topo_key_idx[key] = len(topo_key_idx)
            cluster.topo_keys.append(key)
            vocab, ids = cluster.cols.val_ids(key)
            row = ids[None, :].astype(np.int32)
            cluster.topo_id = np.concatenate([cluster.topo_id, row], axis=0) \
                if cluster.topo_id.size else row
            nd = np.array([max(len(vocab), 1)], dtype=np.int32)
            cluster.num_domains = np.concatenate([cluster.num_domains, nd])
        return topo_key_idx[key]

    def selcls_row(key: tuple, matcher) -> int:
        if key not in selcls_idx:
            selcls_idx[key] = len(selcls_matchers)
            selcls_matchers.append(matcher)
        return selcls_idx[key]

    def pts_selcls_row(namespace: str, sel) -> int:
        def matcher(p, _ns=namespace, _sel=sel):
            # PTS counting excludes terminating pods (countPodsMatchSelector)
            return (p.metadata.namespace == _ns
                    and p.metadata.deletion_timestamp is None
                    and _sel.matches(p.metadata.labels))

        return selcls_row(("pts", namespace, repr(sel)), matcher)

    from ..scheduler.plugins.helpers import pts_effective_selector

    ct_rows, st_rows = [], []
    fallback_class = np.zeros(len(rep_pods), dtype=bool)
    for ci, pod in enumerate(rep_pods):
        if pod.spec.resource_claims or pod.spec.resource_claim_templates:
            # DRA claims need the allocator's Reserve/Unreserve/PreBind
            # transitions — serial path (dynamic_resources.py)
            fallback_class[ci] = True
        if any(v.scheduling_relevant for v in pod.spec.volumes):
            # PVC/ephemeral/shared-disk constraints (binding/zone/limits/
            # conflicts) are not dense-encoded; those pods take the serial path
            # where the volume plugins run with Reserve/PreBind semantics.
            # configMap/secret/emptyDir-style volumes don't constrain placement
            # and stay on device (VERDICT round-1 weak item 2).
            fallback_class[ci] = True
        for c in pod.spec.topology_spread_constraints:
            sel = pts_effective_selector(c, pod)
            if sel is None:
                continue
            if c.node_affinity_policy != "Honor" or c.node_taints_policy != "Ignore":
                fallback_class[ci] = True  # non-default inclusion policies: serial
                continue
            row = (
                ci,
                topo_row(c.topology_key),
                pts_selcls_row(pod.metadata.namespace, sel),
                c.max_skew,
                c.min_domains or 0,
                1 if sel.matches(pod.metadata.labels) else 0,
            )
            if c.when_unsatisfiable == "DoNotSchedule":
                ct_rows.append(row)
            else:
                st_rows.append(row)

    # inter-pod affinity rows + holder groups (registers more selector classes)
    ipa = compile_ipa(
        rep_pods, snapshot, topo_row, selcls_row, ns_labels,
        hard_pod_affinity_weight,
        node_name_to_idx=cluster.cols.name_to_idx, n_nodes=cluster.n,
    )

    # existing matching-pod counts per (selector-class, node)
    sc = len(selcls_matchers)
    selcls_key_tuple = tuple(selcls_idx.keys())

    def _count_node_column(ni) -> np.ndarray:
        col = np.zeros(sc, dtype=np.int32)
        for pinfo in ni.pods:
            p = pinfo.pod
            for si, matcher in enumerate(selcls_matchers):
                if matcher(p):
                    col[si] += 1
        return col

    # IPA namespaceSelector matchers resolve against the live ns_labels
    # table, which the selector-class keys do NOT capture — a namespace
    # relabel must invalidate cached counts
    ns_fp = tuple(sorted(
        (ns, tuple(sorted(lbls.items()))) for ns, lbls in ns_labels.items()))
    if sc == 0:
        # no selector classes registered (constraint-free batch): skip the
        # per-node pod walks outright — the count tensor is empty either way
        selcls_count = np.zeros((0, cluster.n), dtype=np.int32)
    elif (reuse is not None and changed_nodes is not None
            and reuse.selcls_keys == selcls_key_tuple
            and reuse.ns_fingerprint == ns_fp
            and reuse.selcls_count is not None
            and reuse.selcls_count.shape == (sc, cluster.n)):
        # incremental: only changed nodes rescan their pods
        selcls_count = reuse.selcls_count
        for nidx in changed_nodes:
            selcls_count[:, nidx] = _count_node_column(
                snapshot.node_info_list[nidx])
    else:
        selcls_count = np.zeros((sc, cluster.n), dtype=np.int32)
        for nidx, ni in enumerate(snapshot.node_info_list):
            selcls_count[:, nidx] = _count_node_column(ni)
    if reuse is not None:
        reuse.selcls_keys = selcls_key_tuple
        reuse.selcls_count = selcls_count
        reuse.ns_fingerprint = ns_fp
    cluster.selcls_count = selcls_count

    # cross-match: placing a pod of class c bumps counts of selector-class sc?
    class_matches = np.zeros((len(rep_pods), max(sc, 1)), dtype=np.int32)
    for ci, pod in enumerate(rep_pods):
        for si, matcher in enumerate(selcls_matchers):
            if matcher(pod):
                class_matches[ci, si] = 1

    def rows_to_arrays(rows, with_min_domains):
        if not rows:
            z = np.zeros(0, dtype=np.int32)
            return (z, z, z, z, z, z) if with_min_domains else (z, z, z, z, z)
        a = np.array(rows, dtype=np.int32)
        if with_min_domains:
            return a[:, 0], a[:, 1], a[:, 2], a[:, 3], a[:, 4], a[:, 5]
        return a[:, 0], a[:, 1], a[:, 2], a[:, 3], a[:, 5]

    ct_class, ct_key, ct_sel, ct_max_skew, ct_min_domains, ct_self = rows_to_arrays(ct_rows, True)
    st_class, st_key, st_sel, st_max_skew, st_self = rows_to_arrays(st_rows, False)

    from ..scheduler.framework import _host_ports

    class_has_host_ports = np.array(
        [any(True for _ in _host_ports(p)) for p in rep_pods], dtype=bool)

    out = PodBatchTensors(
        pods=list(pods),
        class_of_pod=class_of_pod,
        req=np.asarray(req, dtype=np.int32),
        req_nz=np.asarray(req_nz, dtype=np.int32),
        balanced_active=balanced_active,
        tables=tables,
        ct_class=ct_class, ct_key=ct_key, ct_sel=ct_sel,
        ct_max_skew=ct_max_skew, ct_min_domains=ct_min_domains, ct_self_match=ct_self,
        st_class=st_class, st_key=st_key, st_sel=st_sel,
        st_max_skew=st_max_skew, st_self_match=st_self,
        class_matches_selcls=class_matches,
        ipa=ipa,
        fallback_class=fallback_class,
        raw_req=np.asarray(raw_req, dtype=np.int64),
        raw_req_nz=np.asarray(raw_req_nz, dtype=np.int64),
        class_has_host_ports=class_has_host_ports,
        gang_of_pod=gang_of_pod,
        gang_keys=gang_keys or None,
        gang_bonus=gang_bonus,
        gang_rank=gang_rank,
    )
    if reuse is not None:
        # the cached req vectors are only valid against the same resource-dim
        # layout (a dim swap with equal length would misquantize silently)
        out._resource_dims = tuple(cluster.resource_dims)
        reuse._last_batch = out  # pod-axis reuse for same-backlog re-solves
    return out
