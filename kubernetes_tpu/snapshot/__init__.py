"""Snapshot/tensorizer — cluster state as struct-of-arrays for the device."""

from .class_compiler import ClassTables, NodeColumns, compile_class_tables, pod_class_signature  # noqa: F401
from .tensorizer import ClusterTensors, PodBatchTensors, build_cluster_tensors, build_pod_batch  # noqa: F401
