"""kadm — cluster bootstrap CLI (the kubeadm analog, L10).

reference: cmd/kubeadm (init/join/token flows — the lifecycle surface, not the
code). `kadm init` stands up a control plane: API server (optionally secured
with a generated bootstrap token), leader-elected scheduler + controller
bundle. `kadm join` attaches a (hollow) node over HTTP: registers the Node,
renews its Lease, and runs a minimal remote kubelet loop that watches for
bound pods and reports them Running — the kubemark-style join that exercises
the full client surface instead of in-process store access.
"""

from __future__ import annotations

import argparse
import os
import secrets
import sys
import threading
import time
from typing import Dict, Optional

from ..server.client import APIError, RESTClient


class InitResult:
    """Handle onto an init-ed control plane (library surface for tests/embeds)."""

    def __init__(self, server, control_plane, token: Optional[str], store,
                 join_token: Optional[str] = None):
        self.server = server
        self.control_plane = control_plane
        self.token = token  # admin credential (kubeadm's admin.conf analog)
        self.join_token = join_token  # node bootstrap token (system:bootstrappers)
        self.store = store

    @property
    def url(self) -> str:
        return self.server.url

    def wait_ready(self, timeout: float = 30.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.control_plane.is_leader:
                return True
            time.sleep(0.02)
        return self.control_plane.is_leader

    def stop(self) -> None:
        self.control_plane.stop()
        self.server.stop()


def init_control_plane(port: int = 0, secure: bool = False,
                       identity: str = "kadm-0",
                       use_batch_scheduler: bool = True) -> InitResult:
    """kubeadm init equivalent: store + apiserver (+ bootstrap token RBAC when
    secure) + leader-elected control plane."""
    from ..server.auth import (
        AuthenticatorChain,
        SignedTokenAuthenticator,
        TokenAuthenticator,
        default_component_authorizer,
    )
    from ..server.controlplane import ControlPlane
    from ..server.rest import APIServer
    from ..store import APIStore

    store = APIStore()
    token = join_token = None
    authn = authz = signer = None
    if secure:
        token = secrets.token_urlsafe(16)
        static = TokenAuthenticator()
        # the admin token is cluster-admin, like kubeadm's initial
        # admin.conf credential
        static.add(token, "kubernetes-admin", ["system:masters"])
        # the JOIN token is only a bootstrapper: it can file a CSR and read
        # it back, nothing else — the issued credential carries the real
        # node identity (kubeadm's bootstrap-token + TLS-bootstrap split)
        join_token = secrets.token_urlsafe(16)
        static.add(join_token, "system:bootstrap:kadm", ["system:bootstrappers"])
        signer = SignedTokenAuthenticator(secrets.token_bytes(32))
        authn = AuthenticatorChain([static, signer])
        authz = default_component_authorizer()
        authz.grant("group:system:bootstrappers",
                    ["create", "get", "list", "watch"],
                    ["certificatesigningrequests"])
    server = APIServer(store, port=port, authenticator=authn,
                       authorizer=authz,
                       flowcontrol="default" if secure else None,
                       audit="default" if secure else None,
                       token_signer=signer).start()
    cp = ControlPlane(store, identity=identity,
                      use_batch_scheduler=use_batch_scheduler,
                      signer=signer).start()
    return InitResult(server, cp, token, store, join_token=join_token)


class JoinedNode:
    """A node joined over HTTP: Node object + Lease heartbeats + a fake
    remote kubelet (bound pods get phase Running; deletes observed). Pod
    state arrives through a watching Informer, not per-tick LISTs — N joined
    hollow nodes must not turn the apiserver into an O(N*P) list mill."""

    def __init__(self, client: RESTClient, node_name: str,
                 capacity: Dict[str, str], heartbeat: float = 2.0,
                 credential_refresher=None,
                 labels: Optional[Dict[str, str]] = None):
        self.client = client
        self.node_name = node_name
        self.capacity = dict(capacity)
        # extra node labels (topology zone/region etc.) applied at
        # registration — kubelet's --node-labels
        self.labels = dict(labels or {})
        self.heartbeat = heartbeat
        # () -> new bearer token; called when the current credential expires
        # (the kubelet's client-cert rotation analog)
        self.credential_refresher = credential_refresher
        self.running: Dict[str, object] = {}  # pod key -> typed Pod (informer)
        self._informer = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self) -> None:
        try:
            self.client.create("nodes", {
                "kind": "Node",
                "metadata": {"name": self.node_name,
                             "labels": {"kubernetes.io/hostname": self.node_name,
                                        **self.labels}},
                "status": {"capacity": self.capacity,
                           "allocatable": self.capacity},
            })
        except APIError as e:
            if e.code != 409:
                raise
            # node exists (re-join / restart): reconcile labels onto it —
            # the kubelet re-applies --node-labels at every registration
            if self.labels:
                self.client.patch("nodes", self.node_name, {
                    "metadata": {"labels": {
                        "kubernetes.io/hostname": self.node_name,
                        **self.labels}}}, None)
        self._renew_lease()

    def _renew_lease(self) -> None:
        now = time.time()
        body = {"kind": "Lease",
                "metadata": {"name": self.node_name, "namespace": "kube-node-lease"},
                "spec": {"holderIdentity": self.node_name,
                         "acquireTime": now, "renewTime": now}}
        try:
            cur = self.client.get("leases", self.node_name, "kube-node-lease")
            body["metadata"]["resourceVersion"] = cur["metadata"]["resourceVersion"]
            self.client.update("leases", body, "kube-node-lease")
        except APIError as e:
            if e.code == 404:
                try:
                    self.client.create("leases", body, "kube-node-lease")
                except APIError as e2:
                    if e2.code != 409:
                        raise
            else:
                raise

    def sync_once(self) -> int:
        """One kubelet-ish pass over the informer cache: adopt bound pods,
        report them Running. Adoption happens only AFTER the status write
        succeeds — a 409/422 must be retried on the next pass, not swallowed
        into a forever-Pending pod."""
        from ..api.serialize import to_dict

        if self._informer is None:
            return 0
        n = 0
        seen = set()
        for key, pod in list(self._informer.cache.items()):
            if pod.spec.node_name != self.node_name:
                continue
            seen.add(key)
            if pod.is_terminal() or key in self.running:
                continue
            if pod.status.phase == "Running":
                self.running[key] = pod
                continue
            doc = to_dict(pod)
            doc.setdefault("status", {})["phase"] = "Running"
            try:
                # status subresource: a kubelet's write can only ever touch
                # status, never spec (registry status-REST split)
                self.client.update_status("pods", doc, pod.metadata.namespace)
            except APIError:
                continue  # conflict/validation: retry next pass
            self.running[key] = pod
            self._append_log(pod, "Started container (hollow)")
            n += 1
        for key in list(self.running):
            if key not in seen:
                self.running.pop(key, None)
        n += self._serve_stream_sessions(seen)
        return n

    def _serve_stream_sessions(self, my_pods) -> int:
        """Answer exec/attach/port-forward sessions for pods on this node —
        the HTTP face of the kubelet server's streaming endpoints. The
        command emulation is FakeRuntime's exec_sync/port_data (one table,
        shared with in-process kubelets, not a drifting copy)."""
        import base64

        from ..agent.cri import FakeRuntime
        from ..api.execapi import ATTACH_COMMAND

        if not hasattr(self, "_exec_runtime"):
            self._exec_runtime = FakeRuntime()
        n = 0
        try:
            sessions, _ = self.client.list("podexecs")
        except APIError:
            sessions = []
        for s in sessions:
            spec, st = s.get("spec") or {}, s.get("status") or {}
            ns = (s.get("metadata") or {}).get("namespace", "default")
            pod_key = f"{ns}/{spec.get('podName', '')}"
            if st.get("done") or pod_key not in my_pods:
                continue
            try:
                # per-session guard: one malformed session must not starve
                # the rest of this pass (it gets marked done with an error)
                stdin = base64.b64decode(spec.get("stdin") or "")
                cmd = list(spec.get("command") or [])
                if cmd == [ATTACH_COMMAND]:
                    out = "attached (hollow)\n" + stdin.decode(
                        errors="replace")
                    err_b, code, error = "", 0, ""
                else:
                    o, e, code = self._exec_runtime.exec_sync(
                        pod_key, spec.get("container", ""), cmd, stdin)
                    out = o.decode(errors="replace")
                    err_b, error = e.decode(errors="replace"), ""
            except Exception as ex:
                out, err_b, code, error = "", "", 1, str(ex)
            s.setdefault("status", {}).update(
                {"stdout": out, "stderr": err_b, "exitCode": code,
                 "done": True, **({"error": error} if error else {})})
            try:
                self.client.update("podexecs", s, ns)
                n += 1
            except APIError:
                pass  # deleted (client gave up) or conflict: next pass
        try:
            forwards, _ = self.client.list("podportforwards")
        except APIError:
            forwards = []
        for s in forwards:
            spec, st = s.get("spec") or {}, s.get("status") or {}
            ns = (s.get("metadata") or {}).get("namespace", "default")
            pod_key = f"{ns}/{spec.get('podName', '')}"
            if st.get("done") or pod_key not in my_pods:
                continue
            try:
                data = base64.b64decode(spec.get("data") or "")
                answer = self._exec_runtime.port_data(
                    pod_key, int(spec.get("port", 0) or 0), data)
                s.setdefault("status", {}).update(
                    {"data": base64.b64encode(answer).decode(),
                     "done": True})
            except Exception as ex:
                s.setdefault("status", {}).update(
                    {"done": True, "error": str(ex)})
            try:
                self.client.update("podportforwards", s, ns)
                n += 1
            except APIError:
                pass
        return n

    def _append_log(self, pod, message: str) -> None:
        """Feed the pod's log channel over HTTP (PodLog; best effort)."""
        from ..api.events import PodLog

        ns, name = pod.metadata.namespace, pod.metadata.name
        line = f"{time.time():.3f} [kubelet] {message}"
        try:
            cur = self.client.get("podlogs", name, ns)
            refs = (cur.get("metadata") or {}).get("ownerReferences") or []
            if refs and refs[0].get("uid") not in ("", pod.metadata.uid):
                # recreated same-name pod: fresh stream, re-owned (see
                # append_pod_log)
                self.client.patch("podlogs", name, {
                    "metadata": {"ownerReferences": [{
                        "kind": "Pod", "name": name,
                        "uid": pod.metadata.uid}]},
                    "entries": [line]}, ns)
                return
            entries = (cur.get("entries") or []) + [line]
            self.client.patch("podlogs", name,
                              {"entries": entries[-PodLog.MAX_LINES:]}, ns)
        except APIError as e:
            if e.code != 404:
                return
            try:
                self.client.create("podlogs", {
                    "kind": "PodLog",
                    "metadata": {"name": name, "namespace": ns,
                                 "ownerReferences": [{
                                     "kind": "Pod", "name": name,
                                     "uid": pod.metadata.uid}]},
                    "entries": [line]}, ns)
            except APIError:
                pass

    def start(self) -> "JoinedNode":
        from .. import server as _server  # noqa: F401  (package init)
        from ..server.client import Informer

        self.register()
        self._informer = Informer(
            self.client, "pods",
            field_selector=f"spec.nodeName={self.node_name}").start()

        def loop():
            last_hb = 0.0
            while not self._stop.is_set():
                try:
                    if time.time() - last_hb >= self.heartbeat:
                        self._renew_lease()
                        last_hb = time.time()
                    self.sync_once()
                except APIError as e:
                    if e.code == 401 and self.credential_refresher is not None:
                        try:  # expired credential: rotate and retry next tick
                            self.client.token = self.credential_refresher()
                        except Exception:
                            pass
                except Exception:
                    pass
                self._stop.wait(0.2)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._informer is not None:
            self._informer.stop()
            self._informer = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def bootstrap_node_credential(server_url: str, node_name: str,
                              bootstrap_token: str,
                              timeout: float = 30.0) -> str:
    """The TLS-bootstrap analog: authenticate with the bootstrap token, file
    a CSR for the system:node:<name> identity, wait for the approve+sign
    controllers, return the issued credential. reference: kubeadm join's
    bootstrap flow + pkg/kubelet/certificate/bootstrap."""
    client = RESTClient(server_url, token=bootstrap_token, user_agent="kadm")
    # generated name (the kubelet's csr-<rand> convention): every join or
    # renewal files a FRESH request, so a stale issued credential on an old
    # CSR can never be handed back; the cleaner GCs the leftovers
    name = f"node-csr-{node_name}-{secrets.token_hex(4)}"
    body = {
        "kind": "CertificateSigningRequest",
        "metadata": {"name": name},
        "spec": {
            "request": {"user": f"system:node:{node_name}",
                        "groups": ["system:nodes"]},
            "signerName": "kubernetes.io/kube-apiserver-client-kubelet",
            "usages": ["client auth"],
        },
    }
    client.create("certificatesigningrequests", body, namespace=None)
    deadline = time.time() + timeout
    while time.time() < deadline:
        csr = client.get("certificatesigningrequests", name, namespace=None)
        cert = (csr.get("status") or {}).get("certificate", "")
        if cert:
            return cert
        for c in (csr.get("status") or {}).get("conditions", []):
            if c.get("type") == "Denied":
                raise RuntimeError(f"CSR {name} denied: {c.get('message', '')}")
        time.sleep(0.05)
    raise TimeoutError(f"CSR {name} not issued within {timeout}s")


def join_node(server_url: str, node_name: str,
              capacity: Optional[Dict[str, str]] = None,
              token: Optional[str] = None,
              bootstrap: bool = False,
              labels: Optional[Dict[str, str]] = None) -> JoinedNode:
    """kubeadm join equivalent (library surface). With bootstrap=True the
    token is treated as a bootstrap token: the node first trades it for its
    own signed system:node:<name> credential via the CSR flow, so
    NodeRestriction admission scopes everything it writes."""
    refresher = None
    if bootstrap:
        if not token:
            raise ValueError("bootstrap join requires a bootstrap token")
        bootstrap_token = token
        token = bootstrap_node_credential(server_url, node_name, bootstrap_token)
        refresher = lambda: bootstrap_node_credential(  # noqa: E731
            server_url, node_name, bootstrap_token)
    client = RESTClient(server_url, token=token, user_agent="kadm")
    return JoinedNode(client, node_name,
                      capacity or {"cpu": "8", "memory": "16Gi", "pods": "110"},
                      credential_refresher=refresher, labels=labels).start()


# -- CLI -----------------------------------------------------------------------


def cmd_init(args) -> int:
    res = init_control_plane(port=args.port, secure=args.secure)
    if not res.wait_ready(30):
        print("error: control plane did not become leader", file=sys.stderr)
        return 1
    print(f"control plane ready at {res.url}")
    if args.write_kubeconfig:
        # kubeadm writes admin.conf; ktl's config analog gets a ready context
        from .ktlconfig import load_config, save_config

        cfg = load_config()
        cfg["clusters"]["kadm"] = {"server": res.url}
        cfg["users"]["kadm-admin"] = {"token": res.token or ""}
        cfg["contexts"]["kadm"] = {"cluster": "kadm", "user": "kadm-admin",
                                   "namespace": "default"}
        cfg["current-context"] = "kadm"
        save_config(cfg)
        print("kubeconfig context 'kadm' written (ktl config view)")
    if res.token:
        print(f"admin token: {res.token}")
        print(f"join token: {res.join_token}")
        if args.token_file:
            with open(args.token_file, "w") as f:
                f.write(res.join_token or res.token)
    print(f"join nodes with: kadm join --server {res.url} --node-name <name>"
          + (" --token <join-token> --bootstrap" if res.token else ""))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        res.stop()
    return 0


def cmd_join(args) -> int:
    labels = {}
    for pair in (args.node_labels.split(",") if args.node_labels else []):
        pair = pair.strip()
        if not pair:
            continue
        k, eq, v = pair.partition("=")
        k = k.strip()
        if not eq or not k:
            print(f"error: malformed --node-labels entry {pair!r} "
                  "(want key=value)", file=sys.stderr)
            return 1
        labels[k] = v.strip()
    node = join_node(args.server, args.node_name,
                     capacity={"cpu": args.cpu, "memory": args.memory,
                               "pods": str(args.max_pods)},
                     token=args.token or None,
                     bootstrap=args.bootstrap,
                     labels=labels)
    print(f"node {args.node_name} joined {args.server}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kadm")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("init")
    p.add_argument("--port", type=int, default=18080)
    p.add_argument("--secure", action="store_true")
    p.add_argument("--token-file", default="")
    p.add_argument("--write-kubeconfig", action="store_true",
                   help="write a ready ktl config context (admin.conf analog)")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("join")
    p.add_argument("--server", required=True)
    p.add_argument("--node-name", required=True)
    p.add_argument("--token", default=os.environ.get("KADM_TOKEN", ""))
    p.add_argument("--bootstrap", action="store_true",
                   help="treat --token as a bootstrap token: run the CSR "
                        "flow and join with the issued node credential")
    p.add_argument("--node-labels", default="",
                   help="k=v[,k2=v2] labels applied at registration "
                        "(kubelet --node-labels)")
    p.add_argument("--cpu", default="8")
    p.add_argument("--memory", default="16Gi")
    p.add_argument("--max-pods", type=int, default=110)
    p.set_defaults(fn=cmd_join)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
