"""ktl — the kubectl-equivalent CLI (L8).

reference: staging/src/k8s.io/kubectl/pkg/cmd (the command set, not the code).
Talks HTTP to the API server (KTL_SERVER env or --server).

Commands: get, describe, create -f, apply -f (server-side merge patch),
delete, scale, cordon, uncordon, taint, drain, label, annotate, patch,
rollout status|restart, set image, top nodes|pods, sched stats|trace|slo|top
(top: the steady-state windowed dashboard from /debug/timeseries),
controller stats (reconcile-loop telemetry from /debug/controlstats), vet
(schedlint — the local static-analysis gate, no apiserver needed), wait,
autoscale, api-resources, version.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from ..api.serialize import (
    CLUSTER_SCOPED,
    GROUP_PREFIX,
    KIND_TO_RESOURCE,
    RESOURCE_TO_TYPE,
)
from ..server.client import APIError, RESTClient

ALIASES = {
    "po": "pods", "pod": "pods",
    "no": "nodes", "node": "nodes",
    "ns": "namespaces", "namespace": "namespaces",
    "rs": "replicasets", "replicaset": "replicasets",
    "deploy": "deployments", "deployment": "deployments",
    "lease": "leases",
}


def resolve_resource(name: str) -> str:
    """Static aliases resolve locally; anything else passes through so the
    server's DynamicRegistry can match CRD plurals/singulars/shortNames
    (unknown names come back as a clean 404)."""
    return ALIASES.get(name, name)


def resolve_kind(client: RESTClient, kind: str) -> Optional[str]:
    """Manifest kind -> resource plural; built-ins locally, CRDs via
    discovery."""
    resource = KIND_TO_RESOURCE.get(kind)
    if resource is not None:
        return resource
    try:
        return client._discover(kind.lower())["name"]
    except APIError:
        return None


def load_manifests(path: str) -> List[Dict]:
    """YAML (if available) or JSON manifests; multi-document supported."""
    if path == "-":
        raw = sys.stdin.read()
    else:
        with open(path) as f:
            raw = f.read()
    try:
        import yaml  # type: ignore

        docs = [d for d in yaml.safe_load_all(raw) if d]
        if docs:
            return docs
    except ImportError:
        pass
    raw = raw.strip()
    if raw.startswith("["):
        return json.loads(raw)
    return [json.loads(raw)]


def fmt_table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    out = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


# -- command implementations ---------------------------------------------------


class CLIError(Exception):
    """User-facing CLI error: printed as `error: ...`, exit 1 — without
    swallowing unrelated ValueErrors from command internals."""


_OUTPUT_MODES = ("wide", "json", "yaml")


def _jsonpath_extract(obj, expr: str):
    """The dotted-path subset of kubectl's -o jsonpath: `{.a.b[0].c}`;
    multiple `{...}` templates join with spaces. Range/filter/negative-index
    syntax is not supported (clean error instead of silent garbage)."""
    import re

    parts = re.findall(r"\{([^}]*)\}", expr)
    if not parts:
        raise CLIError(f"invalid jsonpath template {expr!r}")
    out = []
    for part in parts:
        if part.startswith("range") or "?(" in part or "*" in part:
            raise CLIError(f"unsupported jsonpath feature in {{{part}}}")
        cur = obj
        for m in re.finditer(r"([^.\[\]]+)|\[([^\]]*)\]",
                             part.strip().lstrip(".")):
            key, idx = m.group(1), m.group(2)
            if idx is not None:
                if not idx.isdigit():
                    raise CLIError(
                        f"unsupported jsonpath index [{idx}] in {{{part}}}")
                i = int(idx)
                cur = cur[i] if isinstance(cur, list) and i < len(cur) else ""
            elif isinstance(cur, dict):
                cur = cur.get(key, "")
            else:
                cur = ""
        out.append(cur if isinstance(cur, str) else json.dumps(cur))
    return " ".join(out)


# the category `ktl get all` expands to (kubectl's `all` category)
ALL_CATEGORY = ("pods", "services", "deployments", "replicasets",
                "statefulsets", "daemonsets", "jobs", "cronjobs")


def cmd_get(client: RESTClient, args) -> int:
    if args.resource == "all" and not args.name:
        if getattr(args, "watch", False):
            raise CLIError("get all does not support --watch")
        ns = args.namespace or "default"
        sel = getattr(args, "selector", "") or ""
        output = args.output
        if output not in _OUTPUT_MODES and not output.startswith("jsonpath="):
            raise CLIError(f"unknown output format {output!r}")
        collected = []
        for res in ALL_CATEGORY:
            items, _ = client.list(res, None if args.all_namespaces else ns,
                                   label_selector=sel)
            collected.append((res, items))
        if output == "json":
            print(json.dumps([o for _r, items in collected for o in items],
                             indent=2))
            return 0
        if output == "yaml":
            _print_yaml({"items": [o for _r, items in collected for o in items]})
            return 0
        if output.startswith("jsonpath="):
            for _r, items in collected:
                for o in items:
                    print(_jsonpath_extract(o, output[len("jsonpath="):]))
            return 0
        first = True
        for res, items in collected:
            if not items:
                continue
            if not first:
                print()
            first = False
            headers, raw_rows = _rows(res, items)
            # every category member's table starts NAMESPACE, NAME: fold
            # them into the typed name column kubectl prints for `get all`,
            # keeping NAMESPACE when -A made it meaningful
            if args.all_namespaces:
                rows = [[r[0], f"{res[:-1]}/{o['metadata']['name']}"] + r[2:]
                        for o, r in zip(items, raw_rows)]
                print(fmt_table(["NAMESPACE", "NAME"] + headers[2:], rows))
            else:
                rows = [[f"{res[:-1]}/{o['metadata']['name']}"] + r[2:]
                        for o, r in zip(items, raw_rows)]
                print(fmt_table(["NAME"] + headers[2:], rows))
        return 0
    resource = resolve_resource(args.resource)
    ns = None if resource in CLUSTER_SCOPED else (args.namespace or "default")
    output = args.output
    if output not in _OUTPUT_MODES and not output.startswith("jsonpath="):
        raise CLIError(f"unknown output format {output!r} "
                       f"(wide|json|yaml|jsonpath={{...}})")

    def emit(items, single=False):
        if output == "json":
            print(json.dumps(items[0] if single else items, indent=2))
        elif output == "yaml":
            _print_yaml(items[0] if single else {"items": items})
        elif output.startswith("jsonpath="):
            for o in items:
                print(_jsonpath_extract(o, output[len("jsonpath="):]))
        else:
            print(fmt_table(*_rows(resource, items)))

    def stream(rv, field_selector=""):
        # kubectl get -w: the stream keeps the requested format — one JSON/
        # YAML document or jsonpath line per event, table rows otherwise.
        # ring=True (ISSUE 13 satellite): a `-w` dashboard is an
        # OBSERVABILITY consumer — a slow terminal must drop its own oldest
        # rows, never terminate into the relist storm that stalls every
        # bind worker behind the watch bus (the PR-11 failure mode). `ktl
        # logs -f` keeps the eviction contract: it re-anchors on 410 and a
        # silently ring-dropped log line would be data loss.
        try:
            for etype, obj in client.watch(
                    resource, since_rv=rv,
                    namespace=None if args.all_namespaces else ns,
                    field_selector=field_selector,
                    label_selector=getattr(args, "selector", "") or "",
                    ring=True):
                if etype == "BOOKMARK":
                    continue
                if output == "json":
                    print(json.dumps(obj))
                elif output == "yaml":
                    _print_yaml(obj)
                elif output.startswith("jsonpath="):
                    print(etype, _jsonpath_extract(
                        obj, output[len("jsonpath="):]))
                else:
                    _h, rows = _rows(resource, [obj])
                    print(f"{etype:<9}" + "  ".join(rows[0]))
        except KeyboardInterrupt:
            pass

    if args.name:
        obj = client.get(resource, args.name, ns)
        emit([obj], single=True)
        if getattr(args, "watch", False):
            stream(int((obj.get("metadata") or {}).get("resourceVersion", 0)),
                   field_selector=f"metadata.name={args.name}")
        return 0
    sel = getattr(args, "selector", "") or ""
    items, rv = client.list(resource, None if args.all_namespaces else ns,
                            label_selector=sel)
    emit(items)
    if getattr(args, "watch", False):
        stream(rv)
    return 0


def _print_yaml(obj) -> None:
    try:
        import yaml  # type: ignore

        print(yaml.safe_dump(obj, sort_keys=False))
    except ImportError:
        print(json.dumps(obj, indent=2))


def _rows(resource: str, items: List[Dict]):
    if resource == "pods":
        headers = ["NAMESPACE", "NAME", "STATUS", "NODE", "PRIORITY"]
        rows = [[
            (o["metadata"].get("namespace") or ""),
            o["metadata"]["name"],
            (o.get("status") or {}).get("phase", ""),
            (o.get("spec") or {}).get("nodeName", "<none>") or "<pending>",
            str((o.get("spec") or {}).get("priority", 0)),
        ] for o in items]
    elif resource == "nodes":
        headers = ["NAME", "STATUS", "TAINTS", "CPU", "MEMORY"]
        rows = []
        for o in items:
            conds = {c["type"]: c["status"] for c in (o.get("status") or {}).get("conditions", [])}
            ready = "Ready" if conds.get("Ready", "True") == "True" else "NotReady"
            if (o.get("spec") or {}).get("unschedulable"):
                ready += ",SchedulingDisabled"
            taints = ",".join(t["key"] for t in (o.get("spec") or {}).get("taints", [])) or "<none>"
            cap = (o.get("status") or {}).get("allocatable", {})
            rows.append([o["metadata"]["name"], ready, taints,
                         str(cap.get("cpu", "")), str(cap.get("memory", ""))])
    elif resource in ("replicasets", "deployments"):
        headers = ["NAMESPACE", "NAME", "DESIRED", "CURRENT", "READY"]
        rows = [[
            o["metadata"].get("namespace") or "",
            o["metadata"]["name"],
            str((o.get("spec") or {}).get("replicas", 0)),
            str((o.get("status") or {}).get("replicas", 0)),
            str((o.get("status") or {}).get("readyReplicas", 0)),
        ] for o in items]
    elif resource == "events":
        headers = ["TYPE", "REASON", "OBJECT", "COUNT", "MESSAGE"]
        rows = [[
            o.get("type", ""),
            o.get("reason", ""),
            f'{(o.get("involvedObject") or {}).get("kind", "")}/'
            f'{(o.get("involvedObject") or {}).get("name", "")}',
            str(o.get("count", 1)),
            (o.get("message", "") or "")[:80],
        ] for o in sorted(items, key=lambda e: e.get("lastTimestamp", 0))]
    else:
        headers = ["NAMESPACE", "NAME"]
        rows = [[o["metadata"].get("namespace") or "", o["metadata"]["name"]] for o in items]
    return headers, rows


def cmd_create(client: RESTClient, args) -> int:
    rest = getattr(args, "rest", None) or []
    if rest and rest[0] in ("configmap", "cm", "secret"):
        # kubectl create configmap/secret NAME --from-literal k=v ...
        if len(rest) < 2:
            print("error: create configmap/secret requires a NAME", file=sys.stderr)
            return 1
        name = rest[1]
        data = {}
        for pair in args.from_literal or []:
            k, _, v = pair.partition("=")
            data[k] = v
        ns = args.namespace or "default"
        if rest[0] == "secret":
            # kubectl syntax is `create secret {generic|tls|docker-registry}
            # NAME`; only generic is supported — anything else must error,
            # not silently become the secret's name
            subtype = name
            if subtype != "generic":
                print(f"error: unsupported secret type {subtype!r} "
                      "(only 'generic' is supported)", file=sys.stderr)
                return 1
            if len(rest) < 3:
                print("error: create secret generic requires a NAME",
                      file=sys.stderr)
                return 1
            name = rest[2]
            doc = {"kind": "Secret", "metadata": {"name": name},
                   "stringData": data}
            client.create("secrets", doc, ns)
            print(f"secret/{name} created")
        else:
            doc = {"kind": "ConfigMap", "metadata": {"name": name},
                   "data": data}
            client.create("configmaps", doc, ns)
            print(f"configmap/{name} created")
        return 0
    if not args.filename:
        print("error: create requires -f FILE or configmap/secret form",
              file=sys.stderr)
        return 1
    rc = 0
    for doc in load_manifests(args.filename):
        kind = doc.get("kind", "")
        resource = resolve_kind(client, kind)
        if resource is None:
            print(f"error: unsupported kind {kind!r}", file=sys.stderr)
            rc = 1
            continue
        ns = args.namespace or (doc.get("metadata") or {}).get("namespace") or "default"
        try:
            out = client.create(resource, doc, None if resource in CLUSTER_SCOPED else ns)
            print(f"{resource}/{out['metadata']['name']} created")
        except APIError as e:
            print(f"error: {e}", file=sys.stderr)
            rc = 1
    return rc


def cmd_apply(client: RESTClient, args) -> int:
    rc = 0
    for doc in load_manifests(args.filename):
        kind = doc.get("kind", "")
        resource = resolve_kind(client, kind)
        if resource is None:
            print(f"error: unsupported kind {kind!r}", file=sys.stderr)
            rc = 1
            continue
        meta = doc.get("metadata") or {}
        ns = args.namespace or meta.get("namespace") or "default"
        ns_arg = None if resource in CLUSTER_SCOPED else ns
        try:
            # apply = SERVER-SIDE APPLY (kubectl apply --server-side;
            # handlers/patch.go applyPatcher): the manifest is this
            # manager's full intent — fields it stops mentioning are
            # removed, fields owned by other managers conflict (409)
            # unless --force-conflicts steals them
            client.apply(resource, meta["name"], doc, ns_arg,
                         field_manager=args.field_manager,
                         force=args.force_conflicts)
            print(f"{resource}/{meta['name']} serverside-applied")
        except APIError as e:
            print(f"error: {e}", file=sys.stderr)
            rc = 1
    return rc


def cmd_delete(client: RESTClient, args) -> int:
    if getattr(args, "filename", None):
        # kubectl delete -f: resolve each manifest's kind and delete by name
        rc = 0
        for doc in load_manifests(args.filename):
            resource = resolve_kind(client, doc.get("kind", ""))
            meta = doc.get("metadata") or {}
            if resource is None or not meta.get("name"):
                print(f"error: cannot delete {doc.get('kind')!r}", file=sys.stderr)
                rc = 1
                continue
            ns = None if resource in CLUSTER_SCOPED else (
                args.namespace or meta.get("namespace") or "default")
            try:
                client.delete(resource, meta["name"], ns)
                print(f"{resource}/{meta['name']} deleted")
            except APIError as e:
                print(f"error: {e}", file=sys.stderr)
                rc = 1
        return rc
    if args.resource and getattr(args, "all", False):
        # kubectl delete RESOURCE --all [-l selector]; a NAME alongside
        # --all is ambiguous and kubectl rejects it
        if args.name:
            print("error: name cannot be provided when --all is specified",
                  file=sys.stderr)
            return 1
        resource = resolve_resource(args.resource)
        ns = None if resource in CLUSTER_SCOPED else (args.namespace or "default")
        items, _ = client.list(resource, ns,
                               label_selector=getattr(args, "selector", "") or "")
        rc = 0
        for o in items:
            ons = o["metadata"].get("namespace") or None
            try:
                client.delete(resource, o["metadata"]["name"], ons)
                print(f"{resource}/{o['metadata']['name']} deleted")
            except APIError as e:
                if e.code == 404:
                    continue  # deleted concurrently: that's the goal anyway
                print(f"error: {e}", file=sys.stderr)
                rc = 1
        return rc
    if not args.resource or not args.name:
        print("error: delete requires RESOURCE NAME, --all, or -f FILE",
              file=sys.stderr)
        return 1
    resource = resolve_resource(args.resource)
    ns = None if resource in CLUSTER_SCOPED else (args.namespace or "default")
    try:
        client.delete(resource, args.name, ns)
        print(f"{resource}/{args.name} deleted")
        return 0
    except APIError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


def cmd_replace(client: RESTClient, args) -> int:
    """kubectl replace: full PUT of each manifest (handlers/update.go)."""
    rc = 0
    for doc in load_manifests(args.filename):
        resource = resolve_kind(client, doc.get("kind", ""))
        meta = doc.get("metadata") or {}
        if resource is None or not meta.get("name"):
            print(f"error: cannot replace {doc.get('kind')!r}", file=sys.stderr)
            rc = 1
            continue
        ns = None if resource in CLUSTER_SCOPED else (
            args.namespace or meta.get("namespace") or "default")
        try:
            if "resourceVersion" not in (doc.get("metadata") or {}):
                # carry the live RV so OCC applies to the replacement
                cur = client.get(resource, meta["name"], ns)
                doc.setdefault("metadata", {})["resourceVersion"] = \
                    cur["metadata"]["resourceVersion"]
            client.update(resource, doc, ns)
            print(f"{resource}/{meta['name']} replaced")
        except APIError as e:
            print(f"error: {e}", file=sys.stderr)
            rc = 1
    return rc


def cmd_run(client: RESTClient, args) -> int:
    """kubectl run: one pod from flags."""
    requests = {}
    for pair in args.requests.split(",") if args.requests else []:
        k, _, v = pair.partition("=")
        if k and v:
            requests[k] = v
    container = {"name": args.name, "image": args.image}
    if requests:
        container["resources"] = {"requests": requests}
    pod = {"kind": "Pod",
           "metadata": {"name": args.name,
                        "labels": {"run": args.name},
                        "namespace": args.namespace or "default"},
           "spec": {"containers": [container]}}
    client.create("pods", pod, args.namespace or "default")
    print(f"pod/{args.name} created")
    return 0


def cmd_expose(client: RESTClient, args) -> int:
    """kubectl expose: Service selecting the target workload's pods."""
    kind, name = args.target.split("/", 1) if "/" in args.target else ("deployment", args.target)
    resource = resolve_resource(kind)
    ns = args.namespace or "default"
    obj = client.get(resource, name, ns)
    selector = ((obj.get("spec") or {}).get("selector") or {})
    # Service selectors are plain label maps; fold single-value In
    # expressions back down (the serializer normalizes matchLabels into
    # matchExpressions)
    match = dict(selector.get("matchLabels") or {})
    for e in selector.get("matchExpressions") or []:
        if e.get("operator") == "In" and len(e.get("values") or []) == 1:
            match.setdefault(e["key"], e["values"][0])
    if not match:
        match = {"run": name}
    svc = {"kind": "Service",
           "metadata": {"name": args.service_name or name, "namespace": ns},
           "spec": {"selector": match,
                    "ports": [{"port": args.port,
                               "targetPort": args.target_port or args.port}]}}
    client.create("services", svc, ns)
    print(f"service/{svc['metadata']['name']} exposed")
    return 0


def cmd_certificate(client: RESTClient, args) -> int:
    """kubectl certificate approve|deny (certificates/v1 approval)."""
    import time as _time

    cond = {"type": "Approved" if args.action == "approve" else "Denied",
            "reason": "KubectlApprove" if args.action == "approve" else "KubectlDeny",
            "lastUpdateTime": _time.time()}
    csr = client.get("certificatesigningrequests", args.name, None)
    conds = (csr.get("status") or {}).get("conditions", [])
    if any(c.get("type") == cond["type"] for c in conds):
        print(f"certificatesigningrequest/{args.name} already {args.action}d")
        return 0
    opposite = "Denied" if cond["type"] == "Approved" else "Approved"
    if any(c.get("type") == opposite for c in conds):
        # a CSR may not carry both verdicts (certificates/v1 validation)
        print(f"error: certificatesigningrequest/{args.name} is already "
              f"{opposite}", file=sys.stderr)
        return 1
    conds.append(cond)
    client.patch("certificatesigningrequests", args.name,
                 {"status": {"conditions": conds}}, None)
    print(f"certificatesigningrequest/{args.name} {args.action}d")
    return 0


def cmd_auth_can_i(client: RESTClient, args) -> int:
    """kubectl auth can-i: SelfSubjectAccessReview round-trip."""
    out = client.request(
        "POST", "/apis/authorization.k8s.io/v1/selfsubjectaccessreviews",
        {"spec": {"resourceAttributes": {
            "verb": args.verb, "resource": resolve_resource(args.resource)}}})
    allowed = bool((out.get("status") or {}).get("allowed"))
    print("yes" if allowed else "no")
    return 0 if allowed else 1


def cmd_exec(client: RESTClient, args) -> int:
    """kubectl exec [-i] [-c container] POD -- CMD... over the store-channel
    session (reference: kubectl/pkg/cmd/exec/exec.go); exits with the
    remote command's exit code."""
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        raise CLIError("exec requires a command after --")
    ns = args.namespace or "default"
    stdin = b""
    if getattr(args, "stdin", False):
        stdin = sys.stdin.buffer.read()
    try:
        out = client.exec(args.pod, command, ns,
                          container=args.container or "", stdin=stdin)
    except APIError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    sys.stdout.write(out.get("stdout", ""))
    sys.stderr.write(out.get("stderr", ""))
    return int(out.get("exitCode", 0) or 0)


def cmd_attach(client: RESTClient, args) -> int:
    """kubectl attach: the running container's recent output; -i forwards
    stdin to the container."""
    ns = args.namespace or "default"
    stdin = b""
    if getattr(args, "stdin", False):
        stdin = sys.stdin.buffer.read()
    try:
        out = client.attach(args.pod, ns, container=args.container or "",
                            stdin=stdin)
    except APIError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    sys.stdout.write(out.get("stdout", ""))
    return int(out.get("exitCode", 0) or 0)


def cmd_port_forward(client: RESTClient, args) -> int:
    """kubectl port-forward POD LOCAL:REMOTE — a local TCP listener whose
    connections round-trip through the pod's port-forward channel. Serves
    until interrupted; --one-connection exits after the first round
    (scriptable/testable mode)."""
    import socket

    local, _, remote = args.ports.partition(":")
    if not remote:
        remote = local
    local_port, remote_port = int(local), int(remote)
    ns = args.namespace or "default"
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", local_port))
    srv.listen(4)
    bound = srv.getsockname()[1]
    print(f"Forwarding from 127.0.0.1:{bound} -> {remote_port}")
    sys.stdout.flush()
    try:
        while True:
            conn, _addr = srv.accept()
            try:
                conn.settimeout(5.0)
                chunks = []
                try:
                    while True:
                        b = conn.recv(65536)
                        if not b:
                            break
                        chunks.append(b)
                        if len(b) < 65536:
                            break  # request fits; answer now
                except TimeoutError:
                    pass
                data = b"".join(chunks)
                try:
                    answer = client.port_forward(args.pod, remote_port,
                                                 data, ns)
                    conn.sendall(answer)
                except APIError as e:
                    # one failed round must not kill the listener
                    print(f"error forwarding connection: {e}",
                          file=sys.stderr)
            finally:
                conn.close()
            if getattr(args, "one_connection", False):
                return 0
    except KeyboardInterrupt:
        return 0
    finally:
        srv.close()


def cmd_cp(client: RESTClient, args) -> int:
    """kubectl cp over the exec channel (cp.go rides exec+tar in the
    reference; here cat/tee against the container filesystem): `ktl cp
    pod:/path local` and `ktl cp local pod:/path`."""
    import base64
    import os

    ns = args.namespace or "default"

    def split(spec):
        # kubectl's disambiguation: a side is remote only when the prefix
        # before ':' looks like a pod name (no path separator) AND no local
        # file of that exact name exists — `./backup:2026.txt` stays local
        pod, sep, path = spec.partition(":")
        if not sep or "/" in pod or os.path.exists(spec):
            return None, spec
        return pod, path

    src_pod, src_path = split(args.src)
    dst_pod, dst_path = split(args.dst)
    if (src_pod is None) == (dst_pod is None):
        raise CLIError("cp needs exactly one pod:path side")
    try:
        if src_pod is not None:
            out = client.exec(src_pod, ["cat", src_path], ns,
                              container=args.container or "")
            if int(out.get("exitCode", 0) or 0) != 0:
                sys.stderr.write(out.get("stderr", ""))
                return 1
            # byte-faithful channel: the text stdout is lossy for binary
            # content (decoded with errors=replace on the agent)
            if out.get("stdoutB64"):
                data = base64.b64decode(out["stdoutB64"])
            else:
                data = out.get("stdout", "").encode()
            with open(dst_path, "wb") as f:
                f.write(data)
        else:
            with open(src_path, "rb") as f:
                data = f.read()
            out = client.exec(dst_pod, ["tee", dst_path], ns,
                              container=args.container or "", stdin=data)
            if int(out.get("exitCode", 0) or 0) != 0:
                sys.stderr.write(out.get("stderr", ""))
                return 1
    except (APIError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_diff(client: RESTClient, args) -> int:
    """kubectl diff: live object vs what applying the manifest would
    produce — computed with the SAME server-side-apply merge the server
    runs (server/fieldmanager.py), so the preview matches the write.
    Exit 1 when differences exist (the kubectl contract), 0 when clean."""
    import difflib

    from ..server.fieldmanager import Conflict, apply_patch

    changed = False
    for doc in load_manifests(args.filename):
        kind = doc.get("kind", "")
        resource = resolve_kind(client, kind)
        if resource is None:
            print(f"error: unsupported kind {kind!r}", file=sys.stderr)
            return 2
        meta = doc.get("metadata") or {}
        ns = args.namespace or meta.get("namespace") or "default"
        ns_arg = None if resource in CLUSTER_SCOPED else ns
        name = meta.get("name", "")
        try:
            live = client.get(resource, name, ns_arg)
        except APIError as e:
            if e.code != 404:
                print(f"error: {e}", file=sys.stderr)
                return 2
            live = None
        try:
            merged = apply_patch(live, doc, args.field_manager, force=True)
        except Conflict as e:  # force=True never raises; defensive
            print(f"error: {e}", file=sys.stderr)
            return 2
        def dump(d):
            if d is None:
                return []
            d = {k: v for k, v in d.items() if k != "metadata"} | {
                "metadata": {k: v for k, v in (d.get("metadata") or
                                               {}).items()
                             if k not in ("resourceVersion",
                                          "managedFields", "uid",
                                          "creationTimestamp")}}
            return json.dumps(d, indent=2, sort_keys=True,
                              default=str).splitlines(keepends=True)

        diff = list(difflib.unified_diff(
            dump(live), dump(merged),
            fromfile=f"live/{resource}/{name}",
            tofile=f"merged/{resource}/{name}"))
        if diff:
            changed = True
            sys.stdout.writelines(diff)
            if not diff[-1].endswith("\n"):
                print()
    return 1 if changed else 0


def cmd_logs(client: RESTClient, args) -> int:
    """kubectl logs [-f]: the pods/{name}/log subresource (text/plain);
    --follow streams new lines by watching the pod's PodLog channel."""
    ns = args.namespace or "default"
    if not getattr(args, "follow", False):
        sys.stdout.write(client.logs(args.name, ns, tail_lines=args.tail))
        return 0
    # follow: ONE snapshot (entries + resourceVersion) anchors both the
    # initial print and the watch resume — two separate reads would lose
    # lines appended between them. The cursor is the last printed LINE, not
    # an index: the channel trims its front at MAX_LINES and resets wholesale
    # when a same-name pod is recreated, so absolute indexes go stale.
    def snapshot():
        """-> (entries, rv): the channel's content and a watch-resume point.
        With no channel yet, the COLLECTION rv anchors the watch — "-1 /
        from now" would drop lines appended before the watcher registers."""
        try:
            cur = client.get("podlogs", args.name, ns)
            return (cur.get("entries") or [],
                    int((cur.get("metadata") or {}).get("resourceVersion", 0)
                        or 0))
        except APIError as e:
            if e.code != 404:
                raise
            _items, rv = client.list("podlogs", ns)
            return [], rv

    entries, rv = snapshot()
    shown = entries[-args.tail:] if args.tail > 0 else entries
    for line in shown:
        print(line)
    last = entries[-1] if entries else None
    sys.stdout.flush()

    def emit_after(entries, last):
        if last is not None:
            for i in range(len(entries) - 1, -1, -1):
                if entries[i] == last:
                    new = entries[i + 1:]
                    break
            else:
                new = entries  # anchor trimmed away or stream reset
        else:
            new = entries
        for line in new:
            print(line)
        sys.stdout.flush()
        return entries[-1] if entries else last

    import http.client as _http_client
    import urllib.error as _urlerr

    while True:
        try:
            for etype, obj in client.watch(
                    "podlogs", since_rv=rv, namespace=ns,
                    field_selector=f"metadata.name={args.name}"):
                if etype == "BOOKMARK":
                    rv = int((obj.get("metadata") or {})
                             .get("resourceVersion", rv) or rv)
                    continue
                rv = int((obj.get("metadata") or {})
                         .get("resourceVersion", rv) or rv)
                if etype == "DELETED":
                    last = None  # pod gone; a recreation starts fresh
                    continue
                last = emit_after(obj.get("entries") or [], last)
            return 0  # server ended the stream cleanly
        except KeyboardInterrupt:
            return 0
        except _urlerr.HTTPError as e:
            if e.code == 410:
                # reflector contract: the resume point aged out of the watch
                # history — RELIST (re-anchor on fresh content) and rewatch
                entries, rv = snapshot()
                last = emit_after(entries, last)
                continue
            print("error: log stream closed", file=sys.stderr)
            return 1
        except (OSError, _http_client.HTTPException):
            print("error: log stream closed", file=sys.stderr)
            return 1


def cmd_explain(client: RESTClient, args) -> int:
    """kubectl explain: field documentation straight from the API types."""
    import dataclasses

    resource = resolve_resource(args.resource)
    t = RESOURCE_TO_TYPE.get(resource)
    if t is None:
        print(f"error: explain supports built-in resources only", file=sys.stderr)
        return 1
    print(f"KIND:     {getattr(t, 'kind', t.__name__)}")
    print(f"RESOURCE: {resource}\n")
    doc = (t.__doc__ or "").strip().splitlines()
    if doc:
        print(f"DESCRIPTION:\n    {doc[0]}\n")
    print("FIELDS:")
    import typing

    def resolve(cls, ftype):
        """Postponed annotations make f.type a STRING — resolve via
        get_type_hints and unwrap Optional/List/Dict to find a dataclass."""
        try:
            hints = typing.get_type_hints(cls)
        except Exception:
            return None
        hint = hints.get(ftype)
        if dataclasses.is_dataclass(hint):
            return hint
        for arg in typing.get_args(hint):
            if dataclasses.is_dataclass(arg):
                return arg
        return None

    def walk(cls, indent):
        for f in dataclasses.fields(cls):
            tname = getattr(f.type, "__name__", str(f.type))
            print(f"{' ' * indent}{f.name}\t<{tname}>")
            sub = resolve(cls, f.name)
            if sub is not None and indent < 6:
                walk(sub, indent + 3)

    if dataclasses.is_dataclass(t):
        walk(t, 3)
    return 0


def cmd_scale(client: RESTClient, args) -> int:
    resource = resolve_resource(args.resource)
    ns = args.namespace or "default"
    obj = client.get(resource, args.name, ns)
    obj["spec"]["replicas"] = args.replicas
    client.update(resource, obj, ns)
    print(f"{resource}/{args.name} scaled to {args.replicas}")
    return 0


def _patch_node(client: RESTClient, name: str, mutate) -> Dict:
    node = client.get("nodes", name, None)
    mutate(node)
    return client.update("nodes", node, None)


def cmd_cordon(client: RESTClient, args) -> int:
    _patch_node(client, args.name, lambda n: n.setdefault("spec", {}).__setitem__("unschedulable", True))
    print(f"node/{args.name} cordoned")
    return 0


def cmd_uncordon(client: RESTClient, args) -> int:
    _patch_node(client, args.name, lambda n: n.setdefault("spec", {}).__setitem__("unschedulable", False))
    print(f"node/{args.name} uncordoned")
    return 0


def cmd_taint(client: RESTClient, args) -> int:
    # ktl taint nodes NAME key=value:Effect  (key:Effect- to remove)
    spec = args.taint
    removing = spec.endswith("-")
    spec = spec.rstrip("-")
    if "=" in spec:
        key, rest = spec.split("=", 1)
        value, _, effect = rest.partition(":")
    else:
        key, _, effect = spec.partition(":")
        value = ""

    def mutate(n):
        taints = n.setdefault("spec", {}).setdefault("taints", [])
        taints[:] = [t for t in taints if not (t["key"] == key and t.get("effect") == effect)]
        if not removing:
            taints.append({"key": key, **({"value": value} if value else {}), "effect": effect})

    _patch_node(client, args.name, mutate)
    print(f"node/{args.name} {'untainted' if removing else 'tainted'}")
    return 0


def cmd_drain(client: RESTClient, args) -> int:
    """cordon + PDB-respecting evictions (kubectl drain uses the eviction
    subresource, never raw deletes)."""
    cmd_cordon(client, args)
    rc = 0
    pods, _ = client.list("pods")
    for p in pods:
        if (p.get("spec") or {}).get("nodeName") == args.name:
            ns = p["metadata"].get("namespace") or "default"
            pname = p["metadata"]["name"]
            if any(r.get("kind") == "DaemonSet"
                   for r in p["metadata"].get("ownerReferences", [])):
                # daemon pods tolerate the unschedulable taint and would be
                # recreated immediately (kubectl drain's --ignore-daemonsets)
                print(f"ignoring DaemonSet-managed pod/{pname}")
                continue
            try:
                client.evict(pname, ns)
                print(f"pod/{pname} evicted")
            except APIError as e:
                if e.code == 429:
                    print(f"error: cannot evict pod/{pname}: {e}",
                          file=sys.stderr)
                    rc = 1
                elif e.code == 404:
                    continue  # already gone between list and evict
                else:
                    raise
    return rc


def _fmt_kv(d, sep=",") -> str:
    return sep.join(f"{k}={v}" for k, v in sorted(d.items()))


def _describe_pod(obj) -> None:
    """kubectl describe pod's section layout (describe/describe.go)."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    print(f"Name:         {meta.get('name', '')}")
    print(f"Namespace:    {meta.get('namespace', '')}")
    print(f"Node:         {spec.get('nodeName') or '<none>'}")
    print(f"Status:       {status.get('phase', '')}")
    if spec.get("priority") or spec.get("priorityClassName"):
        line = f"Priority:     {spec.get('priority', 0)}"
        if spec.get("priorityClassName"):
            line += f" ({spec['priorityClassName']})"
        print(line)
    if meta.get("labels"):
        print("Labels:       " + _fmt_kv(meta["labels"]))
    print("Containers:")
    for c in spec.get("containers", []):
        print(f"  {c.get('name', '')}:")
        print(f"    Image:    {c.get('image') or '<none>'}")
        req = (c.get("resources") or {}).get("requests") or {}
        if req:
            print("    Requests: " + _fmt_kv(req, sep=", "))
        for e in c.get("env", []):
            if "value" in e:
                val = e["value"]
            elif e.get("valueFrom"):
                val = "<set via valueFrom>"
            else:
                val = ""  # k8s semantics: unset value = empty string
            print(f"    Env:      {e.get('name', '')}={val}")
    if spec.get("tolerations"):
        print("Tolerations:  " + "; ".join(
            f"{t.get('key', '')}:{t.get('effect', '')}"
            for t in spec["tolerations"]))
    conds = status.get("conditions") or []
    if conds:
        print("Conditions:")
        for c in conds:
            line = f"  {c.get('type', '')}={c.get('status', '')}"
            if c.get("reason"):
                line += f" ({c['reason']})"
            print(line)


def _describe_node(obj) -> None:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    print(f"Name:          {meta.get('name', '')}")
    if meta.get("labels"):
        print("Labels:        " + _fmt_kv(meta["labels"]))
    print(f"Unschedulable: {spec.get('unschedulable', False)}")
    for t in spec.get("taints", []):
        print(f"Taint:         {t.get('key', '')}="
              f"{t.get('value', '')}:{t.get('effect', '')}")
    for section in ("capacity", "allocatable"):
        vals = status.get(section) or {}
        if vals:
            print(f"{section.capitalize() + ':':<15}" + _fmt_kv(vals, sep=", "))
    conds = status.get("conditions") or []
    if conds:
        print("Conditions:")
        for c in conds:
            print(f"  {c.get('type', '')}={c.get('status', '')}")
    pinned = (meta.get("annotations") or {}).get(
        "cpumanager.kubernetes-tpu.io/assignments")
    if pinned:
        try:
            assignments = json.loads(pinned)
        except ValueError:
            assignments = None
        if assignments:
            print("CPU Manager (static policy, exclusive CPUs):")
            for pod_key, containers in sorted(assignments.items()):
                for cname, cpus in sorted(containers.items()):
                    print(f"  {pod_key}/{cname}: "
                          f"{','.join(str(c) for c in cpus)}")


def cmd_describe(client: RESTClient, args) -> int:
    resource = resolve_resource(args.resource)
    ns = None if resource in CLUSTER_SCOPED else (args.namespace or "default")
    obj = client.get(resource, args.name, ns)
    if resource == "pods":
        _describe_pod(obj)
    elif resource == "nodes":
        _describe_node(obj)
    else:
        _print_yaml(obj)
    # Events: section (kubectl describe's tail)
    try:
        kind = obj.get("kind", "")
        evs, _ = client.list("events", ns or "default")
        mine = [e for e in evs
                if (e.get("involvedObject") or {}).get("kind") == kind
                and (e.get("involvedObject") or {}).get("name") == args.name]
        if mine:
            print("\nEvents:")
            rows = [[e.get("type", ""), e.get("reason", ""),
                     f'x{e.get("count", 1)}', e.get("message", "")[:90]]
                    for e in sorted(mine, key=lambda e: e.get("lastTimestamp", 0))]
            print(fmt_table(["TYPE", "REASON", "COUNT", "MESSAGE"], rows))
    except APIError:
        pass
    return 0


def _parse_kv_args(pairs: List[str]):
    """key=value -> set; key- -> delete (kubectl label/annotate syntax)."""
    sets, dels = {}, []
    for p in pairs:
        if p.endswith("-") and "=" not in p:
            dels.append(p[:-1])
        elif "=" in p:
            k, _, v = p.partition("=")
            sets[k] = v
        else:
            raise SystemExit(f"error: bad key=value pair {p!r}")
    return sets, dels


def _meta_patch_cmd(client: RESTClient, args, field: str) -> int:
    """Shared label/annotate implementation: a merge PATCH on metadata."""
    resource = resolve_resource(args.resource)
    ns = None if resource in CLUSTER_SCOPED else (args.namespace or "default")
    sets, dels = _parse_kv_args(args.pairs)
    patch = {"metadata": {field: {**sets, **{k: None for k in dels}}}}
    client.patch(resource, args.name, patch, ns)
    print(f"{resource}/{args.name} {field[:-1]}ed" if field.endswith("s")
          else f"{resource}/{args.name} updated")
    return 0


def cmd_label(client: RESTClient, args) -> int:
    """kubectl label (kubectl/pkg/cmd/label)."""
    return _meta_patch_cmd(client, args, "labels")


def cmd_annotate(client: RESTClient, args) -> int:
    """kubectl annotate (kubectl/pkg/cmd/annotate)."""
    return _meta_patch_cmd(client, args, "annotations")


def cmd_patch(client: RESTClient, args) -> int:
    """kubectl patch -p '{...}' (kubectl/pkg/cmd/patch)."""
    resource = resolve_resource(args.resource)
    ns = None if resource in CLUSTER_SCOPED else (args.namespace or "default")
    client.patch(resource, args.name, json.loads(args.patch), ns)
    print(f"{resource}/{args.name} patched")
    return 0


def _split_typed_name(arg: str, default_resource: str) -> (str, str):
    if "/" in arg:
        kind, _, name = arg.partition("/")
        return resolve_resource(kind), name
    return default_resource, arg


def cmd_rollout(client: RESTClient, args) -> int:
    """kubectl rollout status|restart (kubectl/pkg/cmd/rollout)."""
    resource, name = _split_typed_name(args.target, "deployments")
    ns = args.namespace or "default"
    if args.action == "status":
        import time

        deadline = time.time() + args.timeout
        while True:
            d = client.get(resource, name, ns)
            spec = d.get("spec") or {}
            st = d.get("status") or {}
            want = int(spec.get("replicas", 1))
            updated = int(st.get("updatedReplicas", 0))
            ready = int(st.get("readyReplicas", 0))
            if updated >= want and ready >= want:
                print(f'{resource} "{name}" successfully rolled out')
                return 0
            if time.time() > deadline:
                print(f"error: timed out waiting for rollout "
                      f"({updated}/{want} updated, {ready}/{want} ready)",
                      file=sys.stderr)
                return 1
            time.sleep(0.2)
    if args.action == "restart":
        import time

        client.patch(resource, name, {"spec": {"template": {"metadata": {
            "annotations": {"kubectl.kubernetes.io/restartedAt": str(time.time())}}}}},
            ns)
        print(f"{resource}/{name} restarted")
        return 0
    if args.action in ("history", "undo"):
        dep = client.get(resource, name, ns)
        dep_uid = dep["metadata"].get("uid", "")
        rses, _ = client.list("replicasets", ns)
        owned = [rs for rs in rses
                 if any(ref.get("kind") == "Deployment"
                        and ref.get("uid") == dep_uid
                        for ref in rs["metadata"].get("ownerReferences", []))]
        rev_key = "deployment.kubernetes.io/revision"

        def rev(rs):
            try:
                return int(rs["metadata"].get("annotations", {}).get(rev_key, 0))
            except ValueError:
                return 0

        owned.sort(key=rev)
        if args.action == "history":
            print(fmt_table(
                ["REVISION", "REPLICASET", "REPLICAS"],
                [[str(rev(rs)), rs["metadata"]["name"],
                  str((rs.get("spec") or {}).get("replicas", 0))]
                 for rs in owned]))
            return 0
        # undo: previous revision by default, or --to-revision
        if not owned:
            print("error: no rollout history", file=sys.stderr)
            return 1
        if args.to_revision:
            targets = [rs for rs in owned if rev(rs) == args.to_revision]
            if not targets:
                print(f"error: revision {args.to_revision} not found",
                      file=sys.stderr)
                return 1
            target = targets[0]
        else:
            if len(owned) < 2:
                print("error: no previous revision to roll back to",
                      file=sys.stderr)
                return 1
            target = owned[-2]  # current revision is the max
        template = (target.get("spec") or {}).get("template") or {}
        labels = ((template.get("metadata") or {}).get("labels") or {})
        labels.pop("pod-template-hash", None)
        # full PUT, not merge patch: the old template must REPLACE the
        # current one wholesale — a merge cannot remove map keys (labels,
        # nodeSelector, ...) that newer revisions added, which would hash to
        # a third template instead of re-activating the target RS
        dep["spec"]["template"] = template
        client.update(resource, dep, ns)
        print(f"{resource}/{name} rolled back to revision {rev(target)}")
        return 0
    print(f"error: unknown rollout action {args.action!r}", file=sys.stderr)
    return 1


def cmd_set_image(client: RESTClient, args) -> int:
    """kubectl set image deployment/NAME container=image (kubectl/pkg/cmd/set)."""
    resource, name = _split_typed_name(args.target, "deployments")
    ns = args.namespace or "default"
    obj = client.get(resource, name, ns)
    changed = False
    spec = obj.get("spec") or {}
    tmpl = (spec.get("template") or {}).get("spec") or spec
    containers = tmpl.get("containers") or []
    for pair in args.images:
        cname, _, image = pair.partition("=")
        for c in containers:
            if c.get("name") == cname or cname == "*":
                c["image"] = image
                changed = True
    if not changed:
        print("error: no matching container", file=sys.stderr)
        return 1
    client.update(resource, obj, ns)
    print(f"{resource}/{name} image updated")
    return 0


def cmd_top(client: RESTClient, args) -> int:
    """kubectl top nodes|pods — requested/allocatable from the API objects
    (no metrics-server; utilization = scheduled requests, the quantity the
    scheduler actually balances)."""
    from ..api.resources import quantity_milli_value, quantity_value

    pods, _ = client.list("pods")
    if args.what in ("nodes", "node", "no"):
        nodes, _ = client.list("nodes")
        rows = []
        for n in nodes:
            name = n["metadata"]["name"]
            alloc = (n.get("status") or {}).get("allocatable") or {}
            cpu_alloc = quantity_milli_value(alloc.get("cpu", "0"))
            mem_alloc = quantity_value(alloc.get("memory", "0"))
            cpu_req = mem_req = 0
            for p in pods:
                if (p.get("spec") or {}).get("nodeName") != name:
                    continue
                for c in (p["spec"].get("containers") or []):
                    req = ((c.get("resources") or {}).get("requests") or {})
                    cpu_req += quantity_milli_value(req.get("cpu", "0"))
                    mem_req += quantity_value(req.get("memory", "0"))
            rows.append([
                name, f"{cpu_req}m",
                f"{cpu_req * 100 // max(cpu_alloc, 1)}%",
                f"{mem_req // (1024 * 1024)}Mi",
                f"{mem_req * 100 // max(mem_alloc, 1)}%",
            ])
        print(fmt_table(["NAME", "CPU(requests)", "CPU%", "MEMORY(requests)",
                         "MEMORY%"], rows))
        return 0
    ns = args.namespace or "default"
    rows = []
    for p in pods:
        meta = p["metadata"]
        if meta.get("namespace", "default") != ns:
            continue
        cpu = mem = 0
        for c in (p["spec"].get("containers") or []):
            req = ((c.get("resources") or {}).get("requests") or {})
            cpu += quantity_milli_value(req.get("cpu", "0"))
            mem += quantity_value(req.get("memory", "0"))
        rows.append([meta["name"], f"{cpu}m", f"{mem // (1024 * 1024)}Mi"])
    print(fmt_table(["NAME", "CPU(requests)", "MEMORY(requests)"], rows))
    return 0


def _render_sched_stats(doc: Dict) -> str:
    """The live stage table of every registered batch scheduler: counters
    header + a per-stage TOTAL/MEAN/BATCHES table (the flight recorder's
    aggregate view; overlapped stages — the bind worker — are marked so the
    serial rows still explain wall time)."""
    if not doc:
        return ("no batch scheduler registered in the server process "
                "(is the control plane running in-process?)")
    out = []
    for name, st in sorted(doc.items()):
        if "error" in st and len(st) == 1:
            out.append(f"{name}: error: {st['error']}")
            continue
        q = st.get("queue") or {}
        rec = st.get("recorder") or {}
        out.append(
            f"{name}  solver={st.get('solver')} "
            f"batches={st.get('batches_solved', 0)} "
            f"scheduled={st.get('scheduled', 0)} "
            f"failed={st.get('failed', 0)} "
            f"preemptions={st.get('preemptions', 0)}")
        out.append(
            f"queue: active={q.get('active', 0)} "
            f"backoff={q.get('backoff', 0)} "
            f"unschedulable={q.get('unschedulable', 0)} "
            f"gang_staged={q.get('gang_staged', 0)} "
            f"oldest_age={q.get('oldest_pending_age_s', 0.0):.1f}s   "
            f"recorder: {'on' if rec.get('enabled') else 'off'} "
            f"{rec.get('records', 0)}/{rec.get('capacity', 0)} batches")
        tb = st.get("tracebuf") or {}
        out.append(
            f"trace: {'armed' if tb.get('armed') else 'disarmed'} "
            f"events={tb.get('trace_events_total', 0)} "
            f"dropped={tb.get('trace_events_dropped_total', 0)}")
        lat = st.get("latency") or {}
        if lat.get("count"):
            out.append(
                f"submit->bound: count={lat['count']} "
                f"mean={lat.get('mean_s', 0) or 0:.3f}s "
                f"p50={lat.get('p50_s', 0) or 0:.3f}s "
                f"p99={lat.get('p99_s', 0) or 0:.3f}s")
        gang = st.get("gang")
        if gang:
            out.append(
                f"gang: staged={gang.get('staged', 0)} "
                f"parked={gang.get('parked', 0)} "
                f"vetoes={gang.get('vetoes', 0)} "
                f"quorum_expired_assumes="
                f"{gang.get('quorum_expired_assumes', 0)}")
            gp = gang.get("preemption")
            if gp and (gp.get("attempts") or gp.get("preempted")):
                out.append(
                    f"gang preemption: attempts={gp.get('attempts', 0)} "
                    f"preempted={gp.get('preempted', 0)} "
                    f"victims={gp.get('victims', 0)} "
                    f"cover_cost={gp.get('cover_cost', 0)} "
                    f"slices_ripped={gp.get('slices_ripped', 0)} "
                    f"vetoed_partial={gp.get('vetoed_partial', 0)}")
        rb = st.get("rebalance")
        if rb:
            # background rebalancer (ISSUE 17): frag score + bounded
            # migration totals; rendered only once enable_rebalancer() ran
            out.append(
                f"rebalance: cycles={rb.get('cycles', 0)} "
                f"noop={rb.get('noop_cycles', 0)} "
                f"plans={rb.get('plans', 0)} "
                f"migrations={rb.get('migrations', 0)} "
                f"waves={rb.get('waves', 0)} "
                f"aborts={rb.get('slo_aborts', 0)}s/"
                f"{rb.get('fault_aborts', 0)}f "
                f"frag={rb.get('last_frag', 0.0):.3f}")
        rep = st.get("repair")
        if rep:
            last = rep.get("last") or {}
            out.append(
                f"constraint repair: batches={rep.get('batches', 0)} "
                f"rounds={rep.get('rounds', 0)} "
                f"residual={rep.get('residual', 0)} "
                f"full_scan={rep.get('full_scan', 0)} "
                f"violations={rep.get('violations', 0)}"
                + (f"   last: proposed={last.get('proposed', 0)} "
                   f"rounds={last.get('rounds', 0)} "
                   f"residual={last.get('residual', 0)}" if last else ""))
        watch = st.get("watch") or {}
        prop = watch.get("propagation") or {}
        if prop.get("count"):
            # watch-propagation line (ISSUE 9): commit->dequeue latency of
            # the store's watch bus plus the worst subscriber RV lag
            out.append(
                f"watch bus: subscribers={watch.get('subscribers', 0)} "
                f"max_rv_lag={watch.get('max_rv_lag', 0)} "
                f"propagation p50={prop.get('p50_s', 0) or 0:.4f}s "
                f"p99={prop.get('p99_s', 0) or 0:.4f}s "
                f"over {prop['count']} deliveries"
                + (f" dropped={watch.get('dropped')}"
                   if watch.get("dropped") else ""))
        part = st.get("partition")
        if part:
            # partitioned mode (ISSUE 12): this scheduler is one pipeline of
            # a PartitionedScheduler — its shard + the dispatch layer's
            # absorbed races. index -1 is the global residual pass.
            out.append(
                f"partition: index={part.get('index')} "
                f"nodes={part.get('nodes', 0)} "
                f"conflicts={part.get('conflicts', 0)} "
                f"reroutes={part.get('reroutes', 0)}")
        procs = st.get("processes")
        if procs:
            # multi-process mode (ISSUE 19): owner arbitration counters +
            # one row per worker process; thread mode shows the fallback
            # reason so a 1-core rig's "why no processes?" is answerable
            if procs.get("mode") != "mp":
                out.append(
                    f"processes: mode=thread configured="
                    f"{procs.get('configured')} "
                    f"fallback={procs.get('fallback')}")
            else:
                res = procs.get("residual") or {}
                out.append(
                    f"processes: mode=mp n={procs.get('configured')} "
                    f"rounds={procs.get('rounds', 0)} "
                    f"stale_intents={procs.get('stale_intents', 0)} "
                    f"bind_conflicts={procs.get('bind_conflicts', 0)} "
                    f"restarts={procs.get('worker_restarts', 0)} "
                    f"faults={procs.get('dispatch_faults', 0)} "
                    f"cpu={procs.get('worker_cpu_s', 0.0):.2f}s "
                    f"residual={res.get('scheduled', 0)}sched/"
                    f"{res.get('parked', 0)}parked")
                wrows = [[str(w.get("index")), str(w.get("pid")),
                          str(w.get("state")), str(w.get("binds", 0)),
                          str(w.get("conflicts", 0)),
                          str(w.get("restarts", 0)),
                          str(w.get("faults", 0))]
                         for w in (procs.get("workers") or [])]
                if wrows:
                    out.append(fmt_table(
                        ["WORKER", "PID", "STATE", "BINDS", "CONFLICTS",
                         "RESTARTS", "FAULTS"], wrows))
        cols = st.get("store_columnar")
        if cols:
            # columnar pod-row store (ISSUE 15): diverged = rows whose bind
            # lives in the columns only; materialized = lazy reconciliations
            out.append(
                f"store columnar: rows={cols.get('rows', 0)} "
                f"bound={cols.get('bound', 0)} "
                f"diverged={cols.get('diverged', 0)} "
                f"materialized={cols.get('materialized_total', 0)} "
                f"nodes_interned={cols.get('node_table', 0)}")
        brk = st.get("breaker")
        bw = st.get("bind_worker")
        if brk and (brk.get("state") != "closed" or brk.get("trips")
                    or (bw or {}).get("restarts")
                    or (bw or {}).get("failures_dropped")):
            # failure domains: shown only when something actually happened
            out.append(
                f"breaker: {brk.get('state')} trips={brk.get('trips', 0)} "
                f"recoveries={brk.get('recoveries', 0)}   "
                f"bind worker: restarts={(bw or {}).get('restarts', 0)} "
                f"failures_dropped={(bw or {}).get('failures_dropped', 0)}")
        stages = st.get("stages") or {}
        if stages:
            last = (st.get("last_batch") or {}).get("stages") or {}
            rows = []
            for stage, row in stages.items():
                mean = row.get("mean_ms")
                p50 = row.get("p50_ms")
                p99 = row.get("p99_ms")
                rows.append([
                    stage + (" *" if row.get("overlapped") else ""),
                    f"{row.get('total_ms', 0):.1f}",
                    f"{mean:.2f}" if mean is not None else "-",
                    f"{p50:.2f}" if p50 is not None else "-",
                    f"{p99:.2f}" if p99 is not None else "-",
                    f"{last[stage]:.2f}" if stage in last else "-",
                    str(row.get("batches", 0)),
                ])
            out.append(fmt_table(
                ["STAGE", "TOTAL(ms)", "MEAN(ms)", "P50(ms)", "P99(ms)",
                 "LAST(ms)", "BATCHES"],
                rows))
            out.append("(* overlapped with the scheduling thread)")
        else:
            out.append("no batches recorded yet")
        out.append("")
    return "\n".join(out).rstrip()


def _render_sched_why(doc: Dict) -> str:
    """Critical-path attribution (ISSUE 18): per scheduler, the per-window
    dominant submit->bound component with its share, the component p50/p99
    table, and the additivity check (sum of parts vs measured total)."""
    if not doc:
        return ("no batch scheduler registered in the server process "
                "(is the control plane running in-process?)")
    out = []
    for name, cp in sorted(doc.items()):
        if "error" in cp and len(cp) == 1:
            out.append(f"{name}: error: {cp['error']}")
            continue
        overall = cp.get("overall")
        out.append(
            f"{name}  spans={cp.get('spans_analyzed', 0)} "
            f"build_ratio={cp.get('build_ratio', 0.0)}")
        if not overall:
            out.append("  no bound sampled spans yet")
            continue
        rows = [("window", "n", "dominant", "share", "sum_p50", "total_p50",
                 "sum_p99", "total_p99")]
        for w, roll in sorted((cp.get("windows") or {}).items(),
                              key=lambda kv: int(kv[0])):
            share = roll.get("dominant_share")
            rows.append((str(w), str(roll.get("count", 0)),
                         str(roll.get("dominant")),
                         f"{share:.0%}" if share is not None else "-",
                         f"{roll.get('sum_p50_ms', 0)}ms",
                         f"{roll.get('total_p50_ms', 0)}ms",
                         f"{roll.get('sum_p99_ms', 0)}ms",
                         f"{roll.get('total_p99_ms', 0)}ms"))
        widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
        for r in rows:
            out.append("  " + "  ".join(
                c.ljust(widths[i]) for i, c in enumerate(r)).rstrip())
        share = overall.get("dominant_share")
        out.append(
            f"overall: dominant={overall.get('dominant')} "
            + (f"({share:.0%} of submit->bound) " if share is not None
               else "")
            + f"p50 {overall.get('total_p50_ms', 0)}ms "
            f"p99 {overall.get('total_p99_ms', 0)}ms")
        comps = overall.get("components") or {}
        for comp, row in comps.items():
            out.append(
                f"  {comp:<10} p50={row.get('p50_ms', 0)}ms "
                f"p99={row.get('p99_ms', 0)}ms "
                f"mean={row.get('mean_ms', 0)}ms"
                + ("  (post-bound, not in sum)" if comp == "watch" else ""))
    return "\n".join(out)


def _render_sched_trace(doc: Dict) -> str:
    """Sampled pod lifecycle spans (scheduler/podtrace.py): per scheduler, a
    window/latency header plus one row per span with the per-edge offsets —
    where each sampled pod's milliseconds went, submit to bound."""
    if not doc:
        return ("no batch scheduler registered in the server process "
                "(is the control plane running in-process?)")
    out = []
    for name, tr in sorted(doc.items()):
        if "error" in tr and len(tr) == 1:
            out.append(f"{name}: error: {tr['error']}")
            continue
        lat = tr.get("latency") or {}
        out.append(
            f"{name}  tracer={'on' if tr.get('enabled') else 'off'} "
            f"sample_k={tr.get('sample_k')} "
            f"windows={tr.get('windows_rotated', 0)} "
            f"completed={tr.get('completed', 0)} "
            f"live={tr.get('live_incomplete', 0)} "
            f"evicted={tr.get('evicted_incomplete', 0)}")
        if lat.get("count"):
            out.append(
                f"submit->bound (ALL pods): count={lat['count']} "
                f"p50={lat.get('p50_s', 0) or 0:.3f}s "
                f"p99={lat.get('p99_s', 0) or 0:.3f}s")
        spans = tr.get("spans") or []
        if spans:
            rows = []
            for sp in spans[-40:]:  # newest spans; -o json has everything
                st = sp.get("stamps_ms") or {}
                rows.append([
                    sp.get("pod", "?"),
                    "yes" if sp.get("complete") else "no",
                    str(sp.get("pops", 0)),
                ] + [f"{st[k]:.1f}" if k in st else "-"
                     for k in ("pop", "solve", "assume", "dispatch",
                               "bind_commit", "bind_confirmed")])
            out.append(fmt_table(
                ["POD", "DONE", "POPS", "POP", "SOLVE", "ASSUME", "DISPATCH",
                 "COMMIT", "CONFIRMED"], rows))
            out.append("(per-edge offsets in ms since queue admission; "
                       "last 40 spans — use -o json for all)")
        else:
            out.append("no sampled spans yet")
        out.append("")
    return "\n".join(out).rstrip()


def _render_sched_top(doc: Dict) -> str:
    """The steady-state dashboard (ISSUE 13): per scheduler, the resource
    sampler's header line (RSS / live objects / GC pauses / per-thread CPU
    with the clock honesty flag) and one row per recent window — batches,
    pods/s, key stage p99s, queue depth, breaker state, RSS."""
    if not doc:
        return ("no batch scheduler registered in the server process "
                "(is the control plane running in-process?)")
    import datetime as _dt

    out = []
    for name, st in sorted(doc.items()):
        if "error" in st and len(st) == 1:
            out.append(f"{name}: error: {st['error']}")
            continue
        out.append(f"{name}  window={st.get('window_s')}s "
                   f"closed={st.get('windows_closed', 0)} "
                   f"capacity={st.get('capacity', 0)}")
        res = st.get("resource")
        if res:
            cpu = "  ".join(f"{k}={v:.2f}s" for k, v in sorted(
                (res.get("thread_cpu_s") or {}).items()))
            resolution = res.get("clock_resolution_s")
            out.append(
                f"resource: rss={res.get('rss_mb')}MB "
                f"(+{res.get('rss_growth_mb')}) "
                f"alloc_blocks={res.get('alloc_blocks')} "
                f"(+{res.get('alloc_growth_blocks')}) "
                f"gc_pause={res.get('gc', {}).get('pause_s', 0)}s "
                f"overlap_cpu={res.get('overlap_cpu_s')}s"
                + (f"  cpu: {cpu}" if cpu else "")
                + f"  [clock={res.get('clock_source')}"
                + (f" tick={resolution * 1e6:.1f}us"
                   if resolution is not None else "")
                + f" overhead={res.get('overhead_frac', 0):.2%}]")
        windows = st.get("windows") or []
        if not windows:
            out.append("no closed windows yet")
            out.append("")
            continue
        rows = []
        for w in windows[-14:]:
            stages = w.get("stages") or {}

            def p99(stage):
                v = (stages.get(stage) or {}).get("p99_ms")
                return f"{v:.1f}" if v is not None else "-"

            q = w.get("queue") or {}
            r = w.get("resource") or {}
            al = w.get("alloc") or {}
            when = (_dt.datetime.fromtimestamp(w["ts"]).strftime("%H:%M:%S")
                    if "ts" in w else "-")
            rows.append([
                str(w.get("seq", "-")), when,
                str(w.get("batches", 0)),
                f"{w.get('pods_per_sec', 0):.0f}",
                p99("solve"), p99("assume"), p99("bind"),
                str(q.get("active", "-")),
                str(q.get("backoff", "-")),
                # the live zero-alloc gauge (ISSUE 16): per-window pod-object
                # materializations across store + cache columnar tables; 0 is
                # the end-to-end columnar steady state
                str(al.get("pod_obj_allocs", "-")),
                (w.get("breaker") or {}).get("state", "-"),
                (f"{r['rss_mb']:.1f}" if "rss_mb" in r else "-"),
            ])
        rows.reverse()  # newest first: the dashboard reads top-down
        out.append(fmt_table(
            ["WIN", "TIME", "BATCHES", "PODS/S", "SOLVE(p99ms)",
             "ASSUME(p99ms)", "BIND(p99ms)", "ACTIVE", "BACKOFF", "ALLOCS",
             "BREAKER", "RSS(MB)"], rows))
        out.append("(newest window first; use -o json for every column)")
        out.append("")
    return "\n".join(out).rstrip()


def _render_sched_slo(results: Dict) -> str:
    """Per-scheduler SLO verdicts: one PASS/FAIL/SKIP row per check."""
    out = []
    for name, res in sorted(results.items()):
        verdict = "PASS" if res["pass"] else "FAIL"
        out.append(f"{name}: {verdict} "
                   f"({len(res['failed'])} failed, "
                   f"{len(res['skipped'])} skipped)")
        rows = []
        for c in res["checks"]:
            state = ("SKIP" if c["ok"] is None
                     else "PASS" if c["ok"] else "FAIL")
            rows.append([c["name"], str(c["limit"]),
                         "-" if c["actual"] is None else str(c["actual"]),
                         state])
        out.append(fmt_table(["CHECK", "CEILING", "ACTUAL", "STATE"], rows))
        out.append("")
    return "\n".join(out).rstrip()


def cmd_sched(client: RESTClient, args) -> int:
    """ktl sched stats|trace|slo — the batched solver's observability family
    (flight recorder stage table, sampled lifecycle spans, SLO verdicts)
    served from /debug/schedstats and /debug/schedtrace (the kubectl-less
    sibling of `kubectl get --raw /debug/...`)."""
    import time as _time

    if args.action not in ("stats", "trace", "slo", "top", "why"):
        raise CLIError(f"unknown sched action {args.action!r}")
    spec = None
    if args.action == "slo":
        from ..scheduler.slo import DEFAULT_SLO, load_slo_spec

        spec = load_slo_spec(args.spec) if args.spec else DEFAULT_SLO
    if args.action == "trace" and getattr(args, "export", None):
        # unified trace timeline (ISSUE 18): dump the Perfetto-loadable
        # Chrome trace-event JSON; one shot, no watch loop
        doc = client.request("GET", "/debug/trace")
        n = len(doc.get("traceEvents") or [])
        with open(args.export, "w") as f:
            json.dump(doc, f)
        print(f"wrote {n} trace events to {args.export} "
              "(open in https://ui.perfetto.dev)")
        return 0 if n else 1
    # -w/--watch applies to every action (the parser registers it for all
    # three); non-watch mode returns after one fetch with the action's exit
    # code (slo: 1 on any FAIL)
    while True:
        if args.action == "trace":
            doc = client.request("GET", "/debug/schedtrace")
            rendered = (json.dumps(doc, indent=2) if args.output == "json"
                        else _render_sched_trace(doc))
            rc = 0
        elif args.action == "top":
            # the steady-state dashboard (ISSUE 13): windowed time-series +
            # resource sampler, served from /debug/timeseries. `-w` polls
            # the debug endpoint — and any event-stream dashboards ride
            # ring=true subscriptions (client.watch), never the
            # terminate-relist contract
            doc = client.request("GET", "/debug/timeseries")
            rendered = (json.dumps(doc, indent=2) if args.output == "json"
                        else _render_sched_top(doc))
            rc = 0
        elif args.action == "why":
            # critical-path attribution (ISSUE 18): which component owns
            # the sampled submit->bound latency, per window
            doc = client.request("GET", "/debug/critpath")
            rendered = (json.dumps(doc, indent=2) if args.output == "json"
                        else _render_sched_why(doc))
            rc = 0
        elif args.action == "slo":
            from ..scheduler.slo import evaluate_slo

            doc = client.request("GET", "/debug/schedstats")
            if not doc:
                print("no batch scheduler registered in the server process",
                      file=sys.stderr)
                return 1
            results = {}
            for name, st in doc.items():
                if "error" in st and len(st) == 1:
                    # a scheduler whose sched_stats() raised is a FAILING
                    # verdict, not an absent one — the spec's "unavailable
                    # datum never silently passes" rule applies to the whole
                    # snapshot too
                    results[name] = {
                        "pass": False, "failed": ["schedstats_error"],
                        "skipped": [], "checks": [{
                            "name": "schedstats_error", "limit": None,
                            "actual": st["error"], "ok": False}]}
                else:
                    results[name] = evaluate_slo(st, spec)
            rendered = (json.dumps(results, indent=2)
                        if args.output == "json"
                        else _render_sched_slo(results))
            rc = 0 if all(r["pass"] for r in results.values()) else 1
        else:
            doc = client.request("GET", "/debug/schedstats")
            rendered = (json.dumps(doc, indent=2) if args.output == "json"
                        else _render_sched_stats(doc))
            rc = 0
        if args.watch and args.output != "json":
            # ANSI clear+home, like `watch`: live-updating table
            sys.stdout.write("\x1b[2J\x1b[H")
        print(rendered)
        if not args.watch:
            return rc
        sys.stdout.flush()
        _time.sleep(args.interval)


def _render_controller_stats(doc: Dict) -> str:
    """The control-plane flight recorder (ISSUE 9): one row per live
    controller (loops/keys/errors/depth + sync p50/p99), the cross-
    controller reconcile rollup, and the server store's watch-bus
    propagation/lag summary."""
    ctrls = doc.get("controllers") or {}
    out = []
    roll = doc.get("reconcile") or {}
    if roll:
        p99 = roll.get("p99_ms")
        out.append(
            f"reconcile: controllers={roll.get('controllers', 0)} "
            f"loops={roll.get('loops', 0)} keys={roll.get('keys', 0)} "
            f"errors={roll.get('errors', 0)} "
            f"worst_p99={p99 if p99 is not None else '-'}ms"
            + (f" ({roll.get('worst_controller')})"
               if roll.get("worst_controller") else ""))
    watch = doc.get("watch") or {}
    prop = watch.get("propagation") or {}
    if prop.get("count"):
        subs = watch.get("subscribers") or []
        max_lag = max((s.get("rv_lag", 0) for s in subs), default=0)
        out.append(
            f"watch bus: subscribers={len(subs)} max_rv_lag={max_lag} "
            f"propagation p50={prop.get('p50_s', 0) or 0:.4f}s "
            f"p99={prop.get('p99_s', 0) or 0:.4f}s "
            f"over {prop['count']} deliveries")
    if not ctrls:
        out.append("no controllers registered in the server process "
                   "(is the control plane running in-process?)")
        return "\n".join(out)
    rows = []
    for name, st in sorted(ctrls.items()):
        if "error" in st and len(st) == 1:
            rows.append([name, "error: " + str(st["error"]), "", "", "", "",
                         "", "", "", ""])
            continue
        p50 = st.get("reconcile_p50_ms")
        p99 = st.get("reconcile_p99_ms")
        rows.append([
            name,
            str(st.get("loops", 0)),
            str(st.get("keys", 0)),
            str(st.get("events", 0)),
            str(st.get("errors", 0)),
            str(st.get("requeues", 0)),
            str(st.get("depth", 0)),
            f"{st.get('oldest_dirty_age_s', 0):.1f}",
            f"{p50:.2f}" if p50 is not None else "-",
            f"{p99:.2f}" if p99 is not None else "-",
        ])
    out.append(fmt_table(
        ["CONTROLLER", "LOOPS", "KEYS", "EVENTS", "ERRORS", "REQUEUES",
         "DEPTH", "OLDEST(s)", "P50(ms)", "P99(ms)"], rows))
    return "\n".join(out).rstrip()


def cmd_controller(client: RESTClient, args) -> int:
    """ktl controller stats [-o json] [-w] — the reconcile-loop telemetry of
    every live controller, served from /debug/controlstats (the controller
    sibling of `ktl sched stats`)."""
    import time as _time

    if args.action != "stats":
        raise CLIError(f"unknown controller action {args.action!r}")
    while True:
        doc = client.request("GET", "/debug/controlstats")
        rendered = (json.dumps(doc, indent=2) if args.output == "json"
                    else _render_controller_stats(doc))
        if args.watch and args.output != "json":
            sys.stdout.write("\x1b[2J\x1b[H")
        print(rendered)
        if not args.watch:
            return 0
        sys.stdout.flush()
        _time.sleep(args.interval)


def cmd_vet(client: RESTClient, args) -> int:
    """ktl vet [-o json] [--diff [REF]] [--lock-graph] [paths...] — run
    schedlint (the project-native static analyzer, analysis/schedlint.py)
    over the tree. The `go vet` of this control plane: nonzero exit on any
    unsuppressed finding, so CI and pre-commit hooks can gate on it.
    Entirely local (no apiserver). `--diff` narrows findings to the files
    changed vs REF plus their reverse import/call dependents; `--lock-graph`
    renders the runtime lock-graph witness instead of analyzing."""
    from ..analysis import schedlint

    # delegate to the module CLI so the two entry points share one
    # output/exit-code contract (only the flag spelling differs)
    flags = ["--json"] if args.output == "json" else []
    if args.lock_graph:
        flags.append("--lock-graph")
    if args.diff is not None:
        flags.extend(["--diff", args.diff])
    return schedlint.main(flags + list(args.paths))


def cmd_wait(client: RESTClient, args) -> int:
    """kubectl wait --for=condition=X|delete (kubectl/pkg/cmd/wait)."""
    import time

    resource, name = _split_typed_name(args.target, "pods")
    ns = None if resource in CLUSTER_SCOPED else (args.namespace or "default")
    want = args.wait_for
    deadline = time.time() + args.timeout
    while True:
        try:
            obj = client.get(resource, name, ns)
        except APIError as e:
            if e.code == 404:
                if want == "delete":
                    print(f"{resource}/{name} condition met")
                    return 0
                obj = None
            else:
                raise
        if obj is not None and want.startswith("condition="):
            cond = want.split("=", 1)[1]
            conds = ((obj.get("status") or {}).get("conditions") or [])
            if any(c.get("type") == cond and c.get("status") == "True"
                   for c in conds):
                print(f"{resource}/{name} condition met")
                return 0
        if obj is not None and want.startswith("jsonpath="):
            # minimal jsonpath: {.status.phase}=Value
            expr, _, expect = want[len("jsonpath="):].partition("=")
            cur = obj
            for part in expr.strip("{}").lstrip(".").split("."):
                cur = cur.get(part) if isinstance(cur, dict) else None
            if cur is not None and str(cur) == expect:
                print(f"{resource}/{name} condition met")
                return 0
        if time.time() > deadline:
            print(f"error: timed out waiting for {want} on {resource}/{name}",
                  file=sys.stderr)
            return 1
        time.sleep(0.1)


def cmd_autoscale(client: RESTClient, args) -> int:
    """kubectl autoscale deployment NAME --min --max --cpu-percent."""
    resource, name = _split_typed_name(args.target, "deployments")
    ns = args.namespace or "default"
    client.create("horizontalpodautoscalers", {
        "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "scaleTargetRef": {"kind": "Deployment", "name": name},
            "minReplicas": args.min, "maxReplicas": args.max,
            "targetCPUUtilizationPercentage": args.cpu_percent,
        },
    }, ns)
    print(f"horizontalpodautoscaler/{name} autoscaled")
    return 0


def cmd_api_resources(client: RESTClient, args) -> int:
    try:
        doc = client.request("GET", "/apis")
        rows = [[r, e["prefix"].lstrip("/").replace("apis/", "").replace("api/", ""),
                 "true" if e.get("namespaced") else "false", e.get("kind", "")]
                for r, e in sorted((doc.get("resources") or {}).items())]
        print(fmt_table(["NAME", "APIVERSION", "NAMESPACED", "KIND"], rows))
    except APIError:
        rows = [[r, GROUP_PREFIX[r].split("/")[-2] if "apis" in GROUP_PREFIX[r] else "v1"]
                for r in sorted(RESOURCE_TO_TYPE)]
        print(fmt_table(["NAME", "APIVERSION"], rows))
    return 0


def cmd_version(client: RESTClient, args) -> int:
    out = client.request("GET", "/version")
    print(f"Client: kubernetes-tpu v0.1.0\nServer: {out.get('gitVersion', 'unknown')}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="ktl", description="kubernetes-tpu CLI")
    # clientcmd precedence: explicit flags > $KTL_SERVER > kubeconfig context
    parser.add_argument("--server", default=None)
    parser.add_argument("--token", default=None)
    parser.add_argument("-n", "--namespace", default=None)
    sub = parser.add_subparsers(dest="cmd", required=True)
    from .ktlconfig import add_config_parser

    add_config_parser(sub)

    p = sub.add_parser("get")
    p.add_argument("resource")
    p.add_argument("name", nargs="?")
    p.add_argument("-o", "--output", default="wide")  # wide|json|yaml|jsonpath={..}
    p.add_argument("-A", "--all-namespaces", action="store_true")
    p.add_argument("-l", "--selector", default="")
    p.add_argument("-w", "--watch", action="store_true")
    p.set_defaults(fn=cmd_get)

    p = sub.add_parser("describe")
    p.add_argument("resource")
    p.add_argument("name")
    p.set_defaults(fn=cmd_describe)

    p = sub.add_parser("create")
    p.add_argument("rest", nargs="*")  # e.g. configmap NAME / secret generic NAME
    p.add_argument("-f", "--filename")
    p.add_argument("--from-literal", action="append", default=[])
    p.set_defaults(fn=cmd_create)

    p = sub.add_parser("apply")
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--field-manager", default="ktl")
    p.add_argument("--force-conflicts", action="store_true")
    p.set_defaults(fn=cmd_apply)

    p = sub.add_parser("delete")
    p.add_argument("resource", nargs="?")
    p.add_argument("name", nargs="?")
    p.add_argument("-f", "--filename")
    p.add_argument("--all", action="store_true")
    p.add_argument("-l", "--selector", default="")
    p.set_defaults(fn=cmd_delete)

    p = sub.add_parser("replace")
    p.add_argument("-f", "--filename", required=True)
    p.set_defaults(fn=cmd_replace)

    p = sub.add_parser("run")
    p.add_argument("name")
    p.add_argument("--image", required=True)
    p.add_argument("--requests", default="")
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("expose")
    p.add_argument("target")  # deployment/NAME
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--target-port", type=int, default=0)
    p.add_argument("--name", dest="service_name", default="")
    p.set_defaults(fn=cmd_expose)

    p = sub.add_parser("certificate")
    p.add_argument("action", choices=["approve", "deny"])
    p.add_argument("name")
    p.set_defaults(fn=cmd_certificate)

    p = sub.add_parser("auth")
    p.add_argument("subcmd", choices=["can-i"])
    p.add_argument("verb")
    p.add_argument("resource")
    p.set_defaults(fn=cmd_auth_can_i)

    p = sub.add_parser("explain")
    p.add_argument("resource")
    p.set_defaults(fn=cmd_explain)

    p = sub.add_parser("logs")
    p.add_argument("name")
    p.add_argument("--tail", type=int, default=0)
    p.add_argument("-f", "--follow", action="store_true")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("exec")
    p.add_argument("pod")
    p.add_argument("-c", "--container", default="")
    p.add_argument("-i", "--stdin", action="store_true")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command after --")
    p.set_defaults(fn=cmd_exec)

    p = sub.add_parser("attach")
    p.add_argument("pod")
    p.add_argument("-c", "--container", default="")
    p.add_argument("-i", "--stdin", action="store_true")
    p.set_defaults(fn=cmd_attach)

    p = sub.add_parser("port-forward")
    p.add_argument("pod")
    p.add_argument("ports", help="LOCAL:REMOTE (or one port for both)")
    p.add_argument("--one-connection", action="store_true")
    p.set_defaults(fn=cmd_port_forward)

    p = sub.add_parser("cp")
    p.add_argument("src", help="pod:/path or a local file")
    p.add_argument("dst", help="pod:/path or a local file")
    p.add_argument("-c", "--container", default="")
    p.set_defaults(fn=cmd_cp)

    p = sub.add_parser("diff")
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--field-manager", default="ktl")
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser("scale")
    p.add_argument("resource")
    p.add_argument("name")
    p.add_argument("--replicas", type=int, required=True)
    p.set_defaults(fn=cmd_scale)

    for name, fn in (("cordon", cmd_cordon), ("uncordon", cmd_uncordon), ("drain", cmd_drain)):
        p = sub.add_parser(name)
        p.add_argument("name")
        p.set_defaults(fn=fn)

    p = sub.add_parser("taint")
    p.add_argument("resource_kw")  # "nodes"
    p.add_argument("name")
    p.add_argument("taint")
    p.set_defaults(fn=cmd_taint)

    for name, fn in (("label", cmd_label), ("annotate", cmd_annotate)):
        p = sub.add_parser(name)
        p.add_argument("resource")
        p.add_argument("name")
        p.add_argument("pairs", nargs="+")
        p.set_defaults(fn=fn)

    p = sub.add_parser("patch")
    p.add_argument("resource")
    p.add_argument("name")
    p.add_argument("-p", "--patch", required=True)
    p.set_defaults(fn=cmd_patch)

    p = sub.add_parser("rollout")
    p.add_argument("action", choices=["status", "restart", "history", "undo"])
    p.add_argument("target")  # deployment/NAME
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument("--to-revision", type=int, default=0)
    p.set_defaults(fn=cmd_rollout)

    p = sub.add_parser("set")
    p.add_argument("what", choices=["image"])
    p.add_argument("target")
    p.add_argument("images", nargs="+")  # container=image
    p.set_defaults(fn=cmd_set_image)

    p = sub.add_parser("top")
    p.add_argument("what", choices=["nodes", "node", "no", "pods", "pod", "po"])
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser("sched")
    p.add_argument("action", choices=["stats", "trace", "slo", "top", "why"])
    p.add_argument("-o", "--output", default="table",
                   choices=["table", "json"])
    p.add_argument("-w", "--watch", action="store_true")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--spec", default=None,
                   help="SLO spec JSON file (sched slo; default: the "
                        "built-in north-star spec)")
    p.add_argument("--export", default=None, metavar="FILE",
                   help="sched trace: write the Chrome trace-event JSON "
                        "from /debug/trace to FILE (open in "
                        "https://ui.perfetto.dev or chrome://tracing)")
    p.set_defaults(fn=cmd_sched)

    p = sub.add_parser("controller")
    p.add_argument("action", choices=["stats"])
    p.add_argument("-o", "--output", default="table",
                   choices=["table", "json"])
    p.add_argument("-w", "--watch", action="store_true")
    p.add_argument("--interval", type=float, default=2.0)
    p.set_defaults(fn=cmd_controller)

    p = sub.add_parser("vet")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to analyze (default: the package)")
    p.add_argument("-o", "--output", default="table",
                   choices=["table", "json"])
    p.add_argument("--diff", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="narrow findings to files changed vs REF (default "
                        "HEAD) plus reverse import/call dependents")
    p.add_argument("--lock-graph", action="store_true",
                   help="render the runtime lock-graph witness")
    p.set_defaults(fn=cmd_vet)

    p = sub.add_parser("wait")
    p.add_argument("target")  # [resource/]name
    p.add_argument("--for", dest="wait_for", required=True)
    p.add_argument("--timeout", type=float, default=30.0)
    p.set_defaults(fn=cmd_wait)

    p = sub.add_parser("autoscale")
    p.add_argument("target")  # deployment/NAME
    p.add_argument("--min", type=int, default=1)
    p.add_argument("--max", type=int, required=True)
    p.add_argument("--cpu-percent", type=int, default=80)
    p.set_defaults(fn=cmd_autoscale)

    p = sub.add_parser("api-resources")
    p.set_defaults(fn=cmd_api_resources)
    p = sub.add_parser("version")
    p.set_defaults(fn=cmd_version)

    args = parser.parse_args(argv)
    from .ktlconfig import resolve

    cfg_server, cfg_token, cfg_ns = resolve()
    server = (args.server or os.environ.get("KTL_SERVER")
              or cfg_server or "http://127.0.0.1:8001")
    token = args.token or cfg_token
    if args.namespace is None and cfg_ns:
        args.namespace = cfg_ns
    client = RESTClient(server, token=token, user_agent="ktl")
    try:
        return args.fn(client, args)
    except (APIError, CLIError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
