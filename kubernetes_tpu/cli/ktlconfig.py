"""ktl config — the kubeconfig analog.

reference: staging/src/k8s.io/client-go/tools/clientcmd (kubeconfig loading
precedence) and kubectl config view/set-cluster/set-credentials/set-context/
use-context. The file is JSON at $KTLCONFIG or ~/.ktl/config:

    {"clusters":  {"dev": {"server": "http://127.0.0.1:8001"}},
     "users":     {"admin": {"token": "..."}},
     "contexts":  {"dev-admin": {"cluster": "dev", "user": "admin",
                                 "namespace": "default"}},
     "current-context": "dev-admin"}

Resolution precedence matches clientcmd: explicit --server/--token flags win,
then $KTL_SERVER, then the current context.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple


def config_path() -> str:
    return os.environ.get("KTLCONFIG",
                          os.path.join(os.path.expanduser("~"), ".ktl", "config"))


def load_config() -> Dict:
    try:
        with open(config_path()) as f:
            cfg = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        cfg = {}
    cfg.setdefault("clusters", {})
    cfg.setdefault("users", {})
    cfg.setdefault("contexts", {})
    cfg.setdefault("current-context", "")
    return cfg


def save_config(cfg: Dict) -> None:
    path = config_path()
    parent = os.path.dirname(path)
    if parent:  # a bare filename has no directory to create
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    # 0600 like kubeconfig/admin.conf: the file carries bearer tokens
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(cfg, f, indent=2, sort_keys=True)
    os.replace(tmp, path)  # atomic: a concurrent reader never sees a torn file


def resolve(cfg: Optional[Dict] = None
            ) -> Tuple[Optional[str], Optional[str], Optional[str]]:
    """-> (server, token, namespace) from the current context, or Nones."""
    cfg = cfg if cfg is not None else load_config()
    ctx_name = cfg.get("current-context") or ""
    ctx = cfg.get("contexts", {}).get(ctx_name)
    if not ctx:
        return None, None, None
    cluster = cfg.get("clusters", {}).get(ctx.get("cluster", ""), {})
    user = cfg.get("users", {}).get(ctx.get("user", ""), {})
    return (cluster.get("server"), user.get("token"),
            ctx.get("namespace"))


def cmd_config(client, args) -> int:
    import sys

    cfg = load_config()
    sub = args.config_cmd
    if sub == "view":
        redacted = json.loads(json.dumps(cfg))
        for u in redacted.get("users", {}).values():
            if u.get("token"):
                u["token"] = "REDACTED"
        print(json.dumps(redacted, indent=2, sort_keys=True))
        return 0
    if sub == "current-context":
        cur = cfg.get("current-context", "")
        if not cur:
            print("error: current-context is not set", file=sys.stderr)
            return 1
        print(cur)
        return 0
    if sub == "get-contexts":
        cur = cfg.get("current-context", "")
        for name, ctx in sorted(cfg["contexts"].items()):
            marker = "*" if name == cur else " "
            print(f"{marker} {name}\tcluster={ctx.get('cluster', '')}"
                  f"\tuser={ctx.get('user', '')}"
                  f"\tnamespace={ctx.get('namespace', 'default')}")
        return 0
    if sub == "set-cluster":
        cfg["clusters"][args.name] = {"server": args.server_url}
    elif sub == "set-credentials":
        cfg["users"][args.name] = {"token": args.token}
    elif sub == "set-context":
        cfg["contexts"][args.name] = {
            "cluster": args.cluster, "user": args.user_name,
            "namespace": args.context_namespace or "default"}
    elif sub == "use-context":
        if args.name not in cfg["contexts"]:
            print(f"error: no context exists with the name {args.name!r}",
                  file=sys.stderr)
            return 1
        cfg["current-context"] = args.name
    elif sub == "delete-context":
        if cfg["contexts"].pop(args.name, None) is None:
            print(f"error: no context exists with the name {args.name!r}",
                  file=sys.stderr)
            return 1
        if cfg.get("current-context") == args.name:
            cfg["current-context"] = ""
    else:
        print(f"error: unknown config command {sub!r}", file=sys.stderr)
        return 1
    save_config(cfg)
    print(f"{sub}: done")
    return 0


def add_config_parser(sub) -> None:
    p = sub.add_parser("config")
    p.add_argument("config_cmd",
                   choices=["view", "current-context", "get-contexts",
                            "set-cluster", "set-credentials", "set-context",
                            "use-context", "delete-context"])
    p.add_argument("name", nargs="?", default="")
    p.add_argument("--server-url", default="")
    p.add_argument("--token", default="")
    p.add_argument("--cluster", default="")
    p.add_argument("--user", dest="user_name", default="")
    p.add_argument("--namespace", dest="context_namespace", default="")
    p.set_defaults(fn=cmd_config)
