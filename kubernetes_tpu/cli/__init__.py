"""L8 — ktl, the kubectl-equivalent CLI."""
