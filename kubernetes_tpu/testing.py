"""Fluent object builders for tests (reference: pkg/scheduler/testing/wrappers.go
st.MakePod()/MakeNode() — the load-bearing unit-test helper pattern, SURVEY.md §4)."""

from __future__ import annotations

from typing import Dict, List, Optional

from .api import (
    Affinity,
    Container,
    ContainerImage,
    ContainerPort,
    Node,
    NodeSelector,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodSpec,
    PreferredSchedulingTerm,
    Selector,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
    new_uid,
)


class MakePod:
    def __init__(self, name: str = "p", namespace: str = "default"):
        self._pod = Pod(metadata=ObjectMeta(name=name, namespace=namespace, uid=new_uid()))

    def name(self, n: str) -> "MakePod":
        self._pod.metadata.name = n
        return self

    def namespace(self, ns: str) -> "MakePod":
        self._pod.metadata.namespace = ns
        return self

    def uid(self, uid: str) -> "MakePod":
        self._pod.metadata.uid = uid
        return self

    def labels(self, labels: Dict[str, str]) -> "MakePod":
        self._pod.metadata.labels.update(labels)
        return self

    def gang(self, group_name: str, rank: Optional[int] = None) -> "MakePod":
        """Join the PodGroup `group_name` (in the pod's namespace) via the
        pod-group.scheduling/name label convention (api/podgroup.py);
        `rank` adds the positional pod-group.scheduling/rank label the
        rank-alignment pass consumes."""
        from .api.podgroup import POD_GROUP_LABEL, POD_GROUP_RANK_LABEL

        self._pod.metadata.labels[POD_GROUP_LABEL] = group_name
        if rank is not None:
            self._pod.metadata.labels[POD_GROUP_RANK_LABEL] = str(rank)
        return self

    def req(self, requests: Dict[str, str], image: str = "", host_port: int = 0) -> "MakePod":
        """Add a container with the given resource requests."""
        c = Container(
            name=f"c{len(self._pod.spec.containers)}",
            image=image,
            resources={"requests": dict(requests)} if requests else {},
        )
        if host_port:
            c.ports.append(ContainerPort(container_port=host_port, host_port=host_port))
        self._pod.spec.containers.append(c)
        return self

    def init_req(self, requests: Dict[str, str]) -> "MakePod":
        self._pod.spec.init_containers.append(
            Container(name=f"i{len(self._pod.spec.init_containers)}",
                      resources={"requests": dict(requests)})
        )
        return self

    def container(self, image: str) -> "MakePod":
        self._pod.spec.containers.append(
            Container(name=f"c{len(self._pod.spec.containers)}", image=image)
        )
        return self

    def node(self, node_name: str) -> "MakePod":
        self._pod.spec.node_name = node_name
        return self

    def node_selector(self, sel: Dict[str, str]) -> "MakePod":
        self._pod.spec.node_selector.update(sel)
        return self

    def node_affinity_in(self, key: str, values) -> "MakePod":
        self._affinity().node_affinity_required = NodeSelector.from_dict(
            {"nodeSelectorTerms": [{"matchExpressions": [
                {"key": key, "operator": "In", "values": list(values)}]}]}
        )
        return self

    def preferred_node_affinity(self, weight: int, key: str, values) -> "MakePod":
        self._affinity().node_affinity_preferred.append(
            PreferredSchedulingTerm.from_dict({
                "weight": weight,
                "preference": {"matchExpressions": [
                    {"key": key, "operator": "In", "values": list(values)}]},
            })
        )
        return self

    def pod_affinity(self, topology_key: str, match_labels: Dict[str, str]) -> "MakePod":
        self._affinity().pod_affinity_required.append(
            PodAffinityTerm(topology_key=topology_key,
                            selector=Selector.from_match_labels(match_labels))
        )
        return self

    def pod_anti_affinity(self, topology_key: str, match_labels: Dict[str, str]) -> "MakePod":
        self._affinity().pod_anti_affinity_required.append(
            PodAffinityTerm(topology_key=topology_key,
                            selector=Selector.from_match_labels(match_labels))
        )
        return self

    def preferred_pod_affinity(self, weight: int, topology_key: str, match_labels: Dict[str, str]) -> "MakePod":
        self._affinity().pod_affinity_preferred.append(
            WeightedPodAffinityTerm(weight, PodAffinityTerm(
                topology_key=topology_key, selector=Selector.from_match_labels(match_labels)))
        )
        return self

    def preferred_pod_anti_affinity(self, weight: int, topology_key: str, match_labels: Dict[str, str]) -> "MakePod":
        self._affinity().pod_anti_affinity_preferred.append(
            WeightedPodAffinityTerm(weight, PodAffinityTerm(
                topology_key=topology_key, selector=Selector.from_match_labels(match_labels)))
        )
        return self

    def toleration(self, key: str, value: str = "", operator: str = "Equal", effect: str = "") -> "MakePod":
        self._pod.spec.tolerations.append(
            Toleration(key=key, operator=operator, value=value, effect=effect)
        )
        return self

    def topology_spread(self, max_skew: int, topology_key: str, when: str,
                        match_labels: Optional[Dict[str, str]] = None,
                        min_domains: Optional[int] = None) -> "MakePod":
        self._pod.spec.topology_spread_constraints.append(
            TopologySpreadConstraint(
                max_skew=max_skew, topology_key=topology_key, when_unsatisfiable=when,
                selector=Selector.from_match_labels(match_labels or {}),
                min_domains=min_domains,
            )
        )
        return self

    def priority(self, p: int) -> "MakePod":
        self._pod.spec.priority = p
        return self

    def claim(self, claim_name: str, ref_name: str = "") -> "MakePod":
        """Reference a DRA ResourceClaim (PodSpec.resourceClaims)."""
        self._pod.spec.resource_claims.append(
            (ref_name or claim_name, claim_name))
        return self

    def scheduling_gate(self, name: str) -> "MakePod":
        self._pod.spec.scheduling_gates.append(name)
        return self

    def phase(self, phase: str) -> "MakePod":
        self._pod.status.phase = phase
        return self

    def pvc(self, claim_name: str, read_only: bool = False) -> "MakePod":
        from .api.types import Volume

        self._pod.spec.volumes.append(
            Volume(name=f"vol-{len(self._pod.spec.volumes)}",
                   pvc_claim_name=claim_name, pvc_read_only=read_only))
        return self

    def volume(self, **kwargs) -> "MakePod":
        from .api.types import Volume

        kwargs.setdefault("name", f"vol-{len(self._pod.spec.volumes)}")
        self._pod.spec.volumes.append(Volume(**kwargs))
        return self

    def _affinity(self) -> Affinity:
        if self._pod.spec.affinity is None:
            self._pod.spec.affinity = Affinity()
        return self._pod.spec.affinity

    def obj(self) -> Pod:
        return self._pod


class MakeNode:
    def __init__(self, name: str = "n"):
        self._node = Node(metadata=ObjectMeta(name=name, namespace="", uid=new_uid()))
        self._node.metadata.labels["kubernetes.io/hostname"] = name

    def name(self, n: str) -> "MakeNode":
        self._node.metadata.name = n
        self._node.metadata.labels["kubernetes.io/hostname"] = n
        return self

    def labels(self, labels: Dict[str, str]) -> "MakeNode":
        self._node.metadata.labels.update(labels)
        return self

    def tpu_slice(self, slice_id, index: Optional[int] = None) -> "MakeNode":
        """Advertise the node's TPU slice (ICI domain) — api/podgroup.py
        LABEL_TPU_SLICE, consumed by the gang slice-packing score; `index`
        adds the optional ring-position label (LABEL_TPU_SLICE_INDEX) the
        rank-alignment pass measures neighbor distance along."""
        from .api.podgroup import LABEL_TPU_SLICE, LABEL_TPU_SLICE_INDEX

        self._node.metadata.labels[LABEL_TPU_SLICE] = str(slice_id)
        if index is not None:
            self._node.metadata.labels[LABEL_TPU_SLICE_INDEX] = str(index)
        return self

    def capacity(self, cap: Dict[str, str]) -> "MakeNode":
        cap = dict(cap)
        cap.setdefault("pods", "110")
        self._node.status.capacity = cap
        self._node.status.allocatable = dict(cap)
        return self

    def taints(self, taints) -> "MakeNode":
        self._node.spec.taints = [
            t if isinstance(t, Taint) else Taint.from_dict(t) for t in taints
        ]
        return self

    def unschedulable(self, v: bool = True) -> "MakeNode":
        self._node.spec.unschedulable = v
        return self

    def images(self, images: Dict[str, int]) -> "MakeNode":
        self._node.status.images = [
            ContainerImage(names=(name,), size_bytes=size) for name, size in images.items()
        ]
        return self

    def obj(self) -> Node:
        return self._node


def make_pod_group(name: str, min_member: int, namespace: str = "default"):
    """PodGroup builder (api/podgroup.py) for tests and benches."""
    from .api.podgroup import PodGroup, PodGroupSpec

    return PodGroup(
        metadata=ObjectMeta(name=name, namespace=namespace, uid=new_uid()),
        spec=PodGroupSpec(min_member=min_member),
    )


def pod_conservation_report(store, scheduler, keys):
    """Classify every submitted pod key after a (quiesced) chaos run — the
    pod-conservation invariant of ISSUE 6: each pod is exactly one of
    bound / pending / terminally-failed, never lost, never double-bound.

    Call at quiescence (run_until_idle + flush_binds done): a pod mid-flight
    in the bind queue would read as lost. Returns
    {"bound", "pending", "failed", "lost", "double_bound", "counts"} where
    the first five are key lists.

      bound         spec.node_name set in the STORE (the source of truth)
      pending       unbound, non-terminal, and accounted for — tracked by
                    the queue (any tier, incl. gang staging) or still
                    assumed in the cache
      failed        terminal phase (Failed/Succeeded) with its status reason
      lost          none of the above — the invariant violation chaos must
                    never produce
      double_bound  bound MORE than once in the store's event history (two
                    unbind->bind transitions for one key), or accounted on
                    two nodes in the scheduler cache
    """
    pods = {}
    for p in store.list("pods")[0]:
        pods[p.key] = p
    # partitioned scheduler (ISSUE 12): the coordinator exposes its live
    # pipelines + the residual pass; pending-tracking is the UNION of their
    # queues/caches, while the cross-member double-accounting check below
    # covers the disjoint pipelines only (the residual cache deliberately
    # MIRRORS every bound pod, so it is checked for internal dups alone)
    members = getattr(scheduler, "conservation_members", None)
    if members is not None:
        disjoint, mirror = members()
        trackers = list(disjoint) + ([mirror] if mirror is not None else [])
    else:
        disjoint, mirror = [scheduler], None
        trackers = [scheduler]
    queue_keys = set()
    for s in trackers:
        queue_keys.update(s.queue.tracked_keys())
    bound, pending, failed, lost = [], [], [], []
    for key in keys:
        pod = pods.get(key)
        if pod is None:
            lost.append(key)  # deleted: a chaos run we drive never deletes
        elif pod.spec.node_name:
            bound.append(key)
        elif pod.is_terminal():
            failed.append(key)
        elif key in queue_keys or any(s.cache.is_assumed(key)
                                      for s in trackers):
            pending.append(key)
        else:
            lost.append(key)

    # double-bind check #1: the store's own history — count unbound->bound
    # transitions per key (bind_many/bind MODIFIED events carry prev)
    double: List[str] = []
    keyset = set(keys)
    bind_counts: Dict[str, int] = {}
    # history_events flattens columnar LazyBindBatch markers into their
    # per-object events (ISSUE 15); plain Event histories pass through
    history = (store.history_events() if hasattr(store, "history_events")
               else getattr(store, "_history", ()))
    for ev in history:
        if ev.kind != "pods" or ev.type != "MODIFIED":
            continue
        obj, prev = ev.obj, ev.prev
        if (obj is not None and getattr(obj.spec, "node_name", None)
                and (prev is None or not prev.spec.node_name)):
            k = obj.key
            if k in keyset:
                bind_counts[k] = bind_counts.get(k, 0) + 1
    double.extend(k for k, n in bind_counts.items() if n > 1)
    # double-bind check #2: the scheduler cache never accounts one pod on
    # two nodes (an assume/forget bookkeeping bug would). For a partitioned
    # scheduler the DISJOINT pipelines' caches merge into one count — a pod
    # accounted by two partitions is the cross-partition double; the mirror
    # (residual) cache is checked separately for internal duplicates only.
    seen: Dict[str, int] = {}
    for s in disjoint:
        # collapse columnar cache rows (ISSUE 16) so the walk below counts
        # every accounted pod, not only the materialized PodInfos
        mz = getattr(s.cache, "materialize_columnar_rows", None)
        if mz is not None:
            mz()
        snap = s.cache.update_snapshot()
        for ni in snap.node_info_list:
            for pi in ni.pods:
                k = pi.pod.key
                if k in keyset:
                    seen[k] = seen.get(k, 0) + 1
    double.extend(k for k, n in seen.items() if n > 1 and k not in double)
    if mirror is not None:
        mseen: Dict[str, int] = {}
        mz = getattr(mirror.cache, "materialize_columnar_rows", None)
        if mz is not None:
            mz()
        snap = mirror.cache.update_snapshot()
        for ni in snap.node_info_list:
            for pi in ni.pods:
                k = pi.pod.key
                if k in keyset:
                    mseen[k] = mseen.get(k, 0) + 1
        double.extend(k for k, n in mseen.items()
                      if n > 1 and k not in double)

    return {
        "bound": bound, "pending": pending, "failed": failed, "lost": lost,
        "double_bound": double,
        "counts": {"submitted": len(keys), "bound": len(bound),
                   "pending": len(pending), "failed": len(failed),
                   "lost": len(lost), "double_bound": len(double)},
    }


def assert_pod_conservation(store, scheduler, keys):
    """Raise AssertionError (with the offending keys) unless every submitted
    pod is conserved: 0 lost, 0 double-bound. Returns the report."""
    rep = pod_conservation_report(store, scheduler, keys)
    assert not rep["lost"], (
        f"{len(rep['lost'])} pod(s) LOST (not bound, not queued, not "
        f"terminal): {rep['lost'][:10]}")
    assert not rep["double_bound"], (
        f"{len(rep['double_bound'])} pod(s) DOUBLE-BOUND: "
        f"{rep['double_bound'][:10]}")
    return rep


def mutation_detector_guard(monkeypatch):
    """Shared body for the force-enabled mutation-detector autouse fixture
    (the runtime counterpart of schedlint MU001). Use from a test module as

        @pytest.fixture(autouse=True)
        def _force_mutation_detector(monkeypatch):
            yield from mutation_detector_guard(monkeypatch)

    Every APIStore the module builds runs with the detector ON, and every
    store is checked at teardown — a clone-sharing regression (a consumer
    mutation reaching a stored object, or vice versa) fails tier-1 in the
    module that caused it instead of corrupting watchers silently."""
    from .store import APIStore

    monkeypatch.setenv("CACHE_MUTATION_DETECTOR", "1")
    stores = []
    orig = APIStore.__init__

    def wrapped(self, *a, **kw):
        orig(self, *a, **kw)
        stores.append(self)

    monkeypatch.setattr(APIStore, "__init__", wrapped)
    yield
    for s in stores:
        s.check_mutations()
