"""HTTP client for the API server — the client-go analog (typed REST + watch).

reference: staging/src/k8s.io/client-go/rest + tools/cache/reflector.go
(ListAndWatch with resourceVersion resume).
"""

from __future__ import annotations

import json
import threading
import urllib.request
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ..api.serialize import GROUP_PREFIX, CLUSTER_SCOPED, from_dict


class APIError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class RESTClient:
    def __init__(self, base_url: str, timeout: float = 10.0,
                 token: Optional[str] = None, user: Optional[str] = None,
                 user_agent: str = ""):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # token -> Authorization: Bearer (the secured path); user -> the
        # X-Remote-User convention honored by servers without an authenticator
        self.token = token
        self.user = user
        # first token doubles as the default field manager for writes
        # (the server's managedfields default chain reads User-Agent)
        self.user_agent = user_agent
        # plural/alias -> {"prefix", "namespaced"} for CRD-served resources,
        # filled lazily from GET /apis (the reference's discovery client)
        self._dynamic: Dict[str, Dict[str, Any]] = {}

    def _discover(self, resource: str) -> Dict[str, Any]:
        info = self._dynamic.get(resource)
        if info is not None:
            return info
        doc = self.request("GET", "/apis")
        self._dynamic = {}
        for plural, entry in (doc.get("resources") or {}).items():
            self._dynamic[plural] = entry
            for alias in entry.get("shortNames") or []:
                self._dynamic.setdefault(alias, entry)
            for alias in (entry.get("singular", ""),
                          entry.get("kind", "").lower()):
                if alias:
                    self._dynamic.setdefault(alias, entry)
        info = self._dynamic.get(resource)
        if info is None:
            raise APIError(404, f"unknown resource {resource!r} (discovery)")
        return info

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.user_agent:
            h["User-Agent"] = self.user_agent
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        elif self.user:
            h["X-Remote-User"] = self.user
        return h

    def _path(self, resource: str, namespace: Optional[str], name: Optional[str] = None,
              subresource: Optional[str] = None) -> str:
        prefix = GROUP_PREFIX.get(resource)
        if prefix is not None:
            namespaced = resource not in CLUSTER_SCOPED
        else:
            info = self._discover(resource)
            prefix, namespaced = info["prefix"], bool(info.get("namespaced", True))
        if not namespaced or namespace is None:
            p = f"{prefix}/{resource}"
        else:
            p = f"{prefix}/namespaces/{namespace}/{resource}"
        if name:
            p += f"/{name}"
        if subresource:
            p += f"/{subresource}"
        return p

    def request_text(self, path: str) -> str:
        """GET a text/plain endpoint (the pods/{name}/log subresource)."""
        req = urllib.request.Request(self.base_url + path, method="GET",
                                     headers=self._headers())
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.read().decode()
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
                msg = payload.get("message", str(e))
            except Exception:
                msg = str(e)
            raise APIError(e.code, msg) from None

    def logs(self, name: str, namespace: str = "default",
             tail_lines: int = 0) -> str:
        path = self._path("pods", namespace, name, "log")
        if tail_lines:
            path += f"?tailLines={tail_lines}"
        return self.request_text(path)

    def request(self, method: str, path: str, body: Optional[Dict] = None,
                timeout: Optional[float] = None,
                content_type: Optional[str] = None):
        data = json.dumps(body).encode() if body is not None else None
        headers = self._headers()
        if content_type:
            headers["Content-Type"] = content_type
        req = urllib.request.Request(self.base_url + path, data=data, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout or self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
                msg = payload.get("message", str(e))
            except Exception:
                msg = str(e)
            raise APIError(e.code, msg) from None

    # -- typed operations ------------------------------------------------------

    def create(self, resource: str, obj_dict: Dict, namespace: Optional[str] = None):
        ns = namespace or (obj_dict.get("metadata") or {}).get("namespace") or "default"
        return self.request("POST", self._path(resource, ns), obj_dict)

    def get(self, resource: str, name: str, namespace: Optional[str] = "default") -> Dict:
        return self.request("GET", self._path(resource, namespace, name))

    def list(self, resource: str, namespace: Optional[str] = None,
             field_selector: str = "",
             label_selector: str = "") -> Tuple[List[Dict], int]:
        from urllib.parse import quote

        path = self._path(resource, namespace)
        params = []
        if field_selector:
            params.append(f"fieldSelector={quote(field_selector)}")
        if label_selector:
            params.append(f"labelSelector={quote(label_selector)}")
        if params:
            path += "?" + "&".join(params)
        out = self.request("GET", path)
        return out["items"], out["metadata"]["resourceVersion"]

    def update(self, resource: str, obj_dict: Dict, namespace: Optional[str] = None) -> Dict:
        meta = obj_dict.get("metadata") or {}
        ns = namespace or meta.get("namespace") or "default"
        return self.request("PUT", self._path(resource, ns, meta["name"]), obj_dict)

    def delete(self, resource: str, name: str, namespace: Optional[str] = "default") -> Dict:
        return self.request("DELETE", self._path(resource, namespace, name))

    def patch(self, resource: str, name: str, patch: Dict,
              namespace: Optional[str] = "default",
              patch_type: str = "application/strategic-merge-patch+json") -> Dict:
        """PATCH (merge semantics) — reference: handlers/patch.go."""
        return self.request("PATCH", self._path(resource, namespace, name),
                            patch, content_type=patch_type)

    def apply(self, resource: str, name: str, obj_dict: Dict,
              namespace: Optional[str] = "default",
              field_manager: str = "ktl", force: bool = False) -> Dict:
        """Server-side apply (handlers/patch.go:432): PATCH with the
        apply-patch content type; 409 Conflict lists owning managers unless
        force steals the fields."""
        from urllib.parse import quote

        path = (self._path(resource, namespace, name)
                + f"?fieldManager={quote(field_manager)}")
        if force:
            path += "&force=true"
        return self.request("PATCH", path, obj_dict,
                            content_type="application/apply-patch+yaml")

    def update_status(self, resource: str, obj_dict: Dict,
                      namespace: Optional[str] = None) -> Dict:
        """PUT the status subresource: only the status stanza lands (the
        kubelet/controller write path — spec is untouchable here)."""
        meta = obj_dict.get("metadata") or {}
        ns = namespace or meta.get("namespace") or "default"
        return self.request("PUT",
                            self._path(resource, ns, meta["name"], "status"),
                            obj_dict)

    def exec(self, name: str, command, namespace: str = "default",
             container: str = "", stdin: bytes = b"",
             timeout_seconds: float = 10.0) -> Dict:
        """Run a command in a pod's container (pods/{name}/exec session
        channel). Returns {stdout, stderr, exitCode}."""
        import base64

        body = {"command": list(command), "container": container,
                "timeoutSeconds": timeout_seconds}
        if stdin:
            body["stdin"] = base64.b64encode(stdin).decode()
        return self.request("POST", self._path("pods", namespace, name, "exec"),
                            body, timeout=timeout_seconds + 5)

    def attach(self, name: str, namespace: str = "default",
               container: str = "", stdin: bytes = b"",
               timeout_seconds: float = 10.0) -> Dict:
        """Attach to the running container: recent output + optional stdin."""
        import base64

        body = {"container": container, "timeoutSeconds": timeout_seconds}
        if stdin:
            body["stdin"] = base64.b64encode(stdin).decode()
        return self.request("POST",
                            self._path("pods", namespace, name, "attach"),
                            body, timeout=timeout_seconds + 5)

    def port_forward(self, name: str, port: int, data: bytes,
                     namespace: str = "default",
                     timeout_seconds: float = 10.0) -> bytes:
        """One port-forward connection round: bytes out, bytes back."""
        import base64

        out = self.request(
            "POST", self._path("pods", namespace, name, "portforward"),
            {"port": port, "data": base64.b64encode(data).decode(),
             "timeoutSeconds": timeout_seconds},
            timeout=timeout_seconds + 5)
        if out.get("error"):
            # backend failure must not masquerade as an empty response
            raise APIError(502, out["error"])
        return base64.b64decode(out.get("data", ""))

    def evict(self, name: str, namespace: str = "default") -> Dict:
        """PDB-respecting eviction (pods/{name}/eviction); 429 when a
        matching budget has no disruptions left."""
        return self.request("POST", self._path("pods", namespace, name, "eviction"),
                            {"kind": "Eviction",
                             "metadata": {"name": name, "namespace": namespace}})

    def bind(self, namespace: str, pod_name: str, node_name: str) -> Dict:
        return self.request("POST", self._path("pods", namespace, pod_name, "binding"),
                            {"target": {"kind": "Node", "name": node_name}})

    def watch(self, resource: str, since_rv: int = -1,
              namespace: Optional[str] = None,
              field_selector: str = "",
              label_selector: str = "",
              send_initial_events: bool = False,
              ring: bool = False) -> Iterator[Tuple[str, Dict]]:
        """Yields (event_type, object_dict); blocks on the streaming
        response. send_initial_events=True is the WatchList mode
        (KEP-3157): current objects stream first as ADDED, then a BOOKMARK
        annotated k8s.io/initial-events-end, then live events.

        ring=True subscribes through a lossy ring buffer (`?ring=true`,
        ISSUE 12/13): a slow consumer's overflow drops its own oldest
        delivery instead of terminating the subscription into a relist
        storm. OBSERVABILITY consumers (dashboards, `ktl ... -w`) must pass
        it; cache-building consumers (Informer) must not — they need the
        eviction/terminate contract to know they missed events."""
        from urllib.parse import quote

        path = self._path(resource, namespace) + f"?watch=true&resourceVersion={since_rv}"
        if send_initial_events:
            path += "&sendInitialEvents=true"
        if ring:
            path += "&ring=true"
        if field_selector:
            path += f"&fieldSelector={quote(field_selector)}"
        if label_selector:
            path += f"&labelSelector={quote(label_selector)}"
        req = urllib.request.Request(self.base_url + path, headers=self._headers())
        resp = urllib.request.urlopen(req, timeout=3600)
        for raw in resp:
            raw = raw.strip()
            if not raw:
                continue
            ev = json.loads(raw)
            yield ev["type"], ev["object"]


class Informer:
    """List+watch a resource into a local cache with handlers — the
    SharedIndexInformer analog over HTTP."""

    def __init__(self, client: RESTClient, resource: str,
                 on_event: Optional[Callable[[str, Any], None]] = None,
                 field_selector: str = "", label_selector: str = "",
                 watch_list: bool = False):
        self.client = client
        self.resource = resource
        self.cache: Dict[str, Any] = {}
        self.on_event = on_event
        # server-side scope (e.g. spec.nodeName=<me> for a kubelet informer)
        self.field_selector = field_selector
        self.label_selector = label_selector
        # WatchList mode (KEP-3157; reflector.go:121-143): NO separate LIST
        # — every (re)connect streams current objects as initial ADDED
        # events ending in an annotated bookmark, and the cache swap at the
        # bookmark replaces the relist path entirely
        self.watch_list = watch_list
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _swap_cache(self, fresh: Dict[str, Any]) -> None:
        """Replace the cache, emitting synthetic deltas for changes missed
        while disconnected (shared_informer replace semantics). Applied
        key-by-key — a clear()+update() would give concurrent readers an
        empty-cache window mid-resync. Survivors emit MODIFIED only when
        their resourceVersion moved (DeltaFIFO Replace dedup), so a
        transient blip doesn't replay a full-cluster reconcile storm."""
        old = dict(self.cache)
        gone = set(old) - set(fresh)
        for k in gone:
            self.cache.pop(k, None)
        self.cache.update(fresh)
        if self.on_event:
            for k in gone:
                self.on_event("DELETED", old[k])
            for k in set(fresh) - set(old):
                self.on_event("ADDED", fresh[k])
            for k in set(fresh) & set(old):
                if (old[k].metadata.resource_version
                        != fresh[k].metadata.resource_version):
                    self.on_event("MODIFIED", fresh[k])

    def _key(self, obj_dict: Dict) -> str:
        meta = obj_dict.get("metadata") or {}
        ns = meta.get("namespace")
        return f"{ns}/{meta['name']}" if ns else meta["name"]

    def start(self) -> "Informer":
        if self.watch_list:
            rv = -1  # the stream itself primes the cache
        else:
            items, rv = self.client.list(self.resource,
                                         field_selector=self.field_selector,
                                         label_selector=self.label_selector)
            for it in items:
                self.cache[self._key(it)] = from_dict(self.resource, it)

        def loop():
            nonlocal rv
            while not self._stop.is_set():
                try:
                    syncing = self.watch_list
                    fresh: Dict[str, Any] = {}
                    stream = self.client.watch(
                        self.resource,
                        since_rv=-1 if self.watch_list else rv,
                        field_selector=self.field_selector,
                        label_selector=self.label_selector,
                        send_initial_events=self.watch_list)
                    for etype, obj_dict in stream:
                        if self._stop.is_set():
                            return
                        if etype == "BOOKMARK":
                            # rv checkpoint only (reflector.go:156) — no object
                            meta = obj_dict.get("metadata") or {}
                            rv = int(meta.get("resourceVersion", rv))
                            if syncing and (meta.get("annotations") or {}).get(
                                    "k8s.io/initial-events-end") == "true":
                                self._swap_cache(fresh)
                                syncing = False
                            continue
                        obj = from_dict(self.resource, obj_dict)
                        key = self._key(obj_dict)
                        rv = int((obj_dict.get("metadata") or {}).get("resourceVersion", rv))
                        if syncing:
                            fresh[key] = obj  # initial burst: swap at the end bookmark
                            continue
                        if etype == "DELETED":
                            self.cache.pop(key, None)
                        else:
                            self.cache[key] = obj
                        if self.on_event:
                            self.on_event(etype, obj)
                except Exception:
                    if self._stop.is_set():
                        return
                    import time

                    time.sleep(0.2)
                    if self.watch_list:
                        continue  # reconnect re-syncs via initial events
                    # Reflector contract: RELIST then rewatch — retrying the
                    # stale rv after a 410 Expired would loop forever and
                    # freeze the cache.
                    try:
                        items, rv = self.client.list(
                            self.resource, field_selector=self.field_selector,
                            label_selector=self.label_selector)
                        # synthetic deltas for changes missed during the
                        # outage (shared_informer replace semantics)
                        self._swap_cache({self._key(it):
                                          from_dict(self.resource, it)
                                          for it in items})
                    except Exception:
                        pass  # server unreachable: retry the whole cycle

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
