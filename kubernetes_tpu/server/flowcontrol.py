"""API Priority and Fairness (APF) — apiserver request flow control.

reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol (the APF
dispatcher) and the flowcontrol.apiserver.k8s.io API group
(PriorityLevelConfiguration + FlowSchema). The carried subset:

  - PriorityLevel: a seat limit (assured concurrency) + a bounded FIFO queue
    with a wait deadline. Requests beyond seats wait; beyond queue length or
    deadline they get 429 + Retry-After (the reference's reject verdict).
  - FlowSchema: ordered match rules (user / group / verb / resource
    wildcards) -> priority level; first match wins, like the reference's
    matchingPrecedence ordering.
  - Exempt levels dispatch immediately (system:masters traffic must never be
    starved by a misbehaving workload — the `exempt` level).

Long-running requests (watches) are NOT seat-accounted, mirroring the
reference's longRunningRequestCheck: a watch holds its connection for
minutes, and counting it against seats would wedge the level.

The fair-queuing refinement (shuffle sharding over N queues per level) is
collapsed to one FIFO per level: the fairness unit here is the level, which
is the property the tests (and the 429 contract) depend on.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple


class PriorityLevel:
    """Seat-limited dispatch with a bounded wait queue."""

    def __init__(self, name: str, seats: int = 10, queue_length: int = 50,
                 queue_timeout: float = 5.0, exempt: bool = False):
        self.name = name
        self.seats = seats
        self.queue_length = queue_length
        self.queue_timeout = queue_timeout
        self.exempt = exempt
        self._cond = threading.Condition()
        self.inflight = 0
        self.waiting = 0
        self.rejected = 0  # cumulative 429s (metrics surface)
        self.dispatched = 0

    def acquire(self) -> bool:
        """True = seat granted; False = reject with 429."""
        if self.exempt:
            with self._cond:
                self.inflight += 1
                self.dispatched += 1
            return True
        with self._cond:
            if self.inflight < self.seats:
                self.inflight += 1
                self.dispatched += 1
                return True
            if self.waiting >= self.queue_length:
                self.rejected += 1
                return False
            self.waiting += 1
            deadline = self._cond.wait_for(
                lambda: self.inflight < self.seats,
                timeout=self.queue_timeout)
            self.waiting -= 1
            if not deadline:
                self.rejected += 1
                return False
            self.inflight += 1
            self.dispatched += 1
            return True

    def release(self) -> None:
        with self._cond:
            self.inflight -= 1
            # notify_all: a single notify can be consumed by a waiter that is
            # concurrently timing out, stranding the seat while other waiters
            # sleep to rejection
            self._cond.notify_all()

    def stats(self) -> Dict[str, int]:
        with self._cond:
            return {"inflight": self.inflight, "waiting": self.waiting,
                    "rejected": self.rejected, "dispatched": self.dispatched}


@dataclass
class FlowSchema:
    """Match rule -> level. Wildcard "*" matches anything; groups match if
    ANY of the user's groups is listed."""

    name: str
    level: str
    users: Tuple[str, ...] = ("*",)
    groups: Tuple[str, ...] = ("*",)
    verbs: Tuple[str, ...] = ("*",)
    resources: Tuple[str, ...] = ("*",)

    def matches(self, user, verb: str, resource: str) -> bool:
        if "*" not in self.verbs and verb not in self.verbs:
            return False
        if "*" not in self.resources and resource not in self.resources:
            return False
        user_ok = "*" in self.users or (user is not None
                                        and user.name in self.users)
        group_ok = "*" in self.groups or (
            user is not None and any(g in self.groups for g in user.groups))
        # users/groups are alternative subject spellings (reference subjects
        # list): either identifies the flow
        if "*" in self.users and "*" in self.groups:
            return True
        return user_ok if "*" in self.groups else (
            group_ok if "*" in self.users else (user_ok or group_ok))


class FlowController:
    """Classify + dispatch. Levels and schemas are fixed at construction
    (the reference watches its config objects; a rebuild here is a new
    controller on the server)."""

    def __init__(self, levels: Sequence[PriorityLevel],
                 schemas: Sequence[FlowSchema]):
        self.levels = {l.name: l for l in levels}
        self.schemas = list(schemas)
        if not self.schemas:
            raise ValueError("at least one FlowSchema (a catch-all) is required")
        for s in self.schemas:
            if s.level not in self.levels:
                raise ValueError(f"schema {s.name!r} names unknown level {s.level!r}")
        last = self.schemas[-1]
        if not ("*" in last.verbs and "*" in last.resources
                and "*" in last.users and "*" in last.groups):
            # the reference guarantees the catch-all FlowSchema exists;
            # without one, unmatched requests would ride a level whose rule
            # they explicitly failed
            raise ValueError(
                f"last schema {last.name!r} must be a universal catch-all")

    def classify(self, user, verb: str, resource: str) -> PriorityLevel:
        for s in self.schemas:
            if s.matches(user, verb, resource):
                return self.levels[s.level]
        return self.levels[self.schemas[-1].level]  # unreachable: catch-all

    def stats(self) -> Dict[str, Dict[str, int]]:
        return {name: lvl.stats() for name, lvl in self.levels.items()}


def default_flow_controller(default_seats: int = 10,
                            queue_length: int = 50,
                            queue_timeout: float = 5.0) -> FlowController:
    """The bootstrap configuration (flowcontrol/bootstrap defaults):
    exempt for cluster admins, a wide `system` level for nodes and control
    plane components, `workload-high` for controllers' writes, and a
    seat-limited `global-default` catch-all."""
    levels = [
        PriorityLevel("exempt", exempt=True),
        PriorityLevel("system", seats=max(default_seats * 3, 30),
                      queue_length=queue_length, queue_timeout=queue_timeout),
        PriorityLevel("global-default", seats=default_seats,
                      queue_length=queue_length, queue_timeout=queue_timeout),
    ]
    schemas = [
        FlowSchema("exempt", "exempt", users=(), groups=("system:masters",)),
        FlowSchema("system-nodes", "system", users=(),
                   groups=("system:nodes",)),
        FlowSchema("system-components", "system", users=(),
                   groups=("system:kube-scheduler",
                           "system:kube-controller-manager")),
        FlowSchema("catch-all", "global-default"),
    ]
    return FlowController(levels, schemas)


# ---- live configuration from API objects --------------------------------------
#
# The PriorityLevelConfiguration/FlowSchema API types live in
# api/flowcontrolapi.py (the serializer cannot import server modules);
# FlowConfigSource below watches them and rebuilds dispatch on change.


class FlowConfigSource:
    """Watch-driven live APF configuration: when PriorityLevelConfiguration/
    FlowSchema objects exist in the store, they replace the bootstrap config;
    when none do, the bootstrap defaults dispatch. Rebuilds preserve nothing
    across swaps (in-flight requests finish on the old levels — their seats
    release into objects no longer consulted, which is also how the
    reference's config changes drain)."""

    KINDS = ("prioritylevelconfigurations", "flowschemas")
    MANDATORY_SCHEMAS = ("exempt", "system-nodes", "system-components")

    def __init__(self, store, bootstrap: FlowController):
        self._store = store
        self._bootstrap = bootstrap
        self._lock = threading.Lock()
        self._current = bootstrap
        self._list_rebuild_rewatch()

    def _list_rebuild_rewatch(self) -> None:
        # ONE consistent snapshot + watch point: two separate lists would
        # lose an object committed between them (store.list_many exists for
        # exactly this race)
        lists, rv = self._store.list_many(self.KINDS)
        self._rebuild(lists[self.KINDS[0]], lists[self.KINDS[1]])
        self._watch = self._store.watch(kind=set(self.KINDS), since_rv=rv)

    def _rebuild(self, levels, schemas) -> None:
        if not levels or not schemas:
            self._current = self._bootstrap
            return
        try:
            built_levels = {l.metadata.name: l.to_level() for l in levels}
            # the MANDATORY bootstrap configuration survives every custom
            # config (the reference always merges it back): without the
            # exempt/system levels a saturated custom level would 429 the
            # control plane — including the DELETE that removes the bad
            # config. User objects override same-named entries.
            for name, lvl in self._bootstrap.levels.items():
                built_levels.setdefault(name, lvl)
            ordered = sorted(schemas, key=lambda s: s.matching_precedence)
            built = [s.to_schema() for s in ordered]
            user_names = {s.name for s in built}
            mandatory = [s for s in self._bootstrap.schemas
                         if s.name in self.MANDATORY_SCHEMAS
                         and s.name not in user_names]
            built = mandatory + built
            last = built[-1]
            if not ("*" in last.verbs and "*" in last.resources
                    and "*" in last.users and "*" in last.groups):
                # the synthesized catch-all must land on a LIMITED level —
                # an arbitrary (possibly Exempt) target would fail open
                target = next(
                    (n for n in ("global-default", *built_levels)
                     if n in built_levels and not built_levels[n].exempt),
                    None)
                if target is None:
                    raise ValueError("no Limited level for the catch-all")
                built.append(FlowSchema("catch-all", target))
            self._current = FlowController(list(built_levels.values()), built)
        except ValueError:
            # inconsistent objects (schema naming a missing level): keep
            # serving the previous configuration rather than failing open
            pass

    def _sync(self) -> None:
        if self._watch.terminated:
            self._list_rebuild_rewatch()
            return
        events = self._watch.drain()
        if events:
            lists, _rv = self._store.list_many(self.KINDS)
            self._rebuild(lists[self.KINDS[0]], lists[self.KINDS[1]])

    def classify(self, user, verb: str, resource: str) -> PriorityLevel:
        with self._lock:
            self._sync()
            return self._current.classify(user, verb, resource)

    def stats(self):
        with self._lock:
            self._sync()
            return self._current.stats()
