"""Minimal Prometheus-style metrics registry (component-base/metrics analog).

reference: staging/src/k8s.io/component-base/metrics — counters, gauges, and
histograms with a text exposition at /metrics. The scheduler records the same
key series the reference does (pkg/scheduler/metrics/metrics.go:171,226).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                lbl = ",".join(f'{k}="{val}"' for k, val in key)
                out.append(f"{self.name}{{{lbl}}} {v}" if lbl else f"{self.name} {v}")
        return out


class Gauge(Counter):
    def set(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def render(self) -> List[str]:
        out = super().render()
        out[1] = f"# TYPE {self.name} gauge"
        return out


class Histogram:
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 30)

    def __init__(self, name: str, help_: str = "", buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._total += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[i]
                out.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
            out.append(f'{self.name}_bucket{{le="+Inf"}} {self._total}')
            out.append(f"{self.name}_sum {self._sum}")
            out.append(f"{self.name}_count {self._total}")
        return out


class Registry:
    def __init__(self):
        self._metrics: List = []
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._add(Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._add(Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", buckets=Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self._add(Histogram(name, help_, buckets))

    def _add(self, m):
        with self._lock:
            self._metrics.append(m)
        return m

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


global_registry = Registry()

# the scheduler's key series (metrics/metrics.go)
scheduling_attempts = global_registry.counter(
    "scheduler_schedule_attempts_total", "Scheduling attempts by result")
scheduling_attempt_duration = global_registry.histogram(
    "scheduler_scheduling_attempt_duration_seconds", "Scheduling attempt latency")
pending_pods = global_registry.gauge(
    "scheduler_pending_pods", "Pending pods by queue")
batch_solve_duration = global_registry.histogram(
    "scheduler_batch_solve_duration_seconds", "TPU batch solve latency")
batch_size_gauge = global_registry.gauge(
    "scheduler_batch_size", "Pods in the last solved batch")
