"""Minimal Prometheus-style metrics registry (component-base/metrics analog).

reference: staging/src/k8s.io/component-base/metrics — counters, gauges, and
histograms with a text exposition at /metrics. The scheduler records the same
key series the reference does (pkg/scheduler/metrics/metrics.go:171,226).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple


def escape_label_value(value) -> str:
    """Prometheus text-format label escaping (backslash, double-quote,
    newline — exposition format spec). Pod names and failure messages flow
    into label values, so unescaped quotes/backslashes would corrupt the
    exposition for any real scraper."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _render_labels(key: Tuple) -> str:
    return ",".join(f'{k}="{escape_label_value(v)}"' for k, v in key)


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def value(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                lbl = _render_labels(key)
                out.append(f"{self.name}{{{lbl}}} {v}" if lbl else f"{self.name} {v}")
        return out


class Gauge(Counter):
    def set(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def render(self) -> List[str]:
        out = super().render()
        out[1] = f"# TYPE {self.name} gauge"
        return out


class Histogram:
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 30)

    def __init__(self, name: str, help_: str = "", buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self._bucket_arr = None  # lazy numpy mirror for bucket_counts
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._total += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def observe_n(self, value: float, n: int) -> None:
        """n observations of ONE value under a single lock acquisition — the
        coalesced-event shape (ISSUE 9): a CoalescedEvent delivery carries
        len(events) objects that all share the batch's commit stamp, so the
        propagation histogram takes one bucket probe for the whole batch."""
        if n <= 0:
            return
        with self._lock:
            self._sum += value * n
            self._total += n
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += n
                    return
            self._counts[-1] += n

    def counts_snapshot(self) -> Tuple[List[int], float, int]:
        """(bucket counts incl. +Inf, sum, total) under the lock — lets a
        reader merge several same-layout histograms (the per-kind propagation
        children) into one distribution via observe_counts."""
        with self._lock:
            return list(self._counts), self._sum, self._total

    def bucket_counts(self, values):
        """One numpy bucket pass over a chunk of samples WITHOUT mutating
        this histogram: (counts, sum, n) for observe_counts(), so a single
        pass can feed several histograms with identical bucket layouts (the
        tracer's private latency histogram + the process-wide Prometheus
        series — the 100k-pod window must not pay the bucket pass twice).
        Bucket semantics identical to observe(): value <= bound counts into
        that bucket, overflow into +Inf. None for an empty chunk."""
        import numpy as np

        arr = np.asarray(values, dtype=np.float64)
        if arr.size == 0:
            return None
        ba = self._bucket_arr
        if ba is None:
            ba = self._bucket_arr = np.asarray(self.buckets,
                                               dtype=np.float64)
        idx = np.searchsorted(ba, arr, side="left")
        counts = np.bincount(idx, minlength=len(self.buckets) + 1).tolist()
        return counts, float(arr.sum()), int(arr.size)

    def observe_counts(self, counts, total_sum: float, n: int) -> None:
        """Merge a bucket_counts() result — ONE lock acquisition per chunk.
        The caller guarantees the bucket layout matches."""
        with self._lock:
            for i, c in enumerate(counts):
                if c:
                    self._counts[i] += c
            self._sum += total_sum
            self._total += n

    def observe_many(self, values) -> None:
        """Bulk observation: one numpy bucket pass + ONE lock acquisition
        for a whole chunk of samples."""
        res = self.bucket_counts(values)
        if res is not None:
            self.observe_counts(*res)

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate (the histogram_quantile()
        formula: find the bucket holding rank q*count, interpolate linearly
        inside it). Error is bounded by the bucket width — pick log-spaced
        buckets sized to the tolerance the consumer needs. Values landing in
        the +Inf bucket clamp to the highest finite bound (the PromQL
        convention). None when empty."""
        with self._lock:
            counts = list(self._counts)
            total = self._total
        if total == 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if not c:
                continue
            cum += c
            if cum >= rank:
                if i >= len(self.buckets):
                    return float(self.buckets[-1]) if self.buckets else 0.0
                lo = float(self.buckets[i - 1]) if i else 0.0
                hi = float(self.buckets[i])
                frac = (rank - (cum - c)) / c
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
        return float(self.buckets[-1]) if self.buckets else 0.0

    def render(self, label: str = "") -> List[str]:
        """Sample lines; `label` is a pre-rendered 'k="v"' prefix merged into
        each line's label set (LabeledHistogram children)."""
        out = ([] if label else
               [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"])
        sep = f"{label}," if label else ""
        suffix = f"{{{label}}}" if label else ""
        with self._lock:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[i]
                out.append(f'{self.name}_bucket{{{sep}le="{b}"}} {cum}')
            out.append(f'{self.name}_bucket{{{sep}le="+Inf"}} {self._total}')
            out.append(f"{self.name}_sum{suffix} {self._sum}")
            out.append(f"{self.name}_count{suffix} {self._total}")
        return out

    def snapshot(self) -> Tuple[float, int]:
        """(sum, count) under the lock — the stats surfaces read these."""
        with self._lock:
            return self._sum, self._total


class LabeledHistogram:
    """A histogram family keyed by ONE label (the reference's HistogramVec
    restricted to the single-label shape every call site here uses). Children
    are created on first observe; exposition merges the label into each
    bucket/sum/count line."""

    def __init__(self, name: str, help_: str = "", label: str = "le_label",
                 buckets: Sequence[float] = Histogram.DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.label = label
        self.buckets = tuple(buckets)
        self._children: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def child(self, value: str) -> Histogram:
        with self._lock:
            got = self._children.get(value)
            if got is None:
                got = self._children[value] = Histogram(
                    self.name, self.help, self.buckets)
            return got

    def observe(self, value: float, label_value: str) -> None:
        self.child(label_value).observe(value)

    def snapshot(self) -> Dict[str, Tuple[float, int]]:
        with self._lock:
            children = dict(self._children)
        return {k: h.snapshot() for k, h in children.items()}

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            children = sorted(self._children.items())
        for v, h in children:
            out.extend(h.render(
                label=f'{self.label}="{escape_label_value(v)}"'))
        return out


class GaugeFunc:
    """A gauge whose samples come from a callback at read/render time (the
    reference's GaugeFunc / custom collector shape) — for state that lives in
    another component and would be stale or hot-path-expensive to push (the
    per-subscriber watch queue lengths). The callback returns
    [(labels dict, value), ...]; a raising callback renders nothing rather
    than corrupting the whole /metrics page."""

    def __init__(self, name: str, help_: str = "", fn=None):
        self.name = name
        self.help = help_
        self._fn = fn

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        if self._fn is None:
            return []
        try:
            return list(self._fn())
        except Exception:
            return []

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        for labels, v in self.samples():
            lbl = _render_labels(tuple(sorted(labels.items())))
            out.append(f"{self.name}{{{lbl}}} {v}" if lbl
                       else f"{self.name} {v}")
        return out


class Registry:
    def __init__(self):
        self._metrics: List = []
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._add(Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._add(Gauge(name, help_))

    def gauge_func(self, name: str, help_: str = "", fn=None) -> GaugeFunc:
        return self._add(GaugeFunc(name, help_, fn))

    def histogram(self, name: str, help_: str = "", buckets=Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self._add(Histogram(name, help_, buckets))

    def labeled_histogram(self, name: str, help_: str = "", label: str = "label",
                          buckets=Histogram.DEFAULT_BUCKETS) -> LabeledHistogram:
        return self._add(LabeledHistogram(name, help_, label, buckets))

    def _add(self, m):
        with self._lock:
            self._metrics.append(m)
        return m

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


global_registry = Registry()

# the scheduler's key series (metrics/metrics.go)
scheduling_attempts = global_registry.counter(
    "scheduler_schedule_attempts_total", "Scheduling attempts by result")
scheduling_attempt_duration = global_registry.histogram(
    "scheduler_scheduling_attempt_duration_seconds", "Scheduling attempt latency")
pending_pods = global_registry.gauge(
    "scheduler_pending_pods", "Pending pods by queue")
batch_solve_duration = global_registry.labeled_histogram(
    "scheduler_batch_solve_duration_seconds",
    "TPU batch solve latency by outcome", label="outcome")
batch_size_gauge = global_registry.gauge(
    "scheduler_batch_size", "Pods in the last solved batch")

# per-stage timing of the batched schedule->bind->confirm loop (the
# extension-point histograms of framework_duration_seconds, reframed for the
# pipeline stages the ROADMAP table tracks). Buckets reach down to 100us:
# most stages of a small batch land well under the serial path's 1ms floor.
STAGE_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10)
batch_stage_duration = global_registry.labeled_histogram(
    "scheduler_batch_stage_duration_seconds",
    "Batched pipeline stage latency", label="stage", buckets=STAGE_BUCKETS)

# failure-domain observability (ISSUE 6): the solver circuit breaker's live
# state and the pipeline's transient-retry volume by stage
solver_breaker_state = global_registry.gauge(
    "scheduler_solver_breaker_state",
    "Solver circuit breaker state (0 closed, 1 half-open, 2 open)")
batch_retries_total = global_registry.counter(
    "scheduler_batch_retries_total",
    "Pods requeued (stage=solve/assume/dispatch/worker) or chunks retried "
    "(stage=bind) on transient pipeline failures, by stage and reason")

# pod-latency observability (ISSUE 7): queue depth per tier + oldest-pending
# age (updated per pump, never per pod — scheduler/batch.py throttles the
# depth scan), and the aggregate submit->bound latency of EVERY pod, observed
# in bulk per bind chunk from batch-boundary timestamps (scheduler/podtrace.py)
queue_depth = global_registry.gauge(
    "scheduler_queue_depth",
    "Queued pods by tier (active / backoff / unschedulable / gang_staged)")
queue_oldest_age = global_registry.gauge(
    "scheduler_queue_oldest_pending_age_seconds",
    "Age of the oldest pod still waiting in any queue tier")
# log-spaced out to 5 minutes: submit->bound spans queue wait + solve + bind,
# and a chaos/backoff excursion must land in a finite bucket, not +Inf
E2E_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                       0.5, 1, 2.5, 5, 10, 30, 60, 120, 300)
pod_e2e_latency = global_registry.histogram(
    "scheduler_pod_submit_to_bound_seconds",
    "Pod latency from queue admission to committed bind",
    buckets=E2E_LATENCY_BUCKETS)

# store commit latency (ISSUE 7 satellite): one observation per bind_many
# call (a bind-worker chunk) around the two-phase commit — the before/after
# metric for the native-port work on the commit loop
store_bind_many_duration = global_registry.histogram(
    "store_bind_many_duration_seconds",
    "store.bind_many two-phase commit latency per chunk",
    buckets=STAGE_BUCKETS)

# watch-bus telemetry (ISSUE 7 satellite): dropped deliveries were silent —
# a chaos watch.deliver drop or a slow-watcher overflow eviction is now
# countable from /metrics; queue lengths come from live stores at render time
store_watch_dropped = global_registry.counter(
    "store_watch_dropped_deliveries_total",
    "Watch deliveries dropped, by reason (chaos injection / overflow "
    "eviction) and kind")

# watch-propagation tracing (ISSUE 9): commit->delivery latency per kind —
# every event carries its store-commit stamp (shared per batched write) and
# the subscriber's dequeue tap settles the distribution at render time.
# Buckets reach from 100us (in-process same-tick delivery) out to 5 minutes
# (a backlogged subscriber's worst honest lag must land in a finite bucket)
PROPAGATION_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25,
                       0.5, 1, 2.5, 5, 10, 30, 60, 120, 300)
store_watch_propagation = global_registry.labeled_histogram(
    "store_watch_propagation_seconds",
    "Watch event latency from store commit to subscriber dequeue, by kind",
    label="kind", buckets=PROPAGATION_BUCKETS)

_watch_sources: List = []  # weakrefs to APIStores with live watchers
_watch_sources_lock = threading.Lock()


def register_watch_source(ref) -> None:
    """Register a weakref to an APIStore so the subscriber-queue-length
    GaugeFunc can read its watcher list at render time (store/store.py calls
    this on the first watch() subscription)."""
    with _watch_sources_lock:
        if len(_watch_sources) > 64:  # prune dead stores opportunistically
            _watch_sources[:] = [r for r in _watch_sources if r() is not None]
        _watch_sources.append(ref)


def _watch_subscriber_rows():
    """Subscriber rows from every live store — the shared feed of the two
    watch GaugeFuncs below. Uses the subscribers-only telemetry read: one
    scrape must not pay the merged propagation-summary construction twice
    per store just to list subscribers."""
    rows = []
    with _watch_sources_lock:
        refs = list(_watch_sources)
    for ref in refs:
        store = ref()
        if store is None:
            continue
        try:
            rows.extend(store.watch_subscriber_telemetry())
        except Exception:
            continue
    return rows


def _watch_queue_samples():
    return [({"subscriber": sub["id"]}, float(sub["queue_length"]))
            for sub in _watch_subscriber_rows()]


store_watch_queue_length = global_registry.gauge_func(
    "store_watch_subscriber_queue_length",
    "Buffered events per live watch subscriber (read at scrape time)",
    fn=_watch_queue_samples)


def _watch_rv_lag_samples():
    """Delivered-RV lag per live subscriber (ISSUE 9): how many store
    commits behind each watcher's last DEQUEUED event is — the leading
    indicator of a backlogged informer, read from live stores at render
    time like the queue-length gauge."""
    return [({"subscriber": sub["id"]}, float(sub.get("rv_lag", 0)))
            for sub in _watch_subscriber_rows()]


store_watch_rv_lag = global_registry.gauge_func(
    "store_watch_delivered_rv_lag",
    "Store commits not yet dequeued per live watch subscriber",
    fn=_watch_rv_lag_samples)

# reconcile-loop telemetry (ISSUE 9): every controller built on
# controllers/base.py observes ONE duration per process() loop (never per
# key) into this family; workqueue depth comes from the live controller
# registry at render time (obs/reconcile.py)
controller_reconcile_duration = global_registry.labeled_histogram(
    "controller_reconcile_duration_seconds",
    "Reconcile loop latency per controller (one observation per loop)",
    label="controller", buckets=STAGE_BUCKETS)
controller_sync_errors = global_registry.counter(
    "controller_sync_errors_total",
    "sync(key) exceptions per controller (each one also requeues its key)")


def _controller_depth_samples():
    from ..obs.reconcile import workqueue_depth_samples

    return workqueue_depth_samples()


controller_workqueue_depth = global_registry.gauge_func(
    "controller_workqueue_depth",
    "Dirty keys awaiting reconcile per live controller (read at render time)",
    fn=_controller_depth_samples)

# steady-state resource telemetry (ISSUE 13): read from live
# obs/resource.py samplers at render time (the GaugeFunc pattern — the
# sampler thread owns the cadence, /metrics just reads the latest sample)


def _resource_samples(field):
    from ..obs.resource import live_samplers

    out = []
    for s in live_samplers():
        last = s.latest()
        if last is not None and last.get(field) is not None:
            # the sampler label keeps concurrent samplers' series distinct
            # (duplicate identical label sets are invalid exposition)
            out.append(({"sampler": s.id}, float(last[field])))
    return out


process_rss_mb = global_registry.gauge_func(
    "process_resident_memory_megabytes",
    "Resident set size from the resource sampler's latest sample",
    fn=lambda: _resource_samples("rss_mb"))
process_alloc_blocks = global_registry.gauge_func(
    "process_allocated_blocks",
    "sys.getallocatedblocks() from the resource sampler's latest sample "
    "(the deterministic live-object leak signal)",
    fn=lambda: _resource_samples("alloc_blocks"))


def _thread_cpu_samples():
    from ..obs.resource import live_samplers

    out = []
    for s in live_samplers():
        last = s.latest()
        if last is None:
            continue
        for name, t in last.get("threads", {}).items():
            out.append(({"sampler": s.id, "thread": name},
                        float(t["cpu_s"])))
    return out


scheduler_thread_cpu = global_registry.gauge_func(
    "scheduler_thread_cpu_seconds",
    "Per-registered-thread CPU seconds (sched/bind/partition threads; "
    "clock source published by the sampler's honesty flag)",
    fn=_thread_cpu_samples)

# constraint propose-and-repair observability (ISSUE 8): repair-round count
# per constrained batch (a distribution pinned at the REPAIR_MAX_ROUNDS
# bound means the repair loop is thrashing and the residual scan is doing
# the real work) and final-state violations found by the repair check, by
# kind — both observed ONCE per batch from RepairStats, never per pod
constraint_repair_rounds = global_registry.histogram(
    "scheduler_constraint_repair_rounds",
    "Rip-and-repropose rounds per constrained batch (models/repair.py)",
    buckets=(0, 1, 2, 3, 4, 8, 16))
constraint_violations_total = global_registry.counter(
    "scheduler_constraint_violations_total",
    "Constraint violations found by the repair path's final-state check, "
    "by kind (anti_affinity / existing_anti_affinity / affinity / "
    "topology_spread)")

# gang scheduling observability (ROADMAP gang-pipeline open items)
partition_conflicts_total = global_registry.counter(
    "scheduler_partition_conflicts_total",
    "Cross-partition bind races LOST by a partition (the pod was already "
    "bound — an absorbed fact, not an error), by partition")
partition_reroutes_total = global_registry.counter(
    "scheduler_partition_reroutes_total",
    "Pods the dispatch layer re-routed out of a shard that declined them, "
    "by source partition and target (a partition index or 'residual')")
partition_deaths_total = global_registry.counter(
    "scheduler_partition_deaths_total",
    "Hard partition deaths absorbed by the surviving pipelines")
gang_staged = global_registry.gauge(
    "scheduler_gang_staged", "Gang members parked in queue staging")
gang_vetoed_total = global_registry.counter(
    "scheduler_gang_vetoed_total", "Gangs stripped post-solve by reason")
gang_orphan_released_total = global_registry.counter(
    "scheduler_gang_orphan_released_total",
    "Staged gang members released as ordinary pods (PodGroup gone)")
gang_preempted_total = global_registry.counter(
    "scheduler_gang_preempted_total",
    "Gangs admitted by preemption, by reason (victim_cover = a min-cost "
    "victim set on one ICI slice was evicted for the whole quorum)")
gang_quorum_expired_assumes = global_registry.gauge(
    "scheduler_gang_quorum_expired_assumes",
    "Placed gang members still counted toward quorum whose cache entry "
    "expired (the not-yet-fixed quorum leak, now measurable)")
