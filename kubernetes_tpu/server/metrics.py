"""Minimal Prometheus-style metrics registry (component-base/metrics analog).

reference: staging/src/k8s.io/component-base/metrics — counters, gauges, and
histograms with a text exposition at /metrics. The scheduler records the same
key series the reference does (pkg/scheduler/metrics/metrics.go:171,226).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple


def escape_label_value(value) -> str:
    """Prometheus text-format label escaping (backslash, double-quote,
    newline — exposition format spec). Pod names and failure messages flow
    into label values, so unescaped quotes/backslashes would corrupt the
    exposition for any real scraper."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _render_labels(key: Tuple) -> str:
    return ",".join(f'{k}="{escape_label_value(v)}"' for k, v in key)


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def value(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                lbl = _render_labels(key)
                out.append(f"{self.name}{{{lbl}}} {v}" if lbl else f"{self.name} {v}")
        return out


class Gauge(Counter):
    def set(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def render(self) -> List[str]:
        out = super().render()
        out[1] = f"# TYPE {self.name} gauge"
        return out


class Histogram:
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 30)

    def __init__(self, name: str, help_: str = "", buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._total += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def render(self, label: str = "") -> List[str]:
        """Sample lines; `label` is a pre-rendered 'k="v"' prefix merged into
        each line's label set (LabeledHistogram children)."""
        out = ([] if label else
               [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"])
        sep = f"{label}," if label else ""
        suffix = f"{{{label}}}" if label else ""
        with self._lock:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[i]
                out.append(f'{self.name}_bucket{{{sep}le="{b}"}} {cum}')
            out.append(f'{self.name}_bucket{{{sep}le="+Inf"}} {self._total}')
            out.append(f"{self.name}_sum{suffix} {self._sum}")
            out.append(f"{self.name}_count{suffix} {self._total}")
        return out

    def snapshot(self) -> Tuple[float, int]:
        """(sum, count) under the lock — the stats surfaces read these."""
        with self._lock:
            return self._sum, self._total


class LabeledHistogram:
    """A histogram family keyed by ONE label (the reference's HistogramVec
    restricted to the single-label shape every call site here uses). Children
    are created on first observe; exposition merges the label into each
    bucket/sum/count line."""

    def __init__(self, name: str, help_: str = "", label: str = "le_label",
                 buckets: Sequence[float] = Histogram.DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.label = label
        self.buckets = tuple(buckets)
        self._children: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def child(self, value: str) -> Histogram:
        with self._lock:
            got = self._children.get(value)
            if got is None:
                got = self._children[value] = Histogram(
                    self.name, self.help, self.buckets)
            return got

    def observe(self, value: float, label_value: str) -> None:
        self.child(label_value).observe(value)

    def snapshot(self) -> Dict[str, Tuple[float, int]]:
        with self._lock:
            children = dict(self._children)
        return {k: h.snapshot() for k, h in children.items()}

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            children = sorted(self._children.items())
        for v, h in children:
            out.extend(h.render(
                label=f'{self.label}="{escape_label_value(v)}"'))
        return out


class Registry:
    def __init__(self):
        self._metrics: List = []
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._add(Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._add(Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", buckets=Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self._add(Histogram(name, help_, buckets))

    def labeled_histogram(self, name: str, help_: str = "", label: str = "label",
                          buckets=Histogram.DEFAULT_BUCKETS) -> LabeledHistogram:
        return self._add(LabeledHistogram(name, help_, label, buckets))

    def _add(self, m):
        with self._lock:
            self._metrics.append(m)
        return m

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


global_registry = Registry()

# the scheduler's key series (metrics/metrics.go)
scheduling_attempts = global_registry.counter(
    "scheduler_schedule_attempts_total", "Scheduling attempts by result")
scheduling_attempt_duration = global_registry.histogram(
    "scheduler_scheduling_attempt_duration_seconds", "Scheduling attempt latency")
pending_pods = global_registry.gauge(
    "scheduler_pending_pods", "Pending pods by queue")
batch_solve_duration = global_registry.labeled_histogram(
    "scheduler_batch_solve_duration_seconds",
    "TPU batch solve latency by outcome", label="outcome")
batch_size_gauge = global_registry.gauge(
    "scheduler_batch_size", "Pods in the last solved batch")

# per-stage timing of the batched schedule->bind->confirm loop (the
# extension-point histograms of framework_duration_seconds, reframed for the
# pipeline stages the ROADMAP table tracks). Buckets reach down to 100us:
# most stages of a small batch land well under the serial path's 1ms floor.
STAGE_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10)
batch_stage_duration = global_registry.labeled_histogram(
    "scheduler_batch_stage_duration_seconds",
    "Batched pipeline stage latency", label="stage", buckets=STAGE_BUCKETS)

# failure-domain observability (ISSUE 6): the solver circuit breaker's live
# state and the pipeline's transient-retry volume by stage
solver_breaker_state = global_registry.gauge(
    "scheduler_solver_breaker_state",
    "Solver circuit breaker state (0 closed, 1 half-open, 2 open)")
batch_retries_total = global_registry.counter(
    "scheduler_batch_retries_total",
    "Pods requeued (stage=solve/assume/dispatch/worker) or chunks retried "
    "(stage=bind) on transient pipeline failures, by stage and reason")

# gang scheduling observability (ROADMAP gang-pipeline open items)
gang_staged = global_registry.gauge(
    "scheduler_gang_staged", "Gang members parked in queue staging")
gang_vetoed_total = global_registry.counter(
    "scheduler_gang_vetoed_total", "Gangs stripped post-solve by reason")
gang_orphan_released_total = global_registry.counter(
    "scheduler_gang_orphan_released_total",
    "Staged gang members released as ordinary pods (PodGroup gone)")
gang_quorum_expired_assumes = global_registry.gauge(
    "scheduler_gang_quorum_expired_assumes",
    "Placed gang members still counted toward quorum whose cache entry "
    "expired (the not-yet-fixed quorum leak, now measurable)")
