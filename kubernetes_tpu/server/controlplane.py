"""Leader-elected control plane bundle: scheduler + controller manager.

reference: cmd/kube-scheduler/app/server.go:167 (Run wires healthz, then
LeaderElector.Run at :281 — only the leader runs sched.Run) and
cmd/kube-controller-manager/app/controllermanager.go (one elected manager
starting every controller loop). This is the component _cluster_daemon.py and
HA deployments embed: N replicas each construct a ControlPlane; exactly one
drives the cluster at a time, a standby takes over within lease_duration.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from ..store import APIStore
from ..utils.leaderelection import LeaderElector

DEFAULT_CONTROLLERS = (
    "deployment", "replicaset", "statefulset", "daemonset", "job", "cronjob",
    "disruption", "nodelifecycle", "tainteviction", "endpointslice",
    "namespace", "garbagecollector", "resourcequota", "horizontalpodautoscaler",
    "serviceaccount", "ttlafterfinished", "eventttl", "csrapproving",
    "csrcleaner", "podgc", "persistentvolumebinder", "attachdetach",
    "resourceclaim", "apiserviceavailability",
)


def _controller_registry():
    from ..controllers import (
        CSRApprovingController,
        CSRCleanerController,
        CronJobController,
        DaemonSetController,
        DeploymentController,
        DisruptionController,
        EndpointSliceController,
        GarbageCollector,
        HorizontalPodAutoscalerController,
        JobController,
        NamespaceController,
        NodeLifecycleController,
        PodGCController,
        ReplicaSetController,
        ResourceQuotaController,
        EventTTLController,
        ServiceAccountController,
        StatefulSetController,
        TaintEvictionController,
        TTLAfterFinishedController,
        APIServiceAvailabilityController,
        AttachDetachController,
        PersistentVolumeBinder,
        ResourceClaimController,
    )

    return {
        "csrapproving": CSRApprovingController,
        "csrcleaner": CSRCleanerController,
        "serviceaccount": ServiceAccountController,
        "ttlafterfinished": TTLAfterFinishedController,
        "eventttl": EventTTLController,
        "deployment": DeploymentController,
        "replicaset": ReplicaSetController,
        "statefulset": StatefulSetController,
        "daemonset": DaemonSetController,
        "job": JobController,
        "cronjob": CronJobController,
        "disruption": DisruptionController,
        "nodelifecycle": NodeLifecycleController,
        "podgc": PodGCController,
        "tainteviction": TaintEvictionController,
        "endpointslice": EndpointSliceController,
        "namespace": NamespaceController,
        "garbagecollector": GarbageCollector,
        "resourcequota": ResourceQuotaController,
        "horizontalpodautoscaler": HorizontalPodAutoscalerController,
        "persistentvolumebinder": PersistentVolumeBinder,
        "attachdetach": AttachDetachController,
        "resourceclaim": ResourceClaimController,
        "apiserviceavailability": APIServiceAvailabilityController,
    }


class ControlPlane:
    """One control-plane replica. start() joins the election; the winner runs
    the scheduler + the controller set, a loser idles hot. Losing the lease
    stops everything mid-flight (the reference's leaderelection OnStoppedLeading
    exits the process; in-process we stop the loops so a standby's writes can't
    interleave with ours — no double binds)."""

    def __init__(self, store: APIStore, identity: str,
                 controllers: tuple = DEFAULT_CONTROLLERS,
                 use_batch_scheduler: bool = True,
                 scheduler_factory: Optional[Callable] = None,
                 lease_duration: float = 15.0, renew_deadline: float = 10.0,
                 retry_period: float = 2.0, signer=None):
        self.store = store
        self.identity = identity
        # cluster credential signer (auth.SignedTokenAuthenticator); when set,
        # the leader also runs the CSR signing controller
        self.signer = signer
        self.controller_names = tuple(controllers)
        self.use_batch_scheduler = use_batch_scheduler
        self.scheduler_factory = scheduler_factory
        self.scheduler = None
        self.controllers: List = []
        self._lock = threading.Lock()
        self.elector = LeaderElector(
            store, lock_name="kube-controlplane", identity=identity,
            lease_duration=lease_duration, renew_deadline=renew_deadline,
            retry_period=retry_period,
            on_started_leading=self._start_components,
            on_stopped_leading=self._stop_components,
        )

    @property
    def is_leader(self) -> bool:
        return self.elector.is_leader

    def _build_scheduler(self):
        if self.scheduler_factory is not None:
            return self.scheduler_factory(self.store)
        from ..scheduler import Framework
        from ..scheduler.plugins import default_plugins

        if self.use_batch_scheduler:
            from ..scheduler.batch import BatchScheduler

            return BatchScheduler(self.store, Framework(default_plugins()),
                                  solver="auto")
        from ..scheduler.serial import Scheduler

        return Scheduler(self.store, Framework(default_plugins()))

    def _start_components(self) -> None:
        with self._lock:
            registry = _controller_registry()
            self.scheduler = self._build_scheduler()
            self.scheduler.sync()
            self.scheduler.start()
            self.controllers = []
            for name in self.controller_names:
                c = registry[name](self.store)
                c.sync_all()
                c.start()
                self.controllers.append(c)
            if self.signer is not None:
                from ..controllers import CSRSigningController

                c = CSRSigningController(self.store, self.signer)
                c.sync_all()
                c.start()
                self.controllers.append(c)

    def _stop_components(self) -> None:
        with self._lock:
            if self.scheduler is not None:
                self.scheduler.stop()
                self.scheduler = None
            for c in self.controllers:
                c.stop()
            self.controllers = []

    def start(self) -> "ControlPlane":
        self.elector.start()
        return self

    def stop(self) -> None:
        self.elector.stop()  # releases the lease; triggers _stop_components
        self._stop_components()
