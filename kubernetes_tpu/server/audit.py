"""Audit logging — who did what, recorded in the handler chain.

reference: staging/src/k8s.io/apiserver/pkg/audit (+ apis/audit/v1): the
handler chain runs authn -> AUDIT -> authz -> admission; a Policy maps each
request to a level (None/Metadata/Request/RequestResponse) and matching
events are written as JSON lines to a sink. The subset carried here: policy
rules matched in order on user/group/verb/resource, Metadata-level events
(identity + action + outcome; request bodies are not captured), a file sink
plus a bounded in-memory ring for tests and the /auditz debug surface.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

LEVEL_NONE = "None"
LEVEL_METADATA = "Metadata"


@dataclass
class AuditRule:
    """First matching rule decides the level (audit/v1 Policy.rules)."""

    level: str = LEVEL_METADATA
    users: Tuple[str, ...] = ("*",)
    groups: Tuple[str, ...] = ("*",)
    verbs: Tuple[str, ...] = ("*",)
    resources: Tuple[str, ...] = ("*",)

    def matches(self, user, verb: str, resource: str) -> bool:
        """Specified criteria AND together (audit/v1 policy semantics: a rule
        matches only when every non-empty/non-wildcard field matches);
        empty or wildcard fields are unconstrained."""
        if "*" not in self.verbs and verb not in self.verbs:
            return False
        if "*" not in self.resources and resource not in self.resources:
            return False
        checks = []
        if self.users and "*" not in self.users:
            checks.append(user is not None and user.name in self.users)
        if self.groups and "*" not in self.groups:
            checks.append(user is not None
                          and any(g in self.groups for g in user.groups))
        return all(checks)


class AuditPolicy:
    def __init__(self, rules: Sequence[AuditRule] = (),
                 default_level: str = LEVEL_METADATA):
        self.rules = list(rules)
        self.default_level = default_level

    def level_for(self, user, verb: str, resource: str) -> str:
        for r in self.rules:
            if r.matches(user, verb, resource):
                return r.level
        return self.default_level


def default_audit_policy() -> AuditPolicy:
    """The pragmatic default: drop high-volume read-only noise from system
    components (the reference ships a similar recommended policy), audit
    everything else at Metadata."""
    return AuditPolicy(rules=[
        AuditRule(level=LEVEL_NONE, users=(), groups=("system:nodes",),
                  verbs=("get", "list", "watch")),
        AuditRule(level=LEVEL_NONE, verbs=("get", "list", "watch"),
                  resources=("events", "leases", "podlogs"), users=("*",),
                  groups=("*",)),
    ])


class AuditLogger:
    """Metadata-level sink: JSON line per event to an optional file, always
    into a bounded ring (newest last)."""

    def __init__(self, policy: Optional[AuditPolicy] = None,
                 path: Optional[str] = None, ring_size: int = 1000):
        self.policy = policy or default_audit_policy()
        self.path = path
        self.ring_size = ring_size
        self.ring: List[Dict] = []
        self._lock = threading.Lock()
        self._fh = open(path, "a") if path else None

    def log(self, user, verb: str, resource: str, namespace: str,
            name: str, code: int) -> None:
        if self.policy.level_for(user, verb, resource) == LEVEL_NONE:
            return
        ev = {
            "ts": time.time(),
            "level": LEVEL_METADATA,
            "user": getattr(user, "name", "system:anonymous"),
            "groups": list(getattr(user, "groups", ()) or ()),
            "verb": verb,
            "resource": resource,
            "namespace": namespace,
            "name": name,
            "code": code,
        }
        with self._lock:
            self.ring.append(ev)
            if len(self.ring) > self.ring_size:
                del self.ring[:len(self.ring) - self.ring_size]
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(ev) + "\n")
                    self._fh.flush()
                except Exception:
                    pass  # audit must never fail the request

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self.ring)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
