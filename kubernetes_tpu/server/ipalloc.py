"""Service ClusterIP allocation — the apiserver registry's ipallocator.

reference: pkg/registry/core/service/ipallocator (bitmap allocator over the
service CIDR + the repair loop that rebuilds state from stored Services).
Services created without a clusterIP get the next free address; an explicit
request is honored or conflicts; "None" means headless (no allocation);
deletes release the address.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Optional, Set

HEADLESS = "None"


class ClusterIPAllocator:
    def __init__(self, store, cidr: str = "10.96.0.0/16"):
        self.network = ipaddress.ip_network(cidr)
        # skip the network and broadcast addresses like the reference
        self._base = int(self.network.network_address) + 1
        self._size = self.network.num_addresses - 2
        self._store = store
        self._lock = threading.Lock()
        self._used: Set[int] = set()
        self._cursor = 0
        # repair: rebuild from every stored Service (ipallocator/controller),
        # then track the store by WATCH — services die through many paths
        # (namespace sweep, GC, direct store deletes), not only REST DELETE,
        # and every one must release its address
        services, rv = store.list("services")
        for svc in services:
            self._mark(svc.spec.cluster_ip)
        self._watch = store.watch(kind="services", since_rv=rv)

    def _sync_locked(self) -> None:
        """Drain service events (caller holds the lock): deletes release,
        adds/updates mark — covering objects written around the REST layer."""
        if self._watch.terminated:
            # evicted slow watcher: full repair + rewatch (reflector contract)
            self._used.clear()
            services, rv = self._store.list("services")
            for svc in services:
                self._mark(svc.spec.cluster_ip)
            self._watch = self._store.watch(kind="services", since_rv=rv)
            return
        for ev in self._watch.drain():
            ip = ev.obj.spec.cluster_ip
            if ev.type == "DELETED":
                self._release_locked(ip)
            else:
                self._mark(ip)

    def _release_locked(self, ip: Optional[str]) -> None:
        if not ip or ip == HEADLESS:
            return
        try:
            off = int(ipaddress.ip_address(ip)) - self._base
        except ValueError:
            return
        self._used.discard(off)

    def _mark(self, ip: Optional[str]) -> None:
        if not ip or ip == HEADLESS:
            return
        try:
            n = int(ipaddress.ip_address(ip))
        except ValueError:
            return
        off = n - self._base
        if 0 <= off < self._size:
            self._used.add(off)

    def allocate(self, requested: str = "") -> str:
        """-> the assigned IP. Raises ValueError on exhaustion, an
        out-of-range request, or a conflict."""
        with self._lock:
            self._sync_locked()
            if requested:
                try:
                    n = int(ipaddress.ip_address(requested))
                except ValueError:
                    raise ValueError(f"invalid clusterIP {requested!r}")
                off = n - self._base
                if not (0 <= off < self._size):
                    raise ValueError(
                        f"clusterIP {requested} is not in range {self.network}")
                if off in self._used:
                    raise ValueError(f"clusterIP {requested} is already allocated")
                self._used.add(off)
                return requested
            if len(self._used) >= self._size:
                raise ValueError(f"service CIDR {self.network} exhausted")
            # next-free scan from a moving cursor (allocator's round-robin
            # bias keeps freshly released addresses quarantined briefly)
            for i in range(self._size):
                off = (self._cursor + i) % self._size
                if off not in self._used:
                    self._used.add(off)
                    self._cursor = (off + 1) % self._size
                    return str(ipaddress.ip_address(self._base + off))
            raise ValueError(f"service CIDR {self.network} exhausted")

    def release(self, ip: Optional[str]) -> None:
        with self._lock:
            self._sync_locked()
            self._release_locked(ip)
