"""Authentication + authorization for the API server front-end.

reference: the apiserver handler chain runs authn -> audit -> authz ->
admission before any storage access (staging/src/k8s.io/apiserver/pkg/server/
config.go DefaultBuildHandlerChain; SURVEY.md §1 L2).

Carried subset:
  - TokenAuthenticator — static token file authn, the analog of
    `kube-apiserver --token-auth-file` (apiserver/pkg/authentication/
    request/bearertoken + token/tokenfile): `Authorization: Bearer <t>`
    resolves to (user, groups); unknown tokens are 401.
  - RBACAuthorizer — RBAC-lite: rules are (verbs, resources) pairs bound to
    users or groups (staging/src/k8s.io/apiserver/pkg/authorization +
    plugin/pkg/auth/authorizer/rbac). `*` wildcards match everything.
    Unauthorized requests are 403.

Both are optional: a server constructed without them keeps the open,
in-process behavior the test harness uses (identity then comes from the
X-Remote-User header, the authenticating-proxy convention — only trustable
when a trusted proxy sets it, which is why enabling the authenticator
disables the header entirely).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class UserInfo:
    """authentication/user.Info subset."""

    name: str
    groups: Tuple[str, ...] = ()

    @property
    def is_authenticated(self) -> bool:
        return bool(self.name)


ANONYMOUS = UserInfo(name="system:anonymous", groups=("system:unauthenticated",))


class TokenAuthenticator:
    """Static bearer-token table: token -> UserInfo.

    from_csv_lines accepts the reference's token file shape:
    `token,user,uid[,"group1,group2"]` (one per line)."""

    def __init__(self, tokens: Optional[Dict[str, UserInfo]] = None):
        self._tokens: Dict[str, UserInfo] = dict(tokens or {})

    @classmethod
    def from_csv_lines(cls, lines: Sequence[str]) -> "TokenAuthenticator":
        import csv

        tokens: Dict[str, UserInfo] = {}
        for row in csv.reader([l for l in lines if l.strip() and not l.startswith("#")]):
            if len(row) < 2:
                continue
            token, user = row[0].strip(), row[1].strip()
            groups = tuple(g.strip() for g in row[3].split(",")) if len(row) > 3 and row[3] else ()
            tokens[token] = UserInfo(name=user, groups=groups + ("system:authenticated",))
        return cls(tokens)

    @classmethod
    def from_file(cls, path: str) -> "TokenAuthenticator":
        with open(path) as f:
            return cls.from_csv_lines(f.read().splitlines())

    def add(self, token: str, user: str, groups: Sequence[str] = ()) -> None:
        self._tokens[token] = UserInfo(
            name=user, groups=tuple(groups) + ("system:authenticated",))

    def authenticate(self, authorization_header: str) -> Optional[UserInfo]:
        """Returns UserInfo for a valid `Bearer <token>` header, None otherwise."""
        if not authorization_header.startswith("Bearer "):
            return None
        return self._tokens.get(authorization_header[len("Bearer "):].strip())


@dataclass
class Rule:
    """rbac.PolicyRule subset: which verbs on which resources."""

    verbs: Tuple[str, ...]  # get/list/watch/create/update/patch/delete/bind or *
    resources: Tuple[str, ...]  # store kinds or *

    def allows(self, verb: str, resource: str) -> bool:
        return (("*" in self.verbs or verb in self.verbs)
                and ("*" in self.resources or resource in self.resources))


class RBACAuthorizer:
    """Subject (user or `group:<name>`) -> list of rules. Deny by default."""

    def __init__(self):
        self._grants: Dict[str, List[Rule]] = {}

    def grant(self, subject: str, verbs: Sequence[str], resources: Sequence[str]) -> "RBACAuthorizer":
        self._grants.setdefault(subject, []).append(
            Rule(tuple(verbs), tuple(resources)))
        return self

    def authorize(self, user: UserInfo, verb: str, resource: str) -> bool:
        for subject in (user.name, *(f"group:{g}" for g in user.groups)):
            for rule in self._grants.get(subject, ()):
                if rule.allows(verb, resource):
                    return True
        return False


def default_component_authorizer() -> RBACAuthorizer:
    """Grants mirroring the reference's bootstrap cluster roles
    (plugin/pkg/auth/authorizer/rbac/bootstrappolicy): admins everything,
    scheduler binds + reads, nodes status + leases, controllers broad write."""
    a = RBACAuthorizer()
    a.grant("group:system:masters", ["*"], ["*"])
    a.grant("group:system:kube-scheduler",
            ["get", "list", "watch", "update", "patch", "bind"],
            ["pods", "nodes", "namespaces", "persistentvolumes",
             "persistentvolumeclaims", "storageclasses", "csinodes",
             "poddisruptionbudgets", "leases"])
    a.grant("group:system:nodes",
            ["get", "list", "watch", "create", "update", "patch", "delete"],
            ["pods", "nodes", "leases", "events"])
    a.grant("group:system:kube-controller-manager", ["*"], ["*"])
    a.grant("group:system:authenticated", ["get", "list", "watch"], ["*"])
    return a
