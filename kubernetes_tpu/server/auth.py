"""Authentication + authorization for the API server front-end.

reference: the apiserver handler chain runs authn -> audit -> authz ->
admission before any storage access (staging/src/k8s.io/apiserver/pkg/server/
config.go DefaultBuildHandlerChain; SURVEY.md §1 L2).

Carried subset:
  - TokenAuthenticator — static token file authn, the analog of
    `kube-apiserver --token-auth-file` (apiserver/pkg/authentication/
    request/bearertoken + token/tokenfile): `Authorization: Bearer <t>`
    resolves to (user, groups); unknown tokens are 401.
  - RBACAuthorizer — RBAC-lite: rules are (verbs, resources) pairs bound to
    users or groups (staging/src/k8s.io/apiserver/pkg/authorization +
    plugin/pkg/auth/authorizer/rbac). `*` wildcards match everything.
    Unauthorized requests are 403.

Both are optional: a server constructed without them keeps the open,
in-process behavior the test harness uses (identity then comes from the
X-Remote-User header, the authenticating-proxy convention — only trustable
when a trusted proxy sets it, which is why enabling the authenticator
disables the header entirely).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class UserInfo:
    """authentication/user.Info subset."""

    name: str
    groups: Tuple[str, ...] = ()

    @property
    def is_authenticated(self) -> bool:
        return bool(self.name)


ANONYMOUS = UserInfo(name="system:anonymous", groups=("system:unauthenticated",))


class TokenAuthenticator:
    """Static bearer-token table: token -> UserInfo.

    from_csv_lines accepts the reference's token file shape:
    `token,user,uid[,"group1,group2"]` (one per line)."""

    def __init__(self, tokens: Optional[Dict[str, UserInfo]] = None):
        self._tokens: Dict[str, UserInfo] = dict(tokens or {})

    @classmethod
    def from_csv_lines(cls, lines: Sequence[str]) -> "TokenAuthenticator":
        import csv

        tokens: Dict[str, UserInfo] = {}
        for row in csv.reader([l for l in lines if l.strip() and not l.startswith("#")]):
            if len(row) < 2:
                continue
            token, user = row[0].strip(), row[1].strip()
            groups = tuple(g.strip() for g in row[3].split(",")) if len(row) > 3 and row[3] else ()
            tokens[token] = UserInfo(name=user, groups=groups + ("system:authenticated",))
        return cls(tokens)

    @classmethod
    def from_file(cls, path: str) -> "TokenAuthenticator":
        with open(path) as f:
            return cls.from_csv_lines(f.read().splitlines())

    def add(self, token: str, user: str, groups: Sequence[str] = ()) -> None:
        self._tokens[token] = UserInfo(
            name=user, groups=tuple(groups) + ("system:authenticated",))

    def authenticate(self, authorization_header: str) -> Optional[UserInfo]:
        """Returns UserInfo for a valid `Bearer <token>` header, None otherwise."""
        if not authorization_header.startswith("Bearer "):
            return None
        return self._tokens.get(authorization_header[len("Bearer "):].strip())


class SignedTokenAuthenticator:
    """Stateless verifier for cluster-signed bearer credentials — the
    verification half of the certificates flow (reference: the apiserver
    trusting certs chained to the cluster CA; here the CA analog is an HMAC
    key held by the control plane).

    Token wire format: `ktpu.v1.<b64url(payload-json)>.<hex hmac-sha256>`
    with payload {"user": ..., "groups": [...], "exp": epoch-or-null}.
    mint() lives here too so the CSR signing controller and the verifier
    cannot drift."""

    PREFIX = "ktpu.v1."

    def __init__(self, key: bytes, now=None):
        import time

        self._key = key
        self._now = now or time.time

    def mint(self, user: str, groups: Sequence[str] = (),
             expiration_seconds: Optional[int] = None) -> str:
        import base64
        import hashlib
        import hmac
        import json

        payload = {"user": user, "groups": list(groups)}
        if expiration_seconds is not None:
            payload["exp"] = self._now() + expiration_seconds
        body = base64.urlsafe_b64encode(
            json.dumps(payload, sort_keys=True).encode()).decode().rstrip("=")
        sig = hmac.new(self._key, body.encode(), hashlib.sha256).hexdigest()
        return f"{self.PREFIX}{body}.{sig}"

    def authenticate(self, authorization_header: str) -> Optional[UserInfo]:
        import base64
        import hashlib
        import hmac
        import json

        if not authorization_header.startswith("Bearer "):
            return None
        token = authorization_header[len("Bearer "):].strip()
        if not token.startswith(self.PREFIX):
            return None
        rest = token[len(self.PREFIX):]
        body, _, sig = rest.rpartition(".")
        if not body or not sig:
            return None
        want = hmac.new(self._key, body.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            return None
        try:
            pad = "=" * (-len(body) % 4)
            payload = json.loads(base64.urlsafe_b64decode(body + pad))
        except Exception:
            return None
        exp = payload.get("exp")
        if exp is not None and self._now() > exp:
            return None
        return UserInfo(name=payload.get("user", ""),
                        groups=tuple(payload.get("groups") or ())
                        + ("system:authenticated",))


class AuthenticatorChain:
    """First authenticator to recognize the credential wins (the apiserver's
    union authenticator, authentication/request/union)."""

    def __init__(self, authenticators: Sequence):
        self._authns = list(authenticators)

    def authenticate(self, authorization_header: str) -> Optional[UserInfo]:
        for a in self._authns:
            user = a.authenticate(authorization_header)
            if user is not None:
                return user
        return None


@dataclass
class Rule:
    """rbac.PolicyRule subset: which verbs on which resources.
    `except_resources` carves names out of a wildcard resource match — how a
    broad read rule excludes secret payloads without enumerating every
    resource (incl. CRD-served plurals unknown at grant time)."""

    verbs: Tuple[str, ...]  # get/list/watch/create/update/patch/delete/bind or *
    resources: Tuple[str, ...]  # store kinds or *
    except_resources: Tuple[str, ...] = ()

    def allows(self, verb: str, resource: str) -> bool:
        if resource in self.except_resources:
            return False
        return (("*" in self.verbs or verb in self.verbs)
                and ("*" in self.resources or resource in self.resources))


class RBACAuthorizer:
    """Subject (user or `group:<name>`) -> list of rules. Deny by default."""

    def __init__(self):
        self._grants: Dict[str, List[Rule]] = {}

    def grant(self, subject: str, verbs: Sequence[str], resources: Sequence[str],
              except_resources: Sequence[str] = ()) -> "RBACAuthorizer":
        self._grants.setdefault(subject, []).append(
            Rule(tuple(verbs), tuple(resources), tuple(except_resources)))
        return self

    def authorize(self, user: UserInfo, verb: str, resource: str) -> bool:
        for subject in (user.name, *(f"group:{g}" for g in user.groups)):
            for rule in self._grants.get(subject, ()):
                if rule.allows(verb, resource):
                    return True
        return False


def default_component_authorizer() -> RBACAuthorizer:
    """Grants mirroring the reference's bootstrap cluster roles
    (plugin/pkg/auth/authorizer/rbac/bootstrappolicy): admins everything,
    scheduler binds + reads, nodes status + leases, controllers broad write."""
    a = RBACAuthorizer()
    a.grant("group:system:masters", ["*"], ["*"])
    a.grant("group:system:kube-scheduler",
            ["get", "list", "watch", "update", "patch", "bind"],
            ["pods", "nodes", "namespaces", "persistentvolumes",
             "persistentvolumeclaims", "storageclasses", "csinodes",
             "poddisruptionbudgets", "leases"])
    a.grant("group:system:nodes",
            ["get", "list", "watch", "create", "update", "patch", "delete"],
            ["pods", "nodes", "leases", "events", "podlogs",
             "pods/status", "nodes/status",
             # streaming session channels the kubelet answers
             "podexecs", "podportforwards"])
    # nodes may renew their own credential (certificatesigningrequests
    # recognizer allows requestor == requested node identity)
    a.grant("group:system:nodes", ["create", "get", "list", "watch"],
            ["certificatesigningrequests"])
    # nodes resolve pod config payloads (the node authorizer scopes these to
    # pods bound to the node in the reference; kind-level here)
    a.grant("group:system:nodes", ["get", "list", "watch"],
            ["configmaps", "secrets"])
    a.grant("group:system:kube-controller-manager", ["*"], ["*"])
    # authenticated read-all EXCLUDES secrets: no reference bootstrap role
    # puts secret payloads in a wildcard read grant (bootstrappolicy's
    # system:basic-user has nothing; even view/edit enumerate resources).
    # Wildcard-with-carve-out keeps CRD-served plurals readable by default
    # while secrets require an explicit grant.
    a.grant("group:system:authenticated", ["get", "list", "watch"], ["*"],
            # exec stdin/stdout and port-forward bytes are exactly as
            # sensitive as secret payloads: carved out of wildcard reads
            except_resources=("secrets", "podexecs", "podportforwards"))
    return a
