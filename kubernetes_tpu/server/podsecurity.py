"""Pod Security admission: namespace-labelled baseline/restricted levels.

reference: staging/src/k8s.io/pod-security-admission — policy/check_*.go for
the per-field checks, admission/admission.go for the namespace-label
evaluation. The subset carried here is the enforce mode with the checks that
map onto this build's Pod surface:

baseline  — no privileged containers, no host namespaces (hostNetwork/PID/
            IPC), no hostPath volumes, no hostPorts, capability adds limited
            to the baseline allow-list, no Unconfined seccomp.
restricted — baseline plus: runAsNonRoot required, allowPrivilegeEscalation
            must be false, capabilities must drop ALL (only NET_BIND_SERVICE
            may be added back), volume sources limited to the restricted set.

Namespaces opt in via the standard labels:
    pod-security.kubernetes.io/enforce: privileged | baseline | restricted
Unlabelled namespaces are `privileged` (no enforcement), like the reference's
default when no exemption/configuration says otherwise.
"""

from __future__ import annotations

from typing import Any, Dict, List

ENFORCE_LABEL = "pod-security.kubernetes.io/enforce"
LEVELS = ("privileged", "baseline", "restricted")

# capability adds baseline tolerates (policy/check_capabilities_baseline.go)
BASELINE_CAPABILITIES = {
    "AUDIT_WRITE", "CHOWN", "DAC_OVERRIDE", "FOWNER", "FSETID", "KILL",
    "MKNOD", "NET_BIND_SERVICE", "SETFCAP", "SETGID", "SETPCAP", "SETUID",
    "SYS_CHROOT",
}

# volume sources restricted forbids beyond baseline
# (policy/check_restrictedVolumes.go); hostPath is already a baseline
# violation so it is not repeated here
_FORBIDDEN_RESTRICTED_VOLUME_FIELDS = (
    ("gce_pd", "gcePersistentDisk"),
    ("aws_ebs", "awsElasticBlockStore"),
    ("rbd", "rbd"),
    ("iscsi", "iscsi"),
)


def _containers(pod) -> List:
    return list(pod.spec.containers) + list(pod.spec.init_containers)


def _sc(container) -> Dict[str, Any]:
    return container.security_context or {}


def _effective(pod, container, key):
    """Container securityContext wins over pod securityContext (core/v1
    precedence for the fields both levels define)."""
    if key in _sc(container):
        return _sc(container)[key]
    return (pod.spec.security_context or {}).get(key)


def check_baseline(pod) -> List[str]:
    errs: List[str] = []
    if pod.spec.host_network:
        errs.append("hostNetwork is not allowed")
    if pod.spec.host_pid:
        errs.append("hostPID is not allowed")
    if pod.spec.host_ipc:
        errs.append("hostIPC is not allowed")
    for v in pod.spec.volumes:
        if v.host_path:
            errs.append(f"hostPath volume {v.name!r} is not allowed")
    for c in _containers(pod):
        sc = _sc(c)
        if sc.get("privileged"):
            errs.append(f"container {c.name!r}: privileged is not allowed")
        adds = ((sc.get("capabilities") or {}).get("add")) or []
        bad = [a for a in adds if a not in BASELINE_CAPABILITIES]
        if bad:
            errs.append(f"container {c.name!r}: capabilities {bad} not allowed")
        seccomp = _effective(pod, c, "seccompProfile") or {}
        if seccomp.get("type") == "Unconfined":
            errs.append(f"container {c.name!r}: seccompProfile Unconfined "
                        "is not allowed")
        for p in c.ports:
            if p.host_port:
                errs.append(f"container {c.name!r}: hostPort {p.host_port} "
                            "is not allowed")
    return errs


def check_restricted(pod) -> List[str]:
    errs = check_baseline(pod)
    for attr, wire in _FORBIDDEN_RESTRICTED_VOLUME_FIELDS:
        for v in pod.spec.volumes:
            if getattr(v, attr):
                errs.append(f"volume {v.name!r}: {wire} is not allowed")
    for c in _containers(pod):
        sc = _sc(c)
        if _effective(pod, c, "runAsNonRoot") is not True:
            errs.append(f"container {c.name!r}: runAsNonRoot must be true")
        if sc.get("allowPrivilegeEscalation") is not False:
            errs.append(f"container {c.name!r}: allowPrivilegeEscalation "
                        "must be false")
        caps = sc.get("capabilities") or {}
        drops = caps.get("drop") or []
        if "ALL" not in drops:
            errs.append(f"container {c.name!r}: capabilities must drop ALL")
        adds = caps.get("add") or []
        bad = [a for a in adds if a != "NET_BIND_SERVICE"]
        if bad:
            errs.append(f"container {c.name!r}: may only add NET_BIND_SERVICE, "
                        f"got {bad}")
        seccomp = _effective(pod, c, "seccompProfile") or {}
        if seccomp.get("type") not in ("RuntimeDefault", "Localhost"):
            errs.append(f"container {c.name!r}: seccompProfile must be "
                        "RuntimeDefault or Localhost")
    return errs


def check_level(level: str, pod) -> List[str]:
    if level == "baseline":
        return check_baseline(pod)
    if level == "restricted":
        return check_restricted(pod)
    return []
