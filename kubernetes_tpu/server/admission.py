"""Admission chain: mutating then validating plugins between authn and storage.

reference: staging/src/k8s.io/apiserver/pkg/admission (chain execution) and
plugin/pkg/admission/* — the subset carried here: NamespaceLifecycle,
LimitRanger, ResourceQuota, PodTolerationRestriction, NodeRestriction, plus
metadata defaulting. The REST server runs the chain on every create/update;
direct store writes (tests, controllers) bypass it, mirroring how controllers
with etcd access bypass admission in the reference only in the sense that the
chain lives in the apiserver handler path.
"""

from __future__ import annotations

import json
from typing import Callable, List, Optional, Sequence

from ..api.policy import LimitRange, ResourceQuota
from ..api.resources import quantity_milli_value, quantity_value
from ..api.types import Taint, Toleration, new_uid
from ..store import APIStore, NotFoundError
from .podsecurity import ENFORCE_LABEL, LEVELS, check_level

CREATE = "CREATE"
UPDATE = "UPDATE"

# namespaces that always exist (kube-apiserver bootstraps them)
BOOTSTRAP_NAMESPACES = ("default", "kube-system", "kube-public", "kube-node-lease")


class AdmissionError(Exception):
    def __init__(self, message: str, code: int = 403, reason: str = "Forbidden"):
        super().__init__(message)
        self.code = code
        self.reason = reason


class AdmissionPlugin:
    name = "AdmissionPlugin"

    def admit(self, store: APIStore, resource: str, operation: str, obj,
              user: str = "") -> None:
        """Mutating pass: modify obj in place or raise AdmissionError."""

    def validate(self, store: APIStore, resource: str, operation: str, obj,
                 user: str = "") -> None:
        """Validating pass: raise AdmissionError to reject."""


class MetadataDefaulter(AdmissionPlugin):
    """uid + creationTimestamp defaulting (the registry strategies'
    PrepareForCreate in the reference)."""

    name = "MetadataDefaulter"

    def __init__(self, now: Optional[Callable[[], float]] = None):
        import time

        self._now = now or time.time

    def admit(self, store, resource, operation, obj, user="") -> None:
        if operation != CREATE:
            return
        if not obj.metadata.uid:
            obj.metadata.uid = new_uid()
        if not obj.metadata.creation_timestamp:
            obj.metadata.creation_timestamp = self._now()


class NamespaceLifecycle(AdmissionPlugin):
    """Rejects writes into missing or terminating namespaces
    (plugin/pkg/admission/namespace/lifecycle)."""

    name = "NamespaceLifecycle"

    def validate(self, store, resource, operation, obj, user="") -> None:
        ns = getattr(obj.metadata, "namespace", "")
        if not ns or resource == "namespaces" or operation == "DELETE":
            return  # deletes must work even when the namespace is already gone
        if ns in BOOTSTRAP_NAMESPACES:
            return
        try:
            namespace = store.get("namespaces", ns)
        except NotFoundError:
            raise AdmissionError(f'namespace "{ns}" not found', code=404,
                                 reason="NotFound")
        if namespace.metadata.deletion_timestamp is not None and operation == CREATE:
            raise AdmissionError(
                f'namespace "{ns}" is terminating: no new objects allowed')


class LimitRanger(AdmissionPlugin):
    """Applies LimitRange container defaults and enforces min/max
    (plugin/pkg/admission/limitranger)."""

    name = "LimitRanger"

    def admit(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        ranges, _ = store.list(
            "limitranges", lambda lr: lr.metadata.namespace == obj.metadata.namespace)
        for lr in ranges:
            for c in list(obj.spec.containers) + list(obj.spec.init_containers):
                # a manifest may carry "resources": {"requests": null}
                if not isinstance(c.resources, dict):
                    c.resources = {}
                if not isinstance(c.resources.get("requests"), dict):
                    c.resources["requests"] = {}
                if not isinstance(c.resources.get("limits"), dict):
                    c.resources["limits"] = {}
                for key, val in lr.default_requests.items():
                    c.resources["requests"].setdefault(key, val)
                for key, val in lr.default_limits.items():
                    c.resources["limits"].setdefault(key, val)

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        ranges, _ = store.list(
            "limitranges", lambda lr: lr.metadata.namespace == obj.metadata.namespace)
        for lr in ranges:
            for c in list(obj.spec.containers) + list(obj.spec.init_containers):
                requests = (c.resources or {}).get("requests") or {}
                for key, cap in lr.max.items():
                    have = requests.get(key)
                    if have is not None and _cmp(key, have) > _cmp(key, cap):
                        raise AdmissionError(
                            f"maximum {key} usage per Container is {cap}, but "
                            f"request is {have}")
                for key, floor in lr.min.items():
                    have = requests.get(key)
                    if have is not None and _cmp(key, have) < _cmp(key, floor):
                        raise AdmissionError(
                            f"minimum {key} usage per Container is {floor}, but "
                            f"request is {have}")


def _cmp(key: str, value) -> int:
    return quantity_milli_value(value) if key == "cpu" else quantity_value(value)


class ResourceQuotaAdmission(AdmissionPlugin):
    """Rejects pod creates that would exceed any ResourceQuota hard limit
    (plugin/pkg/admission/resourcequota). Usage is recomputed live so the
    check does not depend on the quota controller's status lag."""

    name = "ResourceQuota"

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        ns = obj.metadata.namespace
        quotas, _ = store.list("resourcequotas", lambda q: q.metadata.namespace == ns)
        if not quotas:
            return
        pods, _ = store.list(
            "pods", lambda p: p.metadata.namespace == ns and not p.is_terminal())
        used_cpu = sum(
            quantity_milli_value((c.resources.get("requests") or {}).get("cpu", 0))
            for p in pods for c in list(p.spec.containers) + list(p.spec.init_containers))
        used_mem = sum(
            quantity_value((c.resources.get("requests") or {}).get("memory", 0))
            for p in pods for c in list(p.spec.containers) + list(p.spec.init_containers))
        new_cpu = sum(
            quantity_milli_value((c.resources.get("requests") or {}).get("cpu", 0))
            for c in list(obj.spec.containers) + list(obj.spec.init_containers))
        new_mem = sum(
            quantity_value((c.resources.get("requests") or {}).get("memory", 0))
            for c in list(obj.spec.containers) + list(obj.spec.init_containers))
        for quota in quotas:
            for key, hard in quota.hard.items():
                if key in ("requests.cpu", "cpu"):
                    if used_cpu + new_cpu > quantity_milli_value(hard):
                        self._reject(quota, key, hard)
                elif key in ("requests.memory", "memory"):
                    if used_mem + new_mem > quantity_value(hard):
                        self._reject(quota, key, hard)
                elif key == "pods":
                    if len(pods) + 1 > int(hard):
                        self._reject(quota, key, hard)

    @staticmethod
    def _reject(quota: ResourceQuota, key: str, hard) -> None:
        raise AdmissionError(
            f"exceeded quota: {quota.metadata.name}, limited: {key}={hard}")


class PodTolerationRestriction(AdmissionPlugin):
    """Merges namespace default tolerations into pods
    (plugin/pkg/admission/podtolerationrestriction; annotation
    scheduler.alpha.kubernetes.io/defaultTolerations)."""

    name = "PodTolerationRestriction"
    DEFAULT_KEY = "scheduler.alpha.kubernetes.io/defaultTolerations"
    WHITELIST_KEY = "scheduler.alpha.kubernetes.io/tolerationsWhitelist"

    def admit(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        ns = self._namespace(store, obj)
        if ns is None:
            return
        raw = ns.metadata.annotations.get(self.DEFAULT_KEY)
        if raw:
            for t in json.loads(raw):
                tol = Toleration.from_dict(t)
                if tol not in obj.spec.tolerations:
                    obj.spec.tolerations.append(tol)

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        ns = self._namespace(store, obj)
        if ns is None:
            return
        raw = ns.metadata.annotations.get(self.WHITELIST_KEY)
        if not raw:
            return
        allowed = [Toleration.from_dict(t) for t in json.loads(raw)]
        for tol in obj.spec.tolerations:
            if tol not in allowed:
                raise AdmissionError(
                    f"pod toleration {tol.key!r} not in the namespace whitelist")

    @staticmethod
    def _namespace(store, obj):
        try:
            return store.get("namespaces", obj.metadata.namespace)
        except NotFoundError:
            return None


class NodeRestriction(AdmissionPlugin):
    """A node identity (system:node:<name>) may only modify its own Node object
    and pods bound to it (plugin/pkg/admission/noderestriction)."""

    name = "NodeRestriction"
    PREFIX = "system:node:"

    def validate(self, store, resource, operation, obj, user="") -> None:
        if not user.startswith(self.PREFIX):
            return
        node_name = user[len(self.PREFIX):]
        if resource == "nodes" and obj.metadata.name != node_name:
            raise AdmissionError(
                f"node {node_name!r} may not modify node {obj.metadata.name!r}")
        if resource == "pods" and obj.spec.node_name != node_name:
            raise AdmissionError(
                f"node {node_name!r} may only write pods bound to itself")


class PriorityAdmission(AdmissionPlugin):
    """Resolves pod.spec.priorityClassName into spec.priority and
    spec.preemptionPolicy (plugin/pkg/admission/priority): unknown class
    names are rejected; a globalDefault class applies to pods that name none;
    the system- prefix is reserved."""

    name = "Priority"
    SYSTEM_CLASSES = {
        "system-cluster-critical": 2_000_000_000,
        "system-node-critical": 2_000_001_000,
    }

    def admit(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        name = obj.spec.priority_class_name
        if not name:
            # the class value is AUTHORITATIVE: a client-supplied
            # spec.priority is always overwritten (0 without a default class)
            # — otherwise any tenant could self-assign system priority
            classes, _ = store.list("priorityclasses", lambda c: c.global_default)
            if classes:
                # ties between multiple globalDefault classes resolve to the
                # highest value (priority plugin getDefaultPriority)
                default = max(classes, key=lambda c: c.value)
                obj.spec.priority_class_name = default.metadata.name
                obj.spec.priority = default.value
                obj.spec.preemption_policy = default.preemption_policy
            else:
                obj.spec.priority = 0
            return
        if name in self.SYSTEM_CLASSES:
            # system classes are reserved for kube-system workloads
            if obj.metadata.namespace != "kube-system":
                raise AdmissionError(
                    f"pods with {name} priorityClass may only be created in "
                    "the kube-system namespace")
            obj.spec.priority = self.SYSTEM_CLASSES[name]
            # the class value is authoritative here too — system-critical
            # pods must be able to preempt
            obj.spec.preemption_policy = "PreemptLowerPriority"
            return
        try:
            pc = store.get("priorityclasses", name)
        except NotFoundError:
            raise AdmissionError(f"no PriorityClass with name {name!r} was found",
                                 code=400, reason="Invalid")
        obj.spec.priority = pc.value
        obj.spec.preemption_policy = pc.preemption_policy

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource == "pods" and operation == UPDATE:
            # pod priority is immutable after create (api validation in the
            # reference); without this a PUT could self-assign system priority
            try:
                existing = store.get("pods", obj.key)
            except NotFoundError:
                return
            if (obj.spec.priority != existing.spec.priority
                    or obj.spec.priority_class_name != existing.spec.priority_class_name):
                raise AdmissionError(
                    "pod updates may not change priority or priorityClassName",
                    code=422, reason="Invalid")
            return
        if resource != "priorityclasses" or operation != CREATE:
            return
        if obj.metadata.name.startswith("system-") \
                and obj.metadata.name not in self.SYSTEM_CLASSES:
            raise AdmissionError(
                "the system- prefix is reserved for system priority classes")


class DefaultTolerationSeconds(AdmissionPlugin):
    """Adds the 300s not-ready/unreachable NoExecute tolerations every pod
    gets (plugin/pkg/admission/defaulttolerationseconds) so taint eviction has
    the standard grace period."""

    name = "DefaultTolerationSeconds"
    SECONDS = 300
    KEYS = ("node.kubernetes.io/not-ready", "node.kubernetes.io/unreachable")

    def admit(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        for key in self.KEYS:
            # skip only when an existing toleration ACTUALLY tolerates the
            # taint (ToleratesTaint in the reference plugin) — a key-equal
            # toleration with a non-matching value would not
            taint = Taint(key=key, effect="NoExecute")
            if any(t.tolerates(taint) for t in obj.spec.tolerations):
                continue
            obj.spec.tolerations.append(Toleration(
                key=key, operator="Exists", effect="NoExecute",
                toleration_seconds=self.SECONDS))


class DefaultStorageClass(AdmissionPlugin):
    """PVCs without a storageClassName get the cluster default class
    (plugin/pkg/admission/storage/storageclass/setdefault)."""

    name = "DefaultStorageClass"

    def admit(self, store, resource, operation, obj, user="") -> None:
        if resource != "persistentvolumeclaims" or operation != CREATE:
            return
        # None = "use the default class"; an EXPLICIT "" requests classless
        # static binding and must not be overwritten (setdefault plugin only
        # defaults when the field is nil)
        if obj.spec.storage_class_name is not None:
            return
        classes, _ = store.list("storageclasses", lambda c: c.is_default)
        if classes:
            # several defaults: newest creationTimestamp wins (setdefault
            # plugin tie-break)
            newest = max(classes, key=lambda c: c.metadata.creation_timestamp)
            obj.spec.storage_class_name = newest.metadata.name


class AlwaysPullImages(AdmissionPlugin):
    """Forces imagePullPolicy=Always (plugin/pkg/admission/alwayspullimages —
    multi-tenant image-credential protection). NOT in the default chain, like
    the reference; opt in via AdmissionChain([...])."""

    name = "AlwaysPullImages"

    def admit(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            c.image_pull_policy = "Always"

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            if c.image_pull_policy != "Always":
                raise AdmissionError(
                    f"container {c.name!r} must have imagePullPolicy Always")


class ServiceAccountAdmission(AdmissionPlugin):
    """Defaults pod.spec.serviceAccountName to 'default' and requires an
    explicitly named non-default SA to exist
    (plugin/pkg/admission/serviceaccount). The implicit 'default' SA is not
    required to exist yet — the serviceaccount controller creates it
    asynchronously, same bootstrap tolerance as the reference's retry loop."""

    name = "ServiceAccount"

    def admit(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        if not obj.spec.service_account_name:
            obj.spec.service_account_name = "default"

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        name = obj.spec.service_account_name
        if name in ("", "default"):
            return
        try:
            store.get("serviceaccounts", f"{obj.metadata.namespace}/{name}")
        except NotFoundError:
            raise AdmissionError(
                f"service account {obj.metadata.namespace}/{name} was not found",
                code=403, reason="Forbidden")


class PodSecurityAdmission(AdmissionPlugin):
    """Enforces the namespace's pod-security.kubernetes.io/enforce level on
    pod writes (staging/src/k8s.io/pod-security-admission/admission). The
    level checks live in podsecurity.py; unlabelled namespaces are
    `privileged` (no enforcement)."""

    name = "PodSecurity"

    def validate(self, store, resource, operation, obj, user="") -> None:
        # CREATE only: labelling a namespace must leave existing pods
        # updatable (status writes, labels) — the reference's
        # isSignificantPodUpdate exemption; pod specs are near-immutable
        # anyway, so create-time is where the policy bites
        if resource != "pods" or operation != CREATE:
            return
        try:
            ns = store.get("namespaces", obj.metadata.namespace)
        except NotFoundError:
            return  # NamespaceLifecycle owns this rejection
        level = ns.metadata.labels.get(ENFORCE_LABEL, "privileged")
        if level not in LEVELS:
            level = "restricted"  # unknown label value: fail closed
        errs = check_level(level, obj)
        if errs:
            raise AdmissionError(
                f"violates PodSecurity \"{level}\": " + "; ".join(errs),
                code=403, reason="Forbidden")


class ExtendedResourceToleration(AdmissionPlugin):
    """Pods requesting extended resources (anything not a core compute
    resource) get a matching toleration, so dedicated device nodes can be
    tainted with their resource name
    (plugin/pkg/admission/extendedresourcetoleration)."""

    name = "ExtendedResourceToleration"

    @staticmethod
    def is_extended(key: str) -> bool:
        """helper.IsExtendedResourceName: domain-qualified, not a native
        kubernetes.io resource, not a hugepages size."""
        if "/" not in key:
            return False
        domain = key.split("/", 1)[0]
        if domain == "kubernetes.io" or domain.endswith(".kubernetes.io"):
            return False
        return not key.startswith("requests.")

    def admit(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE:
            return
        extended = set()
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            for section in ("requests", "limits"):
                for key in ((c.resources or {}).get(section) or {}):
                    if self.is_extended(key):
                        extended.add(key)
        for key in sorted(extended):
            if not any(t.key == key for t in obj.spec.tolerations):
                obj.spec.tolerations.append(Toleration(
                    key=key, operator="Exists", effect="NoSchedule"))


class TaintNodesByCondition(AdmissionPlugin):
    """New nodes start tainted not-ready NoSchedule until node_lifecycle
    observes a Ready condition (plugin/pkg/admission/nodetaint) — closes the
    window where a scheduler could bind to a node whose kubelet has not
    reported yet."""

    name = "TaintNodesByCondition"
    NOT_READY = "node.kubernetes.io/not-ready"

    def admit(self, store, resource, operation, obj, user="") -> None:
        if resource != "nodes" or operation != CREATE:
            return
        if not any(t.key == self.NOT_READY for t in obj.spec.taints):
            obj.spec.taints.append(Taint(key=self.NOT_READY, effect="NoSchedule"))


class LimitPodHardAntiAffinityTopology(AdmissionPlugin):
    """Rejects required pod anti-affinity with a topologyKey other than
    kubernetes.io/hostname (plugin/pkg/admission/antiaffinity) — zone-wide
    hard anti-affinity lets one tenant fence whole failure domains. NOT in
    the default chain, same as the reference."""

    name = "LimitPodHardAntiAffinityTopology"
    HOSTNAME = "kubernetes.io/hostname"

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource != "pods" or operation != CREATE or obj.spec.affinity is None:
            return
        for term in obj.spec.affinity.pod_anti_affinity_required:
            if term.topology_key != self.HOSTNAME:
                raise AdmissionError(
                    "affinity.podAntiAffinity.requiredDuringScheduling... "
                    f"topologyKey must be {self.HOSTNAME!r}, got "
                    f"{term.topology_key!r}", code=422, reason="Invalid")


class WorkloadValidation(AdmissionPlugin):
    """API-validation subset for workload specs the controllers depend on
    (pkg/apis/batch/validation): Indexed jobs require completions, and
    parallelism/completions/backoffLimit may not be negative."""

    name = "WorkloadValidation"

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource == "cronjobs" and operation in (CREATE, UPDATE):
            # the schedule and timeZone must parse NOW — a bad value stored
            # would make every controller sync raise forever
            from ..utils.cron import CronSchedule

            try:
                CronSchedule(obj.spec.schedule, tz=obj.spec.time_zone)
            except ValueError as e:
                raise AdmissionError(f"spec.schedule/timeZone: {e}",
                                     code=422, reason="Invalid")
            return
        if resource != "jobs" or operation not in (CREATE, UPDATE):
            return
        spec = obj.spec
        if spec.completion_mode == "Indexed" and spec.completions is None:
            raise AdmissionError(
                "spec.completions: Required value: when completion mode is "
                "Indexed", code=422, reason="Invalid")
        for name, val in (("parallelism", spec.parallelism),
                          ("completions", spec.completions),
                          ("backoffLimit", spec.backoff_limit)):
            if val is not None and val < 0:
                raise AdmissionError(
                    f"spec.{name}: must be greater than or equal to 0",
                    code=422, reason="Invalid")
        if operation == UPDATE:
            # completionMode/completions are immutable (batch validation):
            # flipping a running job to Indexed would orphan its index-less
            # pods and double-schedule every index
            try:
                existing = store.get(
                    "jobs", f"{obj.metadata.namespace}/{obj.metadata.name}")
            except NotFoundError:
                return
            if existing.spec.completion_mode != spec.completion_mode:
                raise AdmissionError("spec.completionMode is immutable",
                                     code=422, reason="Invalid")
            if existing.spec.completions != spec.completions:
                raise AdmissionError("spec.completions is immutable",
                                     code=422, reason="Invalid")


class DefaultIngressClass(AdmissionPlugin):
    """Ingresses without an ingressClassName get the cluster default class
    (plugin/pkg/admission/network/defaultingressclass) — the
    is-default-class annotation drives it, ties resolve to the newest."""

    name = "DefaultIngressClass"

    def admit(self, store, resource, operation, obj, user="") -> None:
        if resource != "ingresses" or operation != CREATE:
            return
        if obj.ingress_class_name is not None:
            return
        classes, _ = store.list("ingressclasses", lambda c: c.is_default)
        if classes:
            newest = max(classes, key=lambda c: c.metadata.creation_timestamp)
            obj.ingress_class_name = newest.metadata.name


class ImmutableConfigAdmission(AdmissionPlugin):
    """Enforces ConfigMap/Secret immutability (validation.Validate{ConfigMap,
    Secret}Update): once immutable, payload may not change and the flag may
    not be cleared — only deletion releases the name."""

    name = "ImmutableConfig"

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource not in ("configmaps", "secrets") or operation != UPDATE:
            return
        try:
            existing = store.get(
                resource, f"{obj.metadata.namespace}/{obj.metadata.name}")
        except NotFoundError:
            return
        if not existing.immutable:
            return
        if not obj.immutable:
            raise AdmissionError(
                f"{resource[:-1]} is immutable: the flag cannot be unset",
                code=422, reason="Invalid")
        changed = existing.data != obj.data
        if resource == "configmaps":
            changed = changed or existing.binary_data != obj.binary_data
        else:
            changed = changed or existing.type != obj.type
        if changed:
            raise AdmissionError(
                f"{resource[:-1]} {obj.metadata.name!r} is immutable: "
                "data cannot be updated", code=422, reason="Invalid")


class ServiceValidation(AdmissionPlugin):
    """spec.clusterIP is immutable once set (core validation
    ValidateServiceUpdate): a mutated address would desynchronize the
    allocator and let two Services share one ClusterIP."""

    name = "ServiceValidation"

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource != "services" or operation != UPDATE:
            return
        try:
            existing = store.get(
                "services", f"{obj.metadata.namespace}/{obj.metadata.name}")
        except NotFoundError:
            return
        if existing.spec.cluster_ip and \
                obj.spec.cluster_ip != existing.spec.cluster_ip:
            raise AdmissionError("spec.clusterIP is immutable", code=422,
                                 reason="Invalid")


class CertificateSubjectRestriction(AdmissionPlugin):
    """Rejects kube-apiserver-client CSRs that request the system:masters
    group (plugin/pkg/admission/certificates/subjectrestriction) — no
    credential-issuance path may mint a cluster-admin identity."""

    name = "CertificateSubjectRestriction"

    def validate(self, store, resource, operation, obj, user="") -> None:
        if resource != "certificatesigningrequests" or operation != CREATE:
            return
        from ..api.certificates import KUBE_APISERVER_CLIENT

        if obj.signer_name == KUBE_APISERVER_CLIENT and \
                "system:masters" in (obj.request.get("groups") or []):
            raise AdmissionError(
                "use of kubernetes.io/kube-apiserver-client signer with "
                "system:masters group is not allowed")


class AdmissionChain:
    """All mutators in order, then all validators (apiserver/pkg/admission
    chainAdmissionHandler)."""

    def __init__(self, plugins: Sequence[AdmissionPlugin]):
        self.plugins = list(plugins)

    def run(self, store: APIStore, resource: str, operation: str, obj,
            user: str = "") -> None:
        for p in self.plugins:
            p.admit(store, resource, operation, obj, user)
        for p in self.plugins:
            p.validate(store, resource, operation, obj, user)


def default_admission_chain() -> AdmissionChain:
    """The default plugin set, in the reference's recommended order
    (kubeapiserver/options/plugins.go — ValidatingAdmissionPolicy just
    before ResourceQuota, ResourceQuota last)."""
    from .admissionpolicy import PolicyAdmission

    return AdmissionChain([
        MetadataDefaulter(),
        NamespaceLifecycle(),
        LimitRanger(),
        ServiceAccountAdmission(),
        PodTolerationRestriction(),
        ExtendedResourceToleration(),
        PriorityAdmission(),
        DefaultTolerationSeconds(),
        DefaultStorageClass(),
        DefaultIngressClass(),
        WorkloadValidation(),
        TaintNodesByCondition(),
        PodSecurityAdmission(),
        ImmutableConfigAdmission(),
        ServiceValidation(),
        CertificateSubjectRestriction(),
        NodeRestriction(),
        PolicyAdmission(),
        ResourceQuotaAdmission(),
    ])
