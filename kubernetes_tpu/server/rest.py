"""HTTP REST front-end over the APIStore — the apiserver surface (L2).

reference: staging/src/k8s.io/apiserver/pkg/endpoints/handlers/{get,create,
update,delete,watch}.go and handlers/watch.go:187 WatchServer. Paths follow the
kubernetes URL scheme:

  GET/POST        /api/v1/namespaces/{ns}/pods[?watch=true&resourceVersion=N]
  GET/PUT/DELETE  /api/v1/namespaces/{ns}/pods/{name}
  POST            /api/v1/namespaces/{ns}/pods/{name}/binding   (BindingREST)
  GET/POST        /api/v1/nodes ... (cluster-scoped)
  GET             /healthz /readyz /metrics

Watches stream newline-delimited JSON events over a chunked response, exactly
the client-go wire shape: {"type": "ADDED", "object": {...}}.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..api.serialize import (
    CLUSTER_SCOPED,
    RESOURCE_TO_TYPE,
    from_dict,
    to_dict,
)
from ..store import (
    AlreadyBoundError,
    AlreadyExistsError,
    APIStore,
    ConflictError,
    NotFoundError,
    ResourceVersionTooOldError,
)


def _parse_path(path: str) -> Optional[Tuple[str, Optional[str], Optional[str], Optional[str]]]:
    """-> (resource, namespace, name, subresource) or None."""
    parts = [p for p in path.split("/") if p]
    # /api/v1/... or /apis/{group}/{version}/...
    if not parts or parts[0] not in ("api", "apis"):
        return None
    parts = parts[2:] if parts[0] == "api" else parts[3:]
    if not parts:
        return None
    if parts[0] == "namespaces" and len(parts) >= 3:
        ns, resource = parts[1], parts[2]
        name = parts[3] if len(parts) > 3 else None
        sub = parts[4] if len(parts) > 4 else None
        return resource, ns, name, sub
    if parts[0] == "namespaces" and len(parts) == 2:
        return "namespaces", None, parts[1], None
    resource = parts[0]
    name = parts[1] if len(parts) > 1 else None
    sub = parts[2] if len(parts) > 2 else None
    return resource, None, name, sub


_FIELD_READERS = {
    "metadata.name": lambda o: o.metadata.name,
    "metadata.namespace": lambda o: getattr(o.metadata, "namespace", ""),
    "spec.nodeName": lambda o: getattr(getattr(o, "spec", None), "node_name", ""),
    "spec.schedulerName": lambda o: getattr(
        getattr(o, "spec", None), "scheduler_name", ""),
    "status.phase": lambda o: getattr(getattr(o, "status", None), "phase", ""),
}


def parse_field_selector(raw: str):
    """`spec.nodeName=n1,status.phase!=Failed` -> predicate(obj) or None.
    The subset the reference serves from etcd/cacher for pods and nodes
    (apiserver fields.Selector); `==` is accepted as an alias of `=`.
    Raises ValueError for unsupported field paths (the apiserver's
    'field label not supported' 400, not a silently-empty result)."""
    if not raw:
        return None
    clauses = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            key, _, want = part.partition("!=")
            neg = True
        else:
            key, _, want = part.partition("=")
            if want.startswith("="):  # the k8s `==` alias
                want = want[1:]
            neg = False
        key = key.strip()
        if key not in _FIELD_READERS:
            raise ValueError(f"field label not supported: {key!r}")
        clauses.append((key, want.strip(), neg))

    def pred(obj):
        for key, want, neg in clauses:
            if (_FIELD_READERS[key](obj) == want) == neg:
                return False
        return True

    return pred


def json_merge_patch(target, patch):
    """RFC 7386 JSON Merge Patch: dicts merge recursively, null deletes,
    everything else replaces (the subset of strategic-merge the build's types
    need — k8s list-merge keys degrade to whole-list replace, which is also
    what strategic merge does for lists without a patchMergeKey)."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = json_merge_patch(out.get(k), v)
    return out


def _builtin_groups():
    """API groups this server serves natively (from the registered
    prefixes) — never proxied to an extension apiserver."""
    from ..api.serialize import GROUP_PREFIX

    groups = set()
    for prefix in GROUP_PREFIX.values():
        parts = [p for p in prefix.split("/") if p]
        if parts and parts[0] == "apis" and len(parts) >= 2:
            groups.add(parts[1])
    return groups


_BUILTIN_GROUPS = None


def _IDENTITY_VIEW(d):
    """Shared identity view: its object identity marks a watch event as
    safely cacheable across watchers (no redaction applied)."""
    return d


class _PatchParseError(Exception):
    """Carries a buffered (code, msg, reason) verdict out of the PATCH
    transaction block."""

    def __init__(self, verdict):
        super().__init__(verdict[1])
        self.verdict = verdict


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kubernetes-tpu-apiserver"

    # quiet by default
    def log_message(self, fmt, *args):
        if self.server.verbose:  # type: ignore[attr-defined]
            super().log_message(fmt, *args)

    @property
    def store(self) -> APIStore:
        return self.server.store  # type: ignore[attr-defined]

    # ---- dynamic (CRD-served) resources --------------------------------------

    def _crd(self, resource: str):
        """CustomResourceDefinition serving `resource`, or None. Static types
        win: a CRD cannot shadow a built-in (the reference's aggregation
        layer has the same precedence)."""
        if resource in RESOURCE_TO_TYPE:
            return None
        reg = getattr(self.server, "crds", None)
        return reg.resolve(resource) if reg is not None else None

    def _known(self, resource: str, crd) -> bool:
        return resource in RESOURCE_TO_TYPE or crd is not None

    def _cluster_scoped(self, resource: str, crd=None) -> bool:
        if resource in RESOURCE_TO_TYPE:
            return resource in CLUSTER_SCOPED
        return crd is not None and crd.scope == "Cluster"

    def _try_aggregate(self) -> bool:
        """The aggregation layer (kube-aggregator; delegation chain
        apiextensions -> core -> aggregator, server.go:173): a request
        under /apis/{group}/... whose group no built-in or CRD serves, but
        an Available APIService claims, is reverse-proxied WHOLESALE to
        the extension apiserver. The authenticated identity forwards as
        X-Remote-User (the reference's front-proxy request headers).
        Returns True when the request was handled here."""
        global _BUILTIN_GROUPS
        import urllib.error
        import urllib.request as _ur

        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        if len(parts) < 3 or parts[0] != "apis":
            return False
        group = parts[1]
        if _BUILTIN_GROUPS is None:
            _BUILTIN_GROUPS = _builtin_groups()
        if group in _BUILTIN_GROUPS or group in (
                "apiregistration.k8s.io", "authorization.k8s.io",
                "authentication.k8s.io", "admission.k8s.io"):
            return False
        parsed = _parse_path(url.path)
        reg = getattr(self.server, "crds", None)
        if reg is not None and parsed is not None:
            crd = reg.resolve(parsed[0])
            # apiextensions precedes aggregation — for the CRD's OWN group
            # only (a same-named plural in another group must still proxy)
            if crd is not None and crd.group == group:
                return False
        try:
            svcs, _ = self.store.list(
                "apiservices", lambda s: s.group == group and not s.local)
        except Exception:
            return False
        if not svcs:
            return False
        # the request's version segment picks its APIService; ties and
        # unversioned requests fall to the highest groupPriorityMinimum
        version = parts[2] if len(parts) > 2 else ""
        matching = [s for s in svcs if s.version == version] or svcs
        svc = sorted(matching, key=lambda s: -s.group_priority_minimum)[0]
        # aggregated requests pass the SAME authn/authz gate as local ones
        # — the proxy must never launder a request past RBAC
        verb, authz_resource = self._request_attrs(parsed)
        user = self._authenticated_user(
            verb, authz_resource or f"{group}/*")
        if user is None:
            return True  # 401/403 already sent
        length = int(self.headers.get("Content-Length", 0) or 0)
        body = self.rfile.read(length) if length else None
        if not svc.available:
            # body already drained: keep-alive connections stay in sync
            self._error(503, f"APIService {svc.metadata.name} is not "
                        f"available: {svc.available_message or 'unknown'}",
                        "ServiceUnavailable")
            return True
        target = svc.service_url.rstrip("/") + url.path + (
            f"?{url.query}" if url.query else "")
        headers = {"Content-Type": self.headers.get("Content-Type",
                                                    "application/json")}
        headers["X-Remote-User"] = user.name
        if user.groups:
            headers["X-Remote-Group"] = ",".join(user.groups)
        req = _ur.Request(target, data=body, method=self.command,
                          headers=headers)
        is_watch = "watch=true" in (url.query or "")
        try:
            resp = _ur.urlopen(req, timeout=3600 if is_watch else 30)
        except urllib.error.HTTPError as e:
            payload = e.read()
            self._audit_record(e.code)
            self.send_response(e.code)
            self.send_header("Content-Type", e.headers.get(
                "Content-Type", "application/json"))
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            return True
        except (urllib.error.URLError, OSError) as e:
            self._error(502, f"error trying to reach APIService "
                        f"{svc.metadata.name}: {e}", "BadGateway")
            return True
        with resp:
            ctype = resp.headers.get("Content-Type", "application/json")
            self._audit_record(resp.status)
            self.send_response(resp.status)
            self.send_header("Content-Type", ctype)
            if is_watch:
                # stream the backend's watch through without buffering
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    while True:
                        # read1: forward whatever is available NOW —
                        # read(n) on a chunked response blocks until n
                        # bytes or EOF, which would buffer a watch stream
                        chunk = resp.read1(65536)
                        if not chunk:
                            break
                        self.wfile.write(
                            f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                return True
            payload = resp.read()
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)
        return True

    def _parse_obj(self, resource: str, body, crd):
        """-> (obj, None) or (None, (code, msg, reason)). Dynamic objects get
        structural-schema defaulting + pruning + validation here — the same
        write path the reference's apiextensions handler runs."""
        from ..api.crd import Unstructured, validate_custom_object

        if not isinstance(body, dict):
            return None, (400, f"body must be a JSON object, got "
                          f"{type(body).__name__}", "BadRequest")
        if crd is not None:
            obj, errs = validate_custom_object(crd, Unstructured.from_dict(body))
            if errs:
                return None, (422, "; ".join(errs), "Invalid")
            return obj, None
        try:
            obj = from_dict(resource, body)
        except Exception as e:
            return None, (400, f"cannot parse {resource}: {e}", "BadRequest")
        if resource == "customresourcedefinitions":
            err = obj.validate()
            if err is not None:
                return None, (422, err, "Invalid")
            # a CRD may not shadow a built-in resource (static check; the
            # cross-CRD plural conflict is checked under the store lock at
            # write time — see _crd_conflict)
            if obj.names.plural in RESOURCE_TO_TYPE:
                return None, (422, f"spec.names.plural {obj.names.plural!r} "
                              "shadows a built-in resource", "Invalid")
        return obj, None

    def _crd_conflict(self, obj):
        """Plurals are a single flat route namespace: a second group claiming
        an existing plural conflicts instead of silently stealing the route
        and the store bucket. Reads the store directly (re-entrant under the
        caller's transaction) so concurrent CRD writes serialize — never the
        DynamicRegistry, whose lock ranks ABOVE the store lock."""
        existing, _rv = self.store.list("customresourcedefinitions")
        for other in existing:
            if (other.names.plural == obj.names.plural
                    and other.metadata.name != obj.metadata.name):
                return (409, f"plural {obj.names.plural!r} already served by "
                        f"{other.metadata.name}", "Conflict")
            if other.metadata.name == obj.metadata.name and \
                    other.scope != obj.scope:
                # scope switches the store key scheme (ns/name vs name) and
                # would orphan existing objects; the reference makes it
                # immutable outright
                return (422, "spec.scope is immutable", "Invalid")
        return None

    def _crd_still_served(self, crd):
        """Inside a CR write transaction: the CRD resolved before the lock may
        have been deleted concurrently (its delete cascades CR removal under
        the same lock) — a write against a stale CRD would orphan the object."""
        try:
            self.store.get("customresourcedefinitions", crd.metadata.name)
            return None
        except NotFoundError:
            return (404, f"unknown resource {crd.names.plural} "
                    "(CRD deleted)", "NotFound")

    def _self_subject_access_review(self) -> None:
        """POST selfsubjectaccessreviews: "can I, the caller, do X?"
        (authorization/v1 SelfSubjectAccessReview; kubectl auth can-i).
        Evaluated against the live authorizer; an open server answers yes."""
        user = self._user()
        if user is None:
            self._error(401, "Unauthorized: invalid or missing bearer token",
                        "Unauthorized")
            return
        try:
            body = self._read_body()
        except json.JSONDecodeError as e:
            self._error(400, f"invalid JSON: {e}")
            return
        attrs = ((body.get("spec") or {}).get("resourceAttributes") or {})
        verb = attrs.get("verb", "")
        resource = attrs.get("resource", "")
        authz = getattr(self.server, "authorizer", None)
        allowed = True if authz is None else authz.authorize(user, verb, resource)
        self._send_json(201, {
            "kind": "SelfSubjectAccessReview",
            "apiVersion": "authorization.k8s.io/v1",
            "spec": {"resourceAttributes": {"verb": verb, "resource": resource}},
            "status": {"allowed": allowed},
        })

    # ---- API priority & fairness (apiserver/pkg/util/flowcontrol) ------------

    _FC_VERBS = {"GET": "get", "POST": "create", "PUT": "update",
                 "PATCH": "patch", "DELETE": "delete"}
    _FC_EXEMPT_PATHS = ("/healthz", "/readyz", "/metrics", "/version",
                        "/configz", "/debug/schedstats", "/debug/schedtrace",
                        "/debug/controlstats", "/debug/timeseries",
                        "/debug/trace", "/debug/critpath")

    def _flow_dispatch(self, orig: "Callable[[], None]") -> None:
        """Seat-accounted dispatch. Health/metrics always pass (the probe
        endpoints must answer exactly when the server is overloaded); watches
        are long-running and bypass seats (longRunningRequestCheck)."""
        fc = getattr(self.server, "flowcontrol", None)
        url = urlparse(self.path)
        if fc is None or url.path in self._FC_EXEMPT_PATHS:
            orig()
            return
        parsed = _parse_path(url.path)
        q = parse_qs(url.query)
        # long-running bypass ONLY for what the GET handler actually treats
        # as a watch (collection GET + watch=true) — `?watch=true` glued onto
        # writes or named GETs must not dodge the seats
        if (self.command == "GET" and parsed is not None and parsed[2] is None
                and q.get("watch", ["false"])[0] == "true"):
            orig()
            return
        # derive the SAME verb/resource vocabulary the handlers/authz use,
        # so FlowSchemas written against 'list'/'bind' actually match
        verb, resource = self._request_attrs(parsed)
        level = fc.classify(self._user(), verb, resource)
        if not level.acquire():
            # drain the request body first: on a keep-alive connection the
            # unread bytes would be parsed as the next request line
            length = int(self.headers.get("Content-Length", 0) or 0)
            if length:
                self.rfile.read(length)
            self._audit_record(429, verb=verb)  # overload IS audit-worthy
            body = json.dumps({
                "kind": "Status", "status": "Failure", "code": 429,
                "reason": "TooManyRequests",
                "message": f"too many requests for priority level "
                           f"{level.name!r}, please try again later",
            }).encode()
            self.send_response(429)
            self.send_header("Content-Type", "application/json")
            self.send_header("Retry-After", "1")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        try:
            orig()
        finally:
            level.release()

    # ---- authn/authz (DefaultBuildHandlerChain order: authn -> authz) --------

    def _user(self):
        """Resolve request identity. With an authenticator configured, only
        bearer tokens count and X-Remote-User is ignored (it is forgeable
        unless a trusted proxy sets it). Without one, the header is honored —
        the open in-process mode tests and local daemons use.

        Memoized per credential headers: flow control resolves the user
        before the handler does, and HMAC verification must not run twice
        per request."""
        from .auth import ANONYMOUS, UserInfo

        key = (self.headers.get("Authorization", ""),
               self.headers.get("X-Remote-User", ""),
               self.headers.get("X-Remote-Group", ""))
        memo = getattr(self, "_user_memo", None)
        if memo is not None and memo[0] == key:
            return memo[1]
        authn = getattr(self.server, "authenticator", None)
        if authn is not None:
            user = authn.authenticate(key[0])
        elif key[1]:
            groups = tuple(g for g in key[2].split(",") if g)
            user = UserInfo(name=key[1], groups=groups)
        else:
            user = ANONYMOUS
        self._user_memo = (key, user)
        return user

    def _authenticated_user(self, verb: str, resource: str):
        """Runs authn then authz; sends the error response and returns None on
        either failure. Health/metrics endpoints bypass (always_allow_paths)."""
        user = self._user()
        if user is None:
            self._error(401, "Unauthorized: invalid or missing bearer token",
                        "Unauthorized")
            return None
        authz = getattr(self.server, "authorizer", None)
        if authz is not None and not authz.authorize(user, verb, resource):
            self._error(403, f"user {user.name!r} cannot {verb} {resource}",
                        "Forbidden")
            return None
        return user

    def _send_json(self, code: int, payload) -> None:
        # audit BEFORE the bytes go out: a client that acts on the response
        # must already find the event recorded (and the in-memory append
        # cannot fail the request)
        self._audit_record(code)
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _request_attrs(self, parsed) -> Tuple[str, str]:
        """-> (verb, authz-resource): the ONE derivation authz, audit, and
        flow control all share. Subresources that grant something the parent
        does not (binding -> bind verb; token -> the serviceaccounts/token
        resource, since minting a credential is a bigger power than creating
        SA objects) are distinguished here."""
        verb = self._FC_VERBS.get(self.command, "get")
        if parsed is None:
            return verb, ""
        resource, _ns, name, sub = parsed
        # CRD aliases canonicalize to the plural here too — audit rules and
        # FlowSchemas match the same name authz sees, however the URL spells it
        crd = self._crd(resource)
        if crd is not None:
            resource = crd.names.plural
        if self.command == "GET" and name is None:
            q = parse_qs(urlparse(self.path).query)
            verb = ("watch" if q.get("watch", ["false"])[0] == "true"
                    else "list")
        elif self.command == "POST" and sub == "binding" and resource == "pods":
            verb = "bind"
        elif self.command == "POST" and sub == "token" \
                and resource == "serviceaccounts":
            resource = "serviceaccounts/token"
        elif self.command == "POST" and sub == "eviction" and resource == "pods":
            resource = "pods/eviction"
        elif self.command == "POST" and resource == "pods" \
                and sub in ("exec", "attach", "portforward"):
            # running commands in containers is a bigger power than creating
            # pods (the reference's pods/exec RBAC resource)
            resource = f"pods/{sub}"
        elif self.command in ("PUT", "PATCH") and sub == "status":
            resource = f"{resource}/status"
        return verb, resource

    def _audit_record(self, code: int, verb: Optional[str] = None) -> None:
        """Metadata-level audit on resource requests (audit.py). Callers
        record BEFORE writing response bytes: a client acting on the response
        must already find the event recorded; the in-memory append cannot
        delay or fail the request."""
        audit = getattr(self.server, "audit", None)
        if audit is None:
            return
        parsed = _parse_path(urlparse(self.path).path)
        if parsed is None:
            return  # non-resource endpoints are not audited (subset)
        derived_verb, resource = self._request_attrs(parsed)
        _r, ns, name, _sub = parsed
        try:
            audit.log(self._user(), verb or derived_verb,
                      resource, ns or "", name or "", code)
        except Exception:
            pass

    def _error(self, code: int, message: str, reason: str = "") -> None:
        self._send_json(code, {"kind": "Status", "status": "Failure",
                               "message": message, "reason": reason, "code": code})

    def _key(self, resource, ns, name, crd=None) -> str:
        return name if self._cluster_scoped(resource, crd) else f"{ns}/{name}"

    def _discovery(self) -> None:
        """GET /apis: every servable resource -> {prefix, namespaced, kind} —
        static registries plus live CRDs. Clients use this instead of baked-in
        tables for dynamic kinds (the reference's APIGroupDiscoveryList)."""
        from ..api.serialize import GROUP_PREFIX, KIND_TO_RESOURCE

        resources = {
            res: {"name": res,
                  "prefix": GROUP_PREFIX[res],
                  "namespaced": res not in CLUSTER_SCOPED,
                  "kind": kind}
            for kind, res in KIND_TO_RESOURCE.items()
        }
        reg = getattr(self.server, "crds", None)
        if reg is not None:
            for crd in reg.all():
                resources[crd.names.plural] = {
                    "name": crd.names.plural,
                    "prefix": crd.group_prefix,
                    "namespaced": crd.scope == "Namespaced",
                    "kind": crd.names.kind,
                    "singular": crd.names.singular,
                    "shortNames": list(crd.names.short_names),
                }
        self._send_json(200, {"kind": "APIResourceList", "resources": resources})

    def _read_body(self):
        length = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(length) if length else b"{}"
        return json.loads(raw or b"{}")

    def _read_body_yaml(self):
        """apply-patch bodies are YAML per the reference content type
        (application/apply-patch+yaml); JSON is a YAML subset."""
        length = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(length) if length else b"{}"
        try:
            return json.loads(raw or b"{}")
        except json.JSONDecodeError:
            import yaml

            return yaml.safe_load(raw.decode())

    def _field_manager(self, user) -> str:
        """Manager identity for field ownership: the fieldManager query param,
        else the User-Agent's first token, else the username (the reference's
        managedfields default chain)."""
        qs = parse_qs(urlparse(self.path).query)
        manager = (qs.get("fieldManager") or [""])[0]
        if manager:
            return manager
        ua = (self.headers.get("User-Agent") or "").split("/")[0].split()[0:1]
        if ua and ua[0]:
            return ua[0]
        return user.name if user is not None else "unknown"

    # ---- GET: get / list / watch / health / metrics --------------------------

    def do_GET(self):
        if self._try_aggregate():
            return
        url = urlparse(self.path)
        if url.path == "/healthz" or url.path == "/readyz":
            self._send_json(200, {"status": "ok"})
            return
        if url.path == "/metrics":
            self._metrics()
            return
        if url.path == "/version":
            self._send_json(200, {"gitVersion": "v0.1.0-kubernetes-tpu"})
            return
        if url.path == "/configz":
            from ..utils.tracing import configz_snapshot

            # configs may be arbitrary objects; coerce like the JSON logger
            body = json.dumps(configz_snapshot(), default=lambda o: vars(o)
                              if hasattr(o, "__dict__") else str(o)).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path == "/debug/schedstats":
            # pipeline flight recorder (scheduler/flightrec.py): per-stage
            # timing + last-batch records of every live in-process batch
            # scheduler — what `ktl sched stats` renders. The debug family
            # sits beside /configz: read-only, introspection-only.
            from ..scheduler.flightrec import schedstats_snapshot

            body = json.dumps(schedstats_snapshot(), default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path == "/debug/schedtrace":
            # sampled pod lifecycle spans (scheduler/podtrace.py): the
            # per-pod latency view `ktl sched trace` renders — same
            # read-only debug family as /debug/schedstats
            from ..scheduler.flightrec import schedtrace_snapshot

            body = json.dumps(schedtrace_snapshot(), default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path == "/debug/trace":
            # unified trace timeline (ISSUE 18): the armed (or last)
            # trace buffer as Chrome trace-event JSON with podtrace flow
            # arrows — save the body and open it in https://ui.perfetto.dev
            # or chrome://tracing. Same read-only debug family.
            from ..scheduler.flightrec import trace_export

            body = json.dumps(trace_export(), default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path == "/debug/critpath":
            # critical-path attribution (ISSUE 18): sampled submit→bound
            # latency decomposed into additive components per window — what
            # `ktl sched why` renders. Same read-only debug family.
            from ..scheduler.flightrec import critpath_snapshot

            body = json.dumps(critpath_snapshot(), default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path == "/debug/timeseries":
            # steady-state telemetry (ISSUE 13): windowed time-series +
            # resource-sampler summary of every live batch scheduler — what
            # `ktl sched top` renders. Same read-only debug family as
            # /debug/schedstats.
            from ..scheduler.flightrec import timeseries_snapshot

            body = json.dumps(timeseries_snapshot(), default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path == "/debug/controlstats":
            # control-plane flight recorder (ISSUE 9): per-controller
            # reconcile-loop telemetry (obs/reconcile.py) plus THIS server's
            # watch-bus propagation/lag view — what `ktl controller stats`
            # renders. Same read-only debug family as /debug/schedstats.
            from ..obs.reconcile import controlstats_snapshot, reconcile_rollup

            snap = controlstats_snapshot()
            doc = {"controllers": snap,
                   "reconcile": reconcile_rollup(snap)}
            try:
                doc["watch"] = self.server.store.watch_telemetry()
            except Exception as e:  # telemetry must not 500 the endpoint
                doc["watch"] = {"error": str(e)}
            body = json.dumps(doc, default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path in ("/apis", "/api"):
            # discovery needs a valid identity but no resource grant (the
            # reference binds system:discovery to all authenticated users)
            if self._user() is None:
                self._error(401, "Unauthorized: invalid or missing bearer token",
                            "Unauthorized")
                return
            self._discovery()
            return
        parsed = _parse_path(url.path)
        if parsed is None:
            self._error(404, f"unknown path {url.path}")
            return
        resource, ns, name, _sub = parsed
        crd = self._crd(resource)
        if not self._known(resource, crd):
            self._error(404, f"unknown resource {resource}")
            return
        if crd is not None:
            resource = crd.names.plural  # singular/shortName aliases
        q = parse_qs(url.query)
        if _sub == "log" and resource == "pods" and name is not None:
            # pods/{name}/log subresource (registry/core/pod/rest/log.go):
            # rendered text/plain from the PodLog channel node agents feed
            if self._authenticated_user("get", "pods") is None:
                return
            try:
                tail = int(q.get("tailLines", ["0"])[0] or 0)
            except ValueError:
                tail = 0
            try:
                log = self.store.get("podlogs", f"{ns}/{name}")
                lines = log.entries[-tail:] if tail > 0 else log.entries
            except NotFoundError:
                # pod exists but has no log yet -> empty body; unknown pod -> 404
                try:
                    self.store.get("pods", f"{ns}/{name}")
                except NotFoundError:
                    self._error(404, f"pods {ns}/{name} not found", "NotFound")
                    return
                lines = []
            body = ("\n".join(lines) + ("\n" if lines else "")).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        is_watch = name is None and q.get("watch", ["false"])[0] == "true"
        verb = "watch" if is_watch else ("get" if name is not None else "list")
        user = self._authenticated_user(verb, resource)
        if user is None:
            return
        try:
            field_pred = parse_field_selector(q.get("fieldSelector", [""])[0])
        except ValueError as e:
            self._error(400, str(e), "BadRequest")
            return
        label_sel = None
        raw_label = q.get("labelSelector", [""])[0]
        if raw_label:
            from ..api.labels import parse_selector_string

            try:
                label_sel = parse_selector_string(raw_label)
            except ValueError as e:
                self._error(400, str(e), "BadRequest")
                return
        view = self._view_transform(resource, user)
        if is_watch:
            self._watch(resource, ns, int(q.get("resourceVersion", ["-1"])[0]),
                        field_pred, view=view, label_sel=label_sel,
                        send_initial_events=q.get(
                            "sendInitialEvents", ["false"])[0] == "true",
                        ring=q.get("ring", ["false"])[0] == "true")
            return
        try:
            if name is not None:
                obj = self.store.get(resource, self._key(resource, ns, name, crd))
                self._send_json(200, view(to_dict(obj)))
            else:
                def pred(o, _ns=ns, _fp=field_pred, _ls=label_sel):
                    if _ns and o.metadata.namespace != _ns:
                        return False
                    if _ls is not None and not _ls.matches(o.metadata.labels):
                        return False
                    return _fp is None or _fp(o)

                items, rv = self.store.list(
                    resource,
                    pred if (ns or field_pred or label_sel) else None)
                self._send_json(200, {
                    "kind": "List",
                    "metadata": {"resourceVersion": rv},
                    "items": [view(to_dict(o)) for o in items],
                })
        except NotFoundError as e:
            self._error(404, str(e), "NotFound")

    def _view_transform(self, resource: str, user):
        """Per-resource response redaction. A CSR's status.certificate is a
        LIVE bearer credential in this build (not a public x509 cert), so only
        cluster admins and the CSR's own requestor may read it — any broader
        read grant (e.g. the system:authenticated read-all bootstrap rule)
        sees the CSR with the credential blanked."""
        if resource != "certificatesigningrequests" or user is None:
            return _IDENTITY_VIEW
        privileged = (getattr(self.server, "authorizer", None) is None
                      or "system:masters" in user.groups)

        def view(d):
            if privileged:
                return d
            if (d.get("spec") or {}).get("username") == user.name:
                return d
            if (d.get("status") or {}).get("certificate"):
                d = dict(d)
                d["status"] = {**d["status"], "certificate": ""}
            return d

        return view

    def _watch(self, resource: str, ns: Optional[str], since_rv: int,
               field_pred=None, view=None, label_sel=None,
               send_initial_events: bool = False,
               ring: bool = False) -> None:
        """ring=true (query param, ISSUE 12 satellite) subscribes through a
        per-subscriber bounded RING: a slow observability stream (`ktl ...
        -w` dashboards) drops its own oldest deliveries — counted as
        reason="ring_overflow" — instead of terminating into a relist storm
        that stalls the store for every partition's bind worker. Cache-
        building clients (informers) must NOT set it: they need the
        terminate->relist signal."""
        if view is None:
            view = _IDENTITY_VIEW
        if label_sel is not None:
            # fold the label selector into the scope predicate so label
            # changes ride the same ADDED/MODIFIED/DELETED transition logic
            # the field selector uses (cacher watch filtering)
            fp = field_pred

            def field_pred(o, _fp=fp, _ls=label_sel):  # noqa: F811
                if not _ls.matches(o.metadata.labels):
                    return False
                return _fp is None or _fp(o)
        initial = None
        if send_initial_events:
            # WatchList (KEP-3157; reflector.go:121-143 streaming lists):
            # the LIST rides the watch stream as ADDED events followed by
            # an initial-events-end bookmark — clients prime caches without
            # a separate large LIST response. list+watch(list_rv) is
            # consistent: the store replays history after the list's RV.
            # The watcher's scope pushes INTO the list (a node-scoped
            # kubelet informer must not deep-copy every pod in the cluster
            # just to discard them in render)
            def _initial_pred(o, _ns=ns, _fp=field_pred, _ls=label_sel):
                if _ns and getattr(o.metadata, "namespace", "") != _ns:
                    return False
                if _ls is not None and not _ls.matches(o.metadata.labels):
                    return False
                return _fp is None or _fp(o)

            initial, since_rv = self.store.list(
                resource,
                _initial_pred if (ns or field_pred or label_sel) else None)
        try:
            w = self.store.watch(resource, since_rv=since_rv, ring=ring)
        except ResourceVersionTooOldError as e:
            self._error(410, str(e), "Expired")
            return
        self._audit_record(200, verb="watch")
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        detached = False
        try:
            import time as _time

            last_sent = _time.monotonic()

            def maybe_bookmark() -> None:
                # periodic BOOKMARK (reflector.go:156): advances the client's
                # resourceVersion so reconnects don't 410-relist, and doubles
                # as a liveness probe reaping dead clients. Fires on QUIET
                # streams and on busy-but-filtered ones alike — 5s since the
                # last actual send, not 5 queue timeouts.
                from .watchmux import bookmark_frame

                nonlocal last_sent
                if _time.monotonic() - last_sent < 5.0:
                    return
                last_sent = _time.monotonic()
                self.wfile.write(bookmark_frame(self.store.rv))
                self.wfile.flush()

            def render(ev):
                """One event -> chunk-framed wire bytes, or None when the
                event is invisible to this watcher."""
                if ns and getattr(ev.obj.metadata, "namespace", "") != ns:
                    return None
                etype = ev.type
                if field_pred is not None:
                    # the cacher's transition rule: evaluate the selector on
                    # the PREVIOUS object state vs the current one
                    # (watch_cache filtering semantics) — works for objects
                    # that matched before this watch connected, because prev
                    # rides on the event itself
                    cur_ok = etype != "DELETED" and field_pred(ev.obj)
                    prev_src = ev.prev if ev.prev is not None else (
                        ev.obj if etype == "DELETED" else None)
                    prev_ok = prev_src is not None and field_pred(prev_src)
                    if cur_ok and prev_ok:
                        etype = "MODIFIED"
                    elif cur_ok:
                        etype = "ADDED"  # entered scope
                    elif prev_ok:
                        etype = "DELETED"  # left scope (or real delete)
                    else:
                        return None  # never visible to this watcher
                line = None
                cacheable = view is _IDENTITY_VIEW and etype == ev.type
                if cacheable:
                    # serialize ONCE per event across all watchers (the
                    # cacher's cachingObject, cacher.go) — at 5k watch
                    # streams per-watcher dumps dominate the fan-out cost.
                    # Only the untransformed view is cacheable: redacted
                    # views and selector-rewritten event types are not.
                    line = getattr(ev, "_wire_line", None)
                if line is None:
                    line = json.dumps({"type": etype,
                                       "object": view(to_dict(ev.obj))
                                       }).encode() + b"\n"
                    if cacheable:
                        # Event is a frozen dataclass: plain attribute
                        # assignment raises FrozenInstanceError — the cache
                        # write must go through object.__setattr__
                        object.__setattr__(ev, "_wire_line", line)
                return f"{len(line):x}\r\n".encode() + line + b"\r\n"

            if initial is not None:
                from ..store import Event as _StoreEvent

                burst = bytearray()
                for o in initial:
                    frame = render(_StoreEvent(
                        type="ADDED", kind=resource, obj=o,
                        resource_version=since_rv))
                    if frame is not None:
                        burst += frame
                endline = json.dumps({
                    "type": "BOOKMARK",
                    "object": {"metadata": {
                        "resourceVersion": str(since_rv),
                        "annotations": {
                            "k8s.io/initial-events-end": "true"}}},
                }).encode() + b"\n"
                burst += f"{len(endline):x}\r\n".encode() + endline + b"\r\n"
                self.wfile.write(bytes(burst))
                self.wfile.flush()
                last_sent = _time.monotonic()
            mux = getattr(self.server, "watch_mux", None)
            if mux is not None:
                # hand the stream to the select-based mux: ONE thread fans
                # out to every watcher (thread-per-watch collapsed 10x at
                # 5k streams — see server/watchmux.py). The dup'd fd keeps
                # the TCP stream alive after this handler thread exits.
                self.wfile.flush()
                sock = self.connection.dup()
                self.server.mark_detached(self.connection)  # type: ignore[attr-defined]
                store = self.store
                mux.add(sock, w, render, rv_fn=lambda: store.rv)
                self.close_connection = True
                detached = True
                return  # the finally below must NOT stop the watch
            while True:
                ev = w.get(timeout=1.0)
                if ev is None:
                    if w.terminated or self.server.shutting_down:  # type: ignore[attr-defined]
                        break  # evicted slow watcher: close; client relists
                    maybe_bookmark()
                    continue
                # burst batching: everything already buffered rides ONE
                # write+flush
                payload = bytearray()
                for e in [ev] + w.drain(512):
                    frame = render(e)
                    if frame is not None:
                        payload += frame
                if not payload:
                    maybe_bookmark()
                    continue
                last_sent = _time.monotonic()
                self.wfile.write(bytes(payload))
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            if not detached:
                w.stop()
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except Exception:
                    pass

    def _metrics(self) -> None:
        from .metrics import global_registry

        text = global_registry.render()
        fc = getattr(self.server, "flowcontrol", None)
        if fc is not None:
            lines = []
            for name, st in fc.stats().items():
                for k, v in st.items():
                    lines.append(
                        f'apiserver_flowcontrol_{k}{{priority_level="{name}"}} {v}')
            text += "\n".join(lines) + "\n"
        body = text.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # ---- POST: create / binding ----------------------------------------------

    def do_POST(self):
        if self._try_aggregate():
            return
        path = urlparse(self.path).path
        if path == "/apis/authorization.k8s.io/v1/selfsubjectaccessreviews":
            self._self_subject_access_review()
            return
        parsed = _parse_path(path)
        if parsed is None:
            self._error(404, "unknown path")
            return
        resource, ns, name, sub = parsed
        # canonicalize CRD aliases BEFORE authz so a grant on the plural
        # covers every alias spelling, exactly as in do_GET
        crd = self._crd(resource)
        if crd is not None:
            resource = crd.names.plural
        verb, authz_resource = self._request_attrs(
            (resource, ns, name, sub))
        user = self._authenticated_user(verb, authz_resource)
        if user is None:
            return
        try:
            body = self._read_body()
        except json.JSONDecodeError as e:
            self._error(400, f"invalid JSON: {e}")
            return
        if sub == "binding" and resource == "pods":
            target = (body.get("target") or {}).get("name", "")
            if not target:
                self._error(400, "binding requires target.name")
                return
            try:
                self.store.bind(ns, name, target)
                self._send_json(201, {"kind": "Status", "status": "Success"})
            except NotFoundError as e:
                self._error(404, str(e), "NotFound")
            except AlreadyBoundError as e:
                self._error(409, str(e), "Conflict")
            return
        if sub == "eviction" and resource == "pods":
            # Eviction subresource (registry/core/pod/storage/eviction.go):
            # a PDB-respecting delete — every matching budget must have
            # disruptionsAllowed > 0; the decrement and the delete commit in
            # one transaction so two racing evictions cannot both spend the
            # last allowance
            err = None
            with self.store.transaction():
                try:
                    pod = self.store.get("pods", f"{ns}/{name}")
                    pdbs, _ = self.store.list(
                        "poddisruptionbudgets",
                        lambda b: b.metadata.namespace == ns
                        and b.selector is not None
                        and b.selector.matches(pod.metadata.labels))
                    blocked = [b for b in pdbs if b.disruptions_allowed <= 0]
                    if blocked:
                        err = (429, "Cannot evict pod as it would violate "
                               f"the pod's disruption budget "
                               f"({blocked[0].metadata.name})",
                               "TooManyRequests")
                    else:
                        for b in pdbs:
                            def spend(obj):
                                obj.disruptions_allowed = max(
                                    0, obj.disruptions_allowed - 1)
                                return obj

                            self.store.guaranteed_update(
                                "poddisruptionbudgets", b.key, spend)
                        self.store.delete("pods", f"{ns}/{name}")
                except NotFoundError as e:
                    err = (404, str(e), "NotFound")
            if err is not None:
                self._error(*err)
                return
            self._send_json(201, {"kind": "Status", "status": "Success"})
            return
        if sub in ("exec", "attach", "portforward") and resource == "pods":
            self._pod_stream_session(ns, name, sub, body)
            return
        if sub == "token" and resource == "serviceaccounts":
            # TokenRequest subresource: mint a signed bearer credential for
            # the service account identity (registry/core/serviceaccount/
            # storage TokenREST + the projected-token flow)
            signer = getattr(self.server, "token_signer", None)
            if signer is None:
                self._error(501, "token signing is not configured on this "
                            "server", "NotImplemented")
                return
            try:
                self.store.get("serviceaccounts", f"{ns}/{name}")
            except NotFoundError as e:
                self._error(404, str(e), "NotFound")
                return
            raw_exp = (body.get("spec") or {}).get("expirationSeconds")
            try:
                exp = 3600 if raw_exp is None else int(raw_exp)
            except (TypeError, ValueError):
                self._error(400, "spec.expirationSeconds must be an integer",
                            "BadRequest")
                return
            if exp <= 0:
                self._error(400, "spec.expirationSeconds must be positive",
                            "BadRequest")
                return
            exp = max(600, min(exp, 86400))
            token = signer.mint(
                f"system:serviceaccount:{ns}:{name}",
                ["system:serviceaccounts", f"system:serviceaccounts:{ns}"],
                expiration_seconds=exp)
            self._send_json(201, {
                "kind": "TokenRequest",
                "apiVersion": "authentication.k8s.io/v1",
                "spec": {"expirationSeconds": exp},
                "status": {"token": token, "expirationSeconds": exp},
            })
            return
        if not self._known(resource, crd):
            self._error(404, f"unknown resource {resource}")
            return
        obj, perr = self._parse_obj(resource, body, crd)
        if perr is not None:
            self._error(*perr)
            return
        if ns and not self._cluster_scoped(resource, crd):
            obj.metadata.namespace = ns
        # mutating webhooks run BEFORE registry-side allocation and identity
        # stamping (the reference's order): a webhook patch can never forge
        # CSR identity or bypass the ClusterIP allocator
        obj, _patches, werr = self._run_webhooks(resource, "CREATE", obj,
                                                 user, crd)
        if werr is not None:
            self._error(*werr)
            return
        if resource == "certificatesigningrequests":
            # requestor identity is server-populated and unforgeable
            # (certificates/v1 PrepareForCreate semantics)
            obj.username = user.name
            obj.groups = list(user.groups)
        allocated_ip = None
        if resource == "services":
            # ClusterIP allocation (registry/core/service/ipallocator):
            # empty = assign next free; explicit = honor or conflict;
            # "None" = headless, no address
            from .ipalloc import HEADLESS

            alloc = getattr(self.server, "ipalloc", None)
            if alloc is not None and obj.spec.cluster_ip != HEADLESS:
                try:
                    obj.spec.cluster_ip = alloc.allocate(obj.spec.cluster_ip)
                    allocated_ip = obj.spec.cluster_ip
                except ValueError as e:
                    self._error(422, str(e), "Invalid")
                    return
        # the creating manager owns every field it sent (recomputed
        # server-side — a client-supplied managedFields stanza is ignored)
        from .fieldmanager import capture_update

        obj.metadata.managed_fields = capture_update(
            None, to_dict(obj), self._field_manager(user))
        # admission + create under one store transaction: concurrent creates
        # cannot both pass a quota check they jointly exceed. The verdict is
        # buffered and the HTTP response written AFTER the lock is released —
        # a slow client socket must never block every store consumer.
        err = None
        created = None
        with self.store.transaction():
            if resource == "customresourcedefinitions":
                err = self._crd_conflict(obj)
            elif crd is not None:
                err = self._crd_still_served(crd)
            if err is None:
                err = self._admission_verdict(resource, "CREATE", obj, user)
            if err is None:
                try:
                    created = self.store.create(resource, obj)
                except AlreadyExistsError as e:
                    err = (409, str(e), "AlreadyExists")
        if err is not None:
            if allocated_ip is not None:
                # the create failed AFTER allocation: return the address or
                # a retrying conflicting client drains the CIDR
                self.server.ipalloc.release(allocated_ip)  # type: ignore[attr-defined]
            self._error(*err)
            return
        self._send_json(201, to_dict(created))

    def _run_webhooks(self, resource: str, operation: str, obj, user,
                      crd=None):
        """Webhook phase, OUTSIDE any store transaction (plugin/webhook/:
        an HTTP round-trip must never ride the store lock, and a webhook
        that calls back into this server would deadlock until timeout).
        Returns (possibly-replaced obj, applied JSONPatches, verdict|None)."""
        wh = getattr(self.server, "webhooks", None)
        if wh is None:
            return obj, [], None
        from .admission import AdmissionError

        try:
            wire, patches = wh.run(resource, operation, to_dict(obj),
                                   user.name if user is not None else "")
        except AdmissionError as e:
            return obj, [], (e.code, str(e), e.reason)
        if not patches:
            return obj, [], None
        new_obj, perr = self._parse_obj(resource, wire, crd)
        if perr is not None:
            return obj, [], perr
        # identity is authoritative — a webhook patch can't rename/move
        new_obj.metadata.name = obj.metadata.name
        new_obj.metadata.namespace = obj.metadata.namespace
        new_obj.metadata.uid = obj.metadata.uid
        new_obj.metadata.resource_version = obj.metadata.resource_version
        return new_obj, patches, None

    def _pod_stream_session(self, ns: str, name: str, sub: str, body) -> None:
        """exec / attach / port-forward over a store-channel session
        (api/execapi.py): create the session, long-poll until the pod's
        kubelet answers, return the result, delete the session. Replaces
        the reference's SPDY stream through the apiserver proxy
        (kubelet/server/server.go; kubectl/pkg/cmd/exec/exec.go)."""
        import time as _time
        import uuid as _uuid

        from ..api.execapi import ATTACH_COMMAND, PodExec, PodPortForward

        if not isinstance(body, dict):
            self._error(400, "body must be a JSON object", "BadRequest")
            return
        try:
            pod = self.store.get("pods", f"{ns}/{name}")
        except NotFoundError as e:
            self._error(404, str(e), "NotFound")
            return
        if not pod.spec.node_name:
            self._error(409, f"pod {name} is not scheduled to a node yet",
                        "Conflict")
            return
        try:
            timeout = min(float(body.get("timeoutSeconds", 10) or 10), 30.0)
            port = int(body.get("port", 0) or 0)
        except (TypeError, ValueError) as e:
            self._error(400, f"invalid session parameters: {e}", "BadRequest")
            return
        owner = [{"apiVersion": "v1", "kind": "Pod", "name": name,
                  "uid": pod.metadata.uid, "controller": True}]
        sid = f"{sub}-{name}-{_uuid.uuid4().hex[:8]}"
        if sub == "portforward":
            sess = PodPortForward(pod_name=name, port=port,
                                  data=body.get("data", ""))
            kind = "podportforwards"
        else:
            command = list(body.get("command") or [])
            if sub == "attach":
                command = [ATTACH_COMMAND]
            elif not command:
                self._error(400, "exec requires a command", "BadRequest")
                return
            sess = PodExec(pod_name=name, container=body.get("container", ""),
                           command=command, stdin=body.get("stdin", ""),
                           tty=bool(body.get("tty", False)))
            kind = "podexecs"
        sess.metadata.name = sid
        sess.metadata.namespace = ns
        sess.metadata.owner_references = owner
        self.store.create(kind, sess)
        deadline = _time.monotonic() + timeout
        result = None
        while _time.monotonic() < deadline:
            try:
                cur = self.store.get(kind, f"{ns}/{sid}")
            except NotFoundError:
                break  # pod (and session) deleted mid-round
            if cur.done:
                result = cur
                break
            _time.sleep(0.02)
        try:
            self.store.delete(kind, f"{ns}/{sid}")
        except NotFoundError:
            pass
        if result is None:
            self._error(504, f"{sub} timed out after {timeout:.0f}s waiting "
                        "for the node agent", "Timeout")
            return
        if kind == "podportforwards":
            self._send_json(200, {"kind": "Status", "status": "Success",
                                  "data": result.response,
                                  **({"error": result.error}
                                     if result.error else {})})
        else:
            self._send_json(200, {"kind": "Status", "status": "Success",
                                  "stdout": result.stdout,
                                  **({"stdoutB64": result.stdout_b64}
                                     if result.stdout_b64 else {}),
                                  "stderr": result.stderr,
                                  "exitCode": result.exit_code,
                                  **({"error": result.error}
                                     if result.error else {})})

    def _admission_verdict(self, resource: str, operation: str, obj, user=None):
        """Run the admission chain; returns None on admit or an
        (http_code, message, reason) tuple on reject — the caller sends the
        response outside any store lock. Identity is the authenticated user
        (node agents are system:node:<name>)."""
        chain = getattr(self.server, "admission", None)
        if chain is None:
            return None
        from .admission import AdmissionError

        username = user.name if user is not None else ""
        try:
            chain.run(self.store, resource, operation, obj, user=username)
            return None
        except AdmissionError as e:
            return (e.code, str(e), e.reason)

    def _admit(self, resource: str, operation: str, obj, user=None) -> bool:
        """Lock-free admission wrapper for paths without a transaction."""
        err = self._admission_verdict(resource, operation, obj, user)
        if err is not None:
            self._error(*err)
            return False
        return True

    # ---- PUT / DELETE --------------------------------------------------------

    def _put_status(self, resource: str, ns, name: str, user, crd=None) -> None:
        """The status subresource (registry strategies' status REST): the
        write replaces ONLY the status stanza — a status writer (kubelet,
        controller) can never mutate spec or metadata, however its payload is
        shaped. OCC applies via the body's resourceVersion when provided.
        CRD-served resources keep status inside the Unstructured content."""
        try:
            body = self._read_body()
        except json.JSONDecodeError as e:
            self._error(400, f"invalid JSON: {e}")
            return
        if not isinstance(body, dict):
            self._error(400, "body must be a JSON object", "BadRequest")
            return
        if crd is None:
            try:
                incoming = from_dict(resource, body)
            except Exception as e:
                self._error(400, f"cannot parse {resource}: {e}", "BadRequest")
                return
            if not hasattr(incoming, "status"):
                self._error(400, f"{resource} has no status subresource",
                            "BadRequest")
                return
            body_rv = incoming.metadata.resource_version
        else:
            from ..api.crd import Unstructured

            incoming = Unstructured.from_dict(body)
            body_rv = incoming.metadata.resource_version
        key = self._key(resource, ns, name, crd)
        err = None
        updated = None
        with self.store.transaction():
            try:
                # store reads are read-only by convention (schedlint MU001):
                # splice the status into a PRIVATE object. Under the default
                # deep_copy_on_write store, get() already returns one; only
                # a no-isolation store needs the explicit copy here.
                existing = self.store.get(resource, key)
                if not getattr(self.store, "_deep_copy", True):
                    import copy as _copy

                    existing = _copy.deepcopy(existing)
                if body_rv and body_rv != existing.metadata.resource_version:
                    raise ConflictError(
                        f"{resource} {key}: stale resourceVersion {body_rv}")
                if crd is None:
                    existing.status = incoming.status
                else:
                    from ..api.crd import validate_custom_object

                    existing.content["status"] = incoming.content.get(
                        "status", {})
                    validated, errs = validate_custom_object(crd, existing)
                    if errs:
                        raise _PatchParseError((422, "; ".join(errs), "Invalid"))
                    existing = validated
                err = self._admission_verdict(resource, "UPDATE", existing, user)
                if err is None:
                    updated = self.store.update(resource, existing,
                                                check_rv=False)
            except NotFoundError as e:
                err = (404, str(e), "NotFound")
            except ConflictError as e:
                err = (409, str(e), "Conflict")
            except _PatchParseError as e:
                err = e.verdict
        if err is not None:
            self._error(*err)
            return
        self._send_json(200, to_dict(updated))

    def do_PUT(self):
        if self._try_aggregate():
            return
        parsed = _parse_path(urlparse(self.path).path)
        if parsed is None or parsed[2] is None:
            self._error(404, "unknown path")
            return
        resource, ns, name, sub = parsed
        crd = self._crd(resource)
        if crd is not None:
            resource = crd.names.plural
        _verb, authz_resource = self._request_attrs((resource, ns, name, sub))
        user = self._authenticated_user("update", authz_resource)
        if user is None:
            return
        if not self._known(resource, crd):
            self._error(404, f"unknown resource {resource}")
            return
        if sub == "status":
            self._put_status(resource, ns, name, user, crd)
            return
        try:
            body = self._read_body()
        except json.JSONDecodeError as e:
            self._error(400, f"invalid JSON: {e}")
            return
        obj, perr = self._parse_obj(resource, body, crd)
        if perr is not None:
            self._error(*perr)
            return
        # the URL is authoritative for namespace/name (the body may omit them)
        if ns and not self._cluster_scoped(resource, crd):
            obj.metadata.namespace = ns
        if obj.metadata.name and obj.metadata.name != name:
            self._error(400, f"name mismatch: URL {name!r} vs body {obj.metadata.name!r}")
            return
        obj.metadata.name = name
        obj, _patches, werr = self._run_webhooks(resource, "UPDATE", obj,
                                                 user, crd)
        if werr is not None:
            self._error(*werr)
            return
        err = None
        updated = None
        with self.store.transaction():
            if resource == "customresourcedefinitions":
                err = self._crd_conflict(obj)
            elif crd is not None:
                err = self._crd_still_served(crd)
            if err is None:
                err = self._admission_verdict(resource, "UPDATE", obj, user)
            if err is None:
                try:
                    # fields this PUT changes move to the writing manager
                    # (fieldmanager.go:68); the body can't forge ownership —
                    # it is recomputed from the live diff
                    from .fieldmanager import capture_update

                    existing = self.store.get(
                        resource, self._key(resource, ns, name, crd))
                    obj.metadata.managed_fields = capture_update(
                        to_dict(existing), to_dict(obj),
                        self._field_manager(user))
                    updated = self.store.update(resource, obj)
                except NotFoundError as e:
                    err = (404, str(e), "NotFound")
                except ConflictError as e:
                    err = (409, str(e), "Conflict")
        if err is not None:
            self._error(*err)
            return
        self._send_json(200, to_dict(updated))

    def do_PATCH(self):
        """JSON Merge Patch / strategic-merge-patch (degraded to merge
        semantics) — reference: apiserver/pkg/endpoints/handlers/patch.go.
        get + merge + admission + OCC update run under one store transaction
        so concurrent patches serialize instead of clobbering."""
        if self._try_aggregate():
            return
        parsed = _parse_path(urlparse(self.path).path)
        if parsed is None or parsed[2] is None:
            self._error(404, "unknown path")
            return
        resource, ns, name, sub = parsed
        crd = self._crd(resource)
        if crd is not None:
            resource = crd.names.plural
        _verb, authz_resource = self._request_attrs((resource, ns, name, sub))
        user = self._authenticated_user("patch", authz_resource)
        if user is None:
            return
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype == "application/apply-patch+yaml":
            # server-side apply rides PATCH with its own content type
            # (handlers/patch.go:432 applyPatcher)
            self._apply_ssa(resource, ns, name, sub, crd, user)
            return
        if ctype not in ("application/merge-patch+json",
                        "application/strategic-merge-patch+json",
                        "application/json", ""):
            self._error(415, f"unsupported patch type {ctype!r}")
            return
        if not self._known(resource, crd):
            self._error(404, f"unknown resource {resource}")
            return
        try:
            patch = self._read_body()
        except json.JSONDecodeError as e:
            self._error(400, f"invalid JSON: {e}")
            return
        if sub == "status":
            # the status endpoint only ever merges the status stanza: a
            # status-scoped principal must not smuggle spec/metadata edits
            # through PATCH any more than through PUT
            if not isinstance(patch, dict):
                self._error(400, "body must be a JSON object", "BadRequest")
                return
            patch = {"status": patch.get("status", {})}
        if isinstance(patch, dict) and isinstance(patch.get("metadata"), dict):
            # managedFields are server-managed; a patch can't forge them
            patch["metadata"].pop("managedFields", None)
        key = self._key(resource, ns, name, crd)
        # webhook phase outside the transaction, on a merge computed from a
        # pre-read (bounded staleness — the reference's webhooks see the
        # same); mutating patches are re-applied to the authoritative merge
        # inside. Zero configs (the common case) skips the pre-read.
        webhook_patches = []
        wh = getattr(self.server, "webhooks", None)
        # subresource requests never hit webhooks (rules here carry no
        # subresource dimension; in the reference a rule must name
        # pods/status to match one) — a webhook patch must not smuggle
        # spec edits through the status endpoint's scoping guard
        if wh is not None and not sub and wh.active():
            from .admission import AdmissionError

            try:
                existing0 = self.store.get(resource, key)
                merged0 = json_merge_patch(to_dict(existing0), patch)
                _, webhook_patches = wh.run(
                    resource, "UPDATE", merged0,
                    user.name if user is not None else "")
            except NotFoundError as e:
                self._error(404, str(e), "NotFound")
                return
            except AdmissionError as e:
                self._error(e.code, str(e), e.reason)
                return
        err = None
        updated = None
        with self.store.transaction():
            try:
                existing = self.store.get(resource, key)
                merged = json_merge_patch(to_dict(existing), patch)
                if webhook_patches:
                    from .admissionpolicy import apply_json_patch

                    for wp in webhook_patches:
                        merged = apply_json_patch(merged, wp)
                obj, perr = self._parse_obj(resource, merged, crd)
                if perr is None and resource == "customresourcedefinitions":
                    perr = self._crd_conflict(obj)
                elif perr is None and crd is not None:
                    perr = self._crd_still_served(crd)
                if perr is not None:
                    raise _PatchParseError(perr)
                obj.metadata.name = name
                if ns and not self._cluster_scoped(resource, crd):
                    obj.metadata.namespace = ns
                # patch is read-modify-write of the current object: carry its
                # RV so a concurrent writer between our get and update conflicts
                obj.metadata.resource_version = existing.metadata.resource_version
                # changed fields move to the patching manager
                # (managedfields/fieldmanager.go:68 Update semantics)
                from .fieldmanager import capture_update

                obj.metadata.managed_fields = capture_update(
                    to_dict(existing), to_dict(obj),
                    self._field_manager(user))
                err = self._admission_verdict(resource, "UPDATE", obj, user)
                if err is None:
                    updated = self.store.update(resource, obj)
            except NotFoundError as e:
                err = (404, str(e), "NotFound")
            except ConflictError as e:
                err = (409, str(e), "Conflict")
            except _PatchParseError as e:
                err = e.verdict
            except Exception as e:
                err = (400, f"cannot apply patch: {e}", "Invalid")
        if err is not None:
            self._error(*err)
            return
        self._send_json(200, to_dict(updated))

    def _apply_ssa(self, resource, ns, name, sub, crd, user):
        """Server-side apply (handlers/patch.go:432 applyPatcher +
        managedfields/fieldmanager.go:96): merge the applied configuration
        into the live object under field ownership; 409 lists every
        conflicting (manager, field) unless force=true steals them; absent
        fields this manager previously applied are pruned; create-on-absent."""
        if sub:
            self._error(400, "apply is not supported on subresources",
                        "BadRequest")
            return
        qs = parse_qs(urlparse(self.path).query)
        manager = (qs.get("fieldManager") or [""])[0]
        if not manager:
            # the reference hard-requires an explicit manager for apply
            self._error(400, "fieldManager is required for apply requests",
                        "BadRequest")
            return
        force = (qs.get("force") or ["false"])[0].lower() in ("true", "1")
        if not self._known(resource, crd):
            self._error(404, f"unknown resource {resource}")
            return
        try:
            applied = self._read_body_yaml()
        except Exception as e:
            self._error(400, f"invalid apply body: {e}", "BadRequest")
            return
        if not isinstance(applied, dict) or not isinstance(
                applied.get("metadata", {}), dict):
            self._error(400, "body must be an object with object metadata",
                        "BadRequest")
            return
        from .fieldmanager import Conflict, apply_patch

        applied.setdefault("metadata", {})["name"] = name
        if ns and not self._cluster_scoped(resource, crd):
            applied["metadata"]["namespace"] = ns
        applied["metadata"].pop("managedFields", None)
        # status is reset on main-resource apply (the strategy's resetFields)
        applied.pop("status", None)
        key = self._key(resource, ns, name, crd)
        # webhook phase outside the transaction (same pattern as do_PATCH);
        # an apply Conflict here is ignored — the in-transaction apply
        # raises it authoritatively
        webhook_patches = []
        wh = getattr(self.server, "webhooks", None)
        if wh is not None and wh.active():
            from .admission import AdmissionError

            try:
                try:
                    live0 = to_dict(self.store.get(resource, key))
                    op0 = "UPDATE"
                except NotFoundError:
                    live0 = None
                    op0 = "CREATE"
                try:
                    merged0 = apply_patch(live0, applied, manager,
                                          force=force)
                except Conflict:
                    merged0 = None
                if merged0 is not None:
                    _, webhook_patches = wh.run(
                        resource, op0, merged0,
                        user.name if user is not None else "")
            except AdmissionError as e:
                self._error(e.code, str(e), e.reason)
                return
        err = None
        result = None
        created = False
        with self.store.transaction():
            try:
                try:
                    existing = self.store.get(resource, key)
                except NotFoundError:
                    existing = None
                live = to_dict(existing) if existing is not None else None
                try:
                    merged = apply_patch(live, applied, manager, force=force)
                except Conflict as e:
                    raise _PatchParseError((409, str(e), "Conflict"))
                if webhook_patches:
                    from .admissionpolicy import apply_json_patch

                    for wp in webhook_patches:
                        merged = apply_json_patch(merged, wp)
                obj, perr = self._parse_obj(resource, merged, crd)
                if perr is None and resource == "customresourcedefinitions":
                    perr = self._crd_conflict(obj)
                elif perr is None and crd is not None:
                    perr = self._crd_still_served(crd)
                if perr is not None:
                    raise _PatchParseError(perr)
                obj.metadata.name = name
                if ns and not self._cluster_scoped(resource, crd):
                    obj.metadata.namespace = ns
                if existing is not None:
                    obj.metadata.resource_version = \
                        existing.metadata.resource_version
                    err = self._admission_verdict(resource, "UPDATE", obj, user)
                    if err is None:
                        result = self.store.update(resource, obj)
                else:
                    err = self._admission_verdict(resource, "CREATE", obj, user)
                    if err is None:
                        result = self.store.create(resource, obj)
                        created = True
            except NotFoundError as e:
                err = (404, str(e), "NotFound")
            except ConflictError as e:
                err = (409, str(e), "Conflict")
            except AlreadyExistsError as e:
                err = (409, str(e), "AlreadyExists")
            except _PatchParseError as e:
                err = e.verdict
            except Exception as e:
                err = (400, f"cannot apply: {e}", "Invalid")
        if err is not None:
            self._error(*err)
            return
        self._send_json(201 if created else 200, to_dict(result))

    def do_DELETE(self):
        if self._try_aggregate():
            return
        parsed = _parse_path(urlparse(self.path).path)
        if parsed is None or parsed[2] is None:
            self._error(404, "unknown path")
            return
        resource, ns, name, _ = parsed
        crd = self._crd(resource)
        if crd is not None:
            resource = crd.names.plural
        user = self._authenticated_user("delete", resource)
        if user is None:
            return
        if not self._known(resource, crd):
            self._error(404, f"unknown resource {resource}")
            return
        key = self._key(resource, ns, name, crd)
        err = None
        obj = None
        with self.store.transaction():
            try:
                existing = self.store.get(resource, key)
                # deletes go through admission too (noderestriction covers DELETE)
                err = self._admission_verdict(resource, "DELETE", existing, user)
                if err is None:
                    obj = self.store.delete(resource, key)
                    # services: the allocator releases via its store watch —
                    # an explicit release here would race a concurrent
                    # allocate that already drained the DELETED event
                    if resource == "customresourcedefinitions":
                        # CR data dies with its CRD (the reference's
                        # apiextensions finalizer); same transaction so a
                        # same-plural CRD recreated later starts empty instead
                        # of resurrecting schema-stale objects
                        plural = existing.names.plural
                        crs, _rv = self.store.list(plural)
                        for cr in crs:
                            self.store.delete(plural, self.store.object_key(cr))
            except NotFoundError as e:
                err = (404, str(e), "NotFound")
        if err is not None:
            self._error(*err)
            return
        self._send_json(200, to_dict(obj))


def _install_flowcontrol_wrappers(cls) -> None:
    """Every HTTP verb dispatches through _flow_dispatch; declared once here
    instead of renaming each do_* (the reference inserts its APF filter into
    the handler chain the same way — around, not inside, the handlers)."""
    for verb in ("GET", "POST", "PUT", "PATCH", "DELETE"):
        orig = getattr(cls, f"do_{verb}")

        def make(orig):
            def do(self):
                self._flow_dispatch(lambda: orig(self))

            do.__name__ = orig.__name__
            return do

        setattr(cls, f"do_{verb}", make(orig))


_install_flowcontrol_wrappers(_Handler)


class _Server(ThreadingHTTPServer):
    # kubemark-scale watch storms: thousands of near-simultaneous connects
    # overflow the stdlib default backlog of 5, sending clients into
    # seconds-long SYN retries (500 watchers took 84s to connect; with a
    # real backlog they take under a second)
    request_queue_size = 1024

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._detached_conns = set()
        self._detached_lock = threading.Lock()

    def mark_detached(self, request) -> None:
        """The watch mux took a dup of this connection: the handler teardown
        must not shutdown() the TCP stream (a SHUT_WR sends FIN through
        every dup), only close its own fd."""
        with self._detached_lock:
            self._detached_conns.add(request)

    def shutdown_request(self, request):
        with self._detached_lock:
            detached = request in self._detached_conns
            self._detached_conns.discard(request)
        if detached:
            self.close_request(request)  # close the fd; the dup lives on
        else:
            super().shutdown_request(request)


class APIServer:
    """Embeds the store behind HTTP. start() binds a port; .url for clients."""

    def __init__(self, store: APIStore, host: str = "127.0.0.1", port: int = 0,
                 verbose: bool = False, admission="default",
                 authenticator=None, authorizer=None, flowcontrol=None,
                 audit=None, token_signer=None):
        self.store = store
        self._httpd = _Server((host, port), _Handler)
        self._httpd.store = store  # type: ignore[attr-defined]
        self._httpd.verbose = verbose  # type: ignore[attr-defined]
        self._httpd.shutting_down = False  # type: ignore[attr-defined]
        from ..api.crd import DynamicRegistry
        from .ipalloc import ClusterIPAllocator

        self._httpd.crds = DynamicRegistry(store)  # type: ignore[attr-defined]
        self._httpd.ipalloc = ClusterIPAllocator(store)  # type: ignore[attr-defined]
        from .watchmux import WatchMux

        # all watch streams fan out through ONE select-based writer thread
        self._mux = WatchMux()
        self._httpd.watch_mux = self._mux  # type: ignore[attr-defined]
        from .admissionpolicy import WebhookAdmission

        # live Mutating/ValidatingWebhookConfiguration objects; the phase
        # runs BEFORE store transactions (HTTP must never ride the lock)
        self._httpd.webhooks = WebhookAdmission(store)  # type: ignore[attr-defined]
        if admission == "default":
            from .admission import default_admission_chain

            admission = default_admission_chain()
        self._httpd.admission = admission  # type: ignore[attr-defined]
        # authn/authz: None keeps the open in-process mode (tests, local
        # daemons); see auth.py for the secured configuration
        self._httpd.authenticator = authenticator  # type: ignore[attr-defined]
        self._httpd.authorizer = authorizer  # type: ignore[attr-defined]
        # APF: None = no flow control (open mode); pass a FlowController
        # (flowcontrol.default_flow_controller()) to seat-limit dispatch
        if flowcontrol == "default":
            from .flowcontrol import FlowConfigSource, default_flow_controller

            # live APF: PriorityLevelConfiguration/FlowSchema objects in the
            # store override the bootstrap defaults on the next request
            flowcontrol = FlowConfigSource(store, default_flow_controller())
        self._httpd.flowcontrol = flowcontrol  # type: ignore[attr-defined]
        if audit == "default":
            from .audit import AuditLogger

            audit = AuditLogger()
        self._httpd.audit = audit  # type: ignore[attr-defined]
        # SignedTokenAuthenticator used to mint service-account tokens via
        # the serviceaccounts/{name}/token subresource
        self._httpd.token_signer = token_signer  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "APIServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutting_down = True  # type: ignore[attr-defined]
        self._mux.stop()
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
