"""Restricted CEL-style expression evaluator for admission policies.

The reference evaluates ValidatingAdmissionPolicy expressions with CEL
(apiserver/pkg/admission/plugin/policy/validating/plugin.go + cel-go). This
is a deliberately small, safe replacement covering the subset admission
policies actually use:

  - variables: `object`, `oldObject`, `request` (wire-form dicts; attribute
    access works on dict keys: `object.spec.replicas`)
  - operators: && || !  == != < <= > >=  + - * %  in
  - functions: has(x), size(x), string(x), int(x), double(x),
    x.startsWith(s), x.endsWith(s), x.contains(s), x.matches(re)
  - literals: numbers, strings, lists, true/false/null

Safety: the expression is parsed with `ast` and *interpreted* by an explicit
whitelist walker — no eval(), no attribute access on real Python objects
(dict keys only), no calls except the builtins above. Anything outside the
whitelist raises ExpressionError at compile time.

CEL-vs-Python surface syntax is bridged by token translation (&& -> and,
|| -> or, prefix ! -> not, true/false/null literals); `has()` follows CEL:
missing fields are absent, not errors, and comparisons against an absent
field evaluate false.
"""

from __future__ import annotations

import ast
import re as _re
from typing import Any, Callable, Dict


class ExpressionError(Exception):
    """Compile- or eval-time failure of a policy expression."""


class _Missing:
    """CEL absent-field semantics: propagates through navigation, fails
    every comparison, is falsy."""

    def __repr__(self):
        return "<absent>"

    def __bool__(self):
        return False


MISSING = _Missing()

_ALLOWED_METHODS = {"startsWith", "endsWith", "contains", "matches"}
_ALLOWED_FUNCS = {"has", "size", "string", "int", "double"}


_KEYWORDS = {"true": "True", "false": "False", "null": "None"}


def _translate(src: str) -> str:
    """CEL surface syntax -> Python-parsable: && || ! and true/false/null —
    all rewritten ONLY outside string literals (a policy comparing against
    the strings 'true'/'false'/'null' must see them verbatim)."""
    out = []
    i, n = 0, len(src)
    in_str: str = ""
    while i < n:
        c = src[i]
        if in_str:
            out.append(c)
            if c == in_str and src[i - 1] != "\\":
                in_str = ""
            i += 1
            continue
        if c in ("'", '"'):
            in_str = c
            out.append(c)
            i += 1
            continue
        if src.startswith("&&", i):
            out.append(" and ")
            i += 2
            continue
        if src.startswith("||", i):
            out.append(" or ")
            i += 2
            continue
        if c == "!" and not src.startswith("!=", i):
            out.append(" not ")
            i += 1
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            word = src[i:j]
            # keyword literals only as standalone identifiers, never after
            # a "." (field names like object.spec.true stay untouched)
            prev = out[-1] if out else ""
            if word in _KEYWORDS and prev != ".":
                out.append(_KEYWORDS[word])
            else:
                out.append(word)
            i = j
            continue
        i += 1
        out.append(c)
    return "".join(out).strip()  # leading "!"-space breaks ast.parse


class _Evaluator:
    def __init__(self, variables: Dict[str, Any]):
        self.vars = variables

    def eval(self, node: ast.AST) -> Any:
        m = getattr(self, f"_eval_{type(node).__name__}", None)
        if m is None:
            raise ExpressionError(
                f"disallowed syntax: {type(node).__name__}")
        return m(node)

    def _eval_Expression(self, n):
        return self.eval(n.body)

    def _eval_Constant(self, n):
        if isinstance(n.value, (bool, int, float, str, type(None))):
            return n.value
        raise ExpressionError(f"disallowed literal {n.value!r}")

    def _eval_List(self, n):
        return [self.eval(e) for e in n.elts]

    def _eval_Name(self, n):
        if n.id in self.vars:
            return self.vars[n.id]
        raise ExpressionError(f"unknown variable {n.id!r}")

    def _eval_Attribute(self, n):
        base = self.eval(n.value)
        if base is MISSING:
            return MISSING
        if isinstance(base, dict):
            return base.get(n.attr, MISSING)
        raise ExpressionError(
            f"cannot navigate .{n.attr} on {type(base).__name__}")

    def _eval_Subscript(self, n):
        base = self.eval(n.value)
        if base is MISSING:
            return MISSING
        idx = self.eval(n.slice)
        if isinstance(base, dict):
            return base.get(idx, MISSING)
        if isinstance(base, list) and isinstance(idx, int):
            return base[idx] if -len(base) <= idx < len(base) else MISSING
        raise ExpressionError("bad subscript")

    def _eval_BoolOp(self, n):
        if isinstance(n.op, ast.And):
            return all(self._truthy(self.eval(v)) for v in n.values)
        return any(self._truthy(self.eval(v)) for v in n.values)

    def _eval_UnaryOp(self, n):
        v = self.eval(n.operand)
        if isinstance(n.op, ast.Not):
            return not self._truthy(v)
        if isinstance(n.op, ast.USub) and isinstance(v, (int, float)):
            return -v
        raise ExpressionError("disallowed unary op")

    def _eval_BinOp(self, n):
        left, right = self.eval(n.left), self.eval(n.right)
        if left is MISSING or right is MISSING:
            return MISSING
        ops = {ast.Add: lambda a, b: a + b, ast.Sub: lambda a, b: a - b,
               ast.Mult: lambda a, b: a * b, ast.Mod: lambda a, b: a % b,
               ast.Div: lambda a, b: a / b}
        fn = ops.get(type(n.op))
        if fn is None:
            raise ExpressionError("disallowed operator")
        try:
            return fn(left, right)
        except Exception as e:
            raise ExpressionError(f"arithmetic error: {e}")

    def _eval_Compare(self, n):
        left = self.eval(n.left)
        for op, comp in zip(n.ops, n.comparators):
            right = self.eval(comp)
            if left is MISSING or right is MISSING:
                # CEL: comparisons against absent fields don't match
                # (except != which is vacuously true against absence)
                ok = isinstance(op, ast.NotEq)
            else:
                try:
                    if isinstance(op, ast.Eq):
                        ok = left == right
                    elif isinstance(op, ast.NotEq):
                        ok = left != right
                    elif isinstance(op, ast.Lt):
                        ok = left < right
                    elif isinstance(op, ast.LtE):
                        ok = left <= right
                    elif isinstance(op, ast.Gt):
                        ok = left > right
                    elif isinstance(op, ast.GtE):
                        ok = left >= right
                    elif isinstance(op, ast.In):
                        ok = left in right
                    elif isinstance(op, ast.NotIn):
                        ok = left not in right
                    else:
                        raise ExpressionError("disallowed comparison")
                except TypeError:
                    ok = False
            if not ok:
                return False
            left = right
        return True

    def _eval_Call(self, n):
        if isinstance(n.func, ast.Attribute):
            # string methods: x.startsWith(s) etc.
            method = n.func.attr
            if method not in _ALLOWED_METHODS:
                raise ExpressionError(f"disallowed method {method!r}")
            base = self.eval(n.func.value)
            args = [self.eval(a) for a in n.args]
            if base is MISSING or any(a is MISSING for a in args):
                return False
            if not isinstance(base, str) or len(args) != 1 \
                    or not isinstance(args[0], str):
                raise ExpressionError(f"{method} expects string operands")
            if method == "startsWith":
                return base.startswith(args[0])
            if method == "endsWith":
                return base.endswith(args[0])
            if method == "contains":
                return args[0] in base
            try:
                return _re.search(args[0], base) is not None
            except _re.error as e:
                raise ExpressionError(f"bad regex: {e}")
        if not isinstance(n.func, ast.Name) or n.func.id not in _ALLOWED_FUNCS:
            raise ExpressionError("disallowed call")
        name = n.func.id
        if len(n.args) != 1:
            raise ExpressionError(f"{name}() takes one argument")
        if name == "has":
            # has() navigates without erroring: absent -> False
            return self.eval(n.args[0]) is not MISSING
        v = self.eval(n.args[0])
        if v is MISSING:
            return MISSING
        try:
            if name == "size":
                return len(v)
            if name == "string":
                return str(v)
            if name == "int":
                return int(v)
            return float(v)
        except (TypeError, ValueError) as e:
            raise ExpressionError(f"{name}(): {e}")

    @staticmethod
    def _truthy(v) -> bool:
        if v is MISSING:
            return False
        if not isinstance(v, bool):
            raise ExpressionError(f"non-boolean in boolean context: {v!r}")
        return v


def compile_expression(src: str) -> Callable[[Dict[str, Any]], bool]:
    """Parse once; returns evaluate(variables) -> bool. Raises
    ExpressionError on disallowed syntax (checked eagerly with dummy
    variables where possible — full checking happens per evaluation)."""
    try:
        tree = ast.parse(_translate(src), mode="eval")
    except SyntaxError as e:
        raise ExpressionError(f"cannot parse {src!r}: {e}")

    for node in ast.walk(tree):
        if isinstance(node, (ast.Lambda, ast.Await, ast.Yield, ast.YieldFrom,
                             ast.NamedExpr, ast.Starred, ast.FormattedValue,
                             ast.JoinedStr, ast.GeneratorExp, ast.ListComp,
                             ast.SetComp, ast.DictComp)):
            raise ExpressionError(
                f"disallowed syntax: {type(node).__name__}")
        if isinstance(node, ast.Attribute) and node.attr.startswith("__"):
            # navigation is dict-keyed so dunders are inert, but reject
            # them eagerly anyway — no policy legitimately uses them
            raise ExpressionError(f"disallowed attribute {node.attr!r}")
        if isinstance(node, ast.Name) and node.id.startswith("__"):
            raise ExpressionError(f"disallowed name {node.id!r}")

    def evaluate(variables: Dict[str, Any]) -> bool:
        result = _Evaluator(variables).eval(tree)
        if result is MISSING:
            return False
        if not isinstance(result, bool):
            raise ExpressionError(
                f"expression must evaluate to bool, got {type(result).__name__}")
        return result

    return evaluate
