"""Select-based watch fan-out: ONE writer thread for every watch stream.

The reference's WatchServer spends a goroutine per stream (handlers/
watch.go:187) — goroutines are cheap. Python threads are not: at 5k watch
streams on a small host, per-event thread wakeups + GIL churn collapsed
fan-out from 25k deliveries/s (500 watchers) to 2.2k/s (5000), with or
without burst batching. The mux replaces the per-stream handler loop: the
HTTP handler writes the response headers, detaches the connection (dup'd
fd), and registers (socket, Watch, render) here; one thread drains every
watch queue, renders frames (shared wire cache upstream), and writes with
a selector handling slow sockets via bounded per-stream backlogs.

Eviction keeps the store's slow-watcher contract: a stream whose pending
buffer exceeds MAX_PENDING (client not reading) or whose Watch was
terminated (queue overflow) is closed; the client relists, exactly as with
the threaded path.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Callable, List, Optional


def bookmark_frame(rv: int) -> bytes:
    """One chunk-framed BOOKMARK event — shared by the mux and the threaded
    watch path so the wire shape can never drift between them."""
    line = json.dumps(
        {"type": "BOOKMARK",
         "object": {"metadata": {"resourceVersion": str(rv)}}}
    ).encode() + b"\n"
    return f"{len(line):x}\r\n".encode() + line + b"\r\n"


class _Stream:
    __slots__ = ("sock", "watch", "render", "pending", "last_sent", "rv_fn")

    def __init__(self, sock, watch, render, rv_fn):
        self.sock = sock
        self.watch = watch
        self.render = render
        self.rv_fn = rv_fn
        self.pending = bytearray()
        self.last_sent = time.monotonic()


class WatchMux:
    MAX_PENDING = 4 * 1024 * 1024  # bytes buffered for a non-reading client
    BOOKMARK_EVERY = 5.0

    def __init__(self):
        self._streams: List[_Stream] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._stopped_forever = False
        self._thread: Optional[threading.Thread] = None

    # -- registration (called from handler threads) ----------------------------

    def add(self, sock: socket.socket, watch, render: Callable,
            rv_fn: Callable[[], int]) -> None:
        sock.setblocking(False)
        st = _Stream(sock, watch, render, rv_fn)
        # immediate wake on new events for THIS watch: the store's deliver
        # path pings the mux instead of waking a dedicated thread
        watch.on_event = self._wake.set
        with self._lock:
            if self._stopped_forever:
                # a handler racing server shutdown must not resurrect the
                # mux (a cleared _stop here would leak thread + stream)
                self._close(st, final_chunk=True)
                return
            self._streams.append(st)
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()
        self._wake.set()

    def stop(self) -> None:
        with self._lock:
            self._stopped_forever = True
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        with self._lock:
            streams, self._streams = self._streams, []
        for st in streams:
            self._close(st, final_chunk=True)

    @property
    def stream_count(self) -> int:
        with self._lock:
            return len(self._streams)

    # -- the loop --------------------------------------------------------------

    def _close(self, st: _Stream, final_chunk: bool = False) -> None:
        st.watch.stop()
        try:
            if final_chunk:
                st.sock.setblocking(False)
                st.sock.send(b"0\r\n\r\n")
        except OSError:
            pass
        try:
            st.sock.close()
        except OSError:
            pass

    def _flush(self, st: _Stream, now: float) -> bool:
        """Send buffered bytes; False = dead socket. Eviction for a
        non-reading client happens only when pending is STILL over the cap
        after the send attempt (a big burst to a fast reader drains here
        and must not be evicted)."""
        if st.pending:
            try:
                sent = st.sock.send(bytes(st.pending))
                if sent:
                    del st.pending[:sent]
                    st.last_sent = now
            except (BlockingIOError, InterruptedError):
                pass  # kernel buffer full: retry next pass
            except OSError:
                return False  # reset/broken pipe
        return len(st.pending) <= self.MAX_PENDING

    def _pump_stream(self, st: _Stream, now: float) -> bool:
        """Render new events into pending + flush; returns False when the
        stream is dead (terminated watch / over-buffered / peer gone)."""
        if st.watch.terminated:
            return False
        for ev in st.watch.drain(512):
            frame = st.render(ev)
            if frame is not None:
                st.pending += frame
        if not st.pending and now - st.last_sent >= self.BOOKMARK_EVERY:
            st.pending += bookmark_frame(st.rv_fn())
        if not self._flush(st, now):
            return False
        # peer-close detection: a readable watch socket either sent bytes
        # (clients don't) or closed
        try:
            got = st.sock.recv(4096)
            if got == b"":
                return False
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            return False
        return True

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            now = time.monotonic()
            with self._lock:
                streams = list(self._streams)
            dead = []
            for st in streams:
                try:
                    ok = self._pump_stream(st, now)
                except Exception:
                    # a poisoned render/predicate kills ONE stream, never
                    # the whole mux (the threaded path's blast radius)
                    ok = False
                if not ok:
                    dead.append(st)
            # drain partial writes promptly WITHOUT re-pumping healthy
            # streams: only sockets with buffered bytes are touched
            slow = [s for s in streams if s.pending and s not in dead]
            deadline = now + 0.2
            while slow and time.monotonic() < deadline \
                    and not self._stop.is_set():
                time.sleep(0.001)
                t = time.monotonic()
                still = []
                for st in slow:
                    try:
                        if not self._flush(st, t):
                            dead.append(st)
                        elif st.pending:
                            still.append(st)
                    except Exception:
                        dead.append(st)
                slow = still
            if slow:
                self._wake.set()  # backlog persists: next pass retries
            if dead:
                with self._lock:
                    self._streams = [s for s in self._streams
                                     if s not in dead]
                for st in dead:
                    self._close(st, final_chunk=True)
