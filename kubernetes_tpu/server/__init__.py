"""L2/L3 — HTTP API server + client (apiserver/client-go analogs)."""

from .client import APIError, Informer, RESTClient  # noqa: F401
from .metrics import Registry, global_registry  # noqa: F401
from .rest import APIServer  # noqa: F401
