"""Admission extensibility: ValidatingAdmissionPolicy + HTTP webhooks.

Two mechanisms, matching the reference's admission plugin split:

- `PolicyAdmission` (apiserver/pkg/admission/plugin/policy/validating/
  plugin.go): ValidatingAdmissionPolicy(+Binding) objects read LIVE from the
  store; expressions run on the restricted evaluator (celexpr.py) over
  `object` / `request`. In-process and allocation-free, so it runs inside
  the normal admission chain (under the store transaction) like every
  compiled-in plugin.

- `WebhookAdmission` (apiserver/pkg/admission/plugin/webhook/{mutating,
  validating}): Mutating/ValidatingWebhookConfiguration objects call out
  over HTTP with an AdmissionReview payload. Webhook round-trips MUST NOT
  run under the store transaction (a slow webhook would stall every store
  consumer; a webhook that calls back into this API server would deadlock
  until timeout), so the REST handlers run this phase BEFORE entering the
  transaction; mutating patches are re-applied to the authoritative object
  inside (rest.py). With zero webhook configurations the phase is two dict
  lookups — the common path stays free.

Self-referential loop guard: the four admissionregistration resources are
never sent to webhooks (the reference excludes webhook configuration
objects the same way).
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from ..store import APIStore
from .admission import AdmissionError, AdmissionPlugin
from .celexpr import ExpressionError, compile_expression

_SELF_RESOURCES = {
    "validatingadmissionpolicies", "validatingadmissionpolicybindings",
    "mutatingwebhookconfigurations", "validatingwebhookconfigurations",
}

_REASON_CODES = {"Invalid": 422, "Forbidden": 403, "Unauthorized": 401,
                 "RequestEntityTooLarge": 413}


def _ns_labels(store: APIStore, namespace: str) -> Dict[str, str]:
    if not namespace:
        return {}
    try:
        ns = store.get("namespaces", namespace)
    except KeyError:
        return {}
    except Exception:
        return {}
    return dict(ns.metadata.labels or {})


class PolicyAdmission(AdmissionPlugin):
    """Evaluates live ValidatingAdmissionPolicy objects bound by
    ValidatingAdmissionPolicyBinding. A policy with no binding is inert;
    a binding's namespaceSelector scopes it; validationActions without
    "Deny" degrade to warnings (per-thread `last_warnings`, never
    rejecting). On UPDATE, `oldObject` is the live stored object (fetched
    under the same transaction, so it is exactly the pre-write state)."""

    name = "ValidatingAdmissionPolicy"

    def __init__(self):
        import threading

        # expression -> compiled evaluator; keyed by source so policy
        # updates (new expression strings) compile fresh
        self._cache: Dict[str, Any] = {}
        self._tl = threading.local()

    @property
    def last_warnings(self) -> List[str]:
        return getattr(self._tl, "warnings", [])

    def _compiled(self, src: str):
        fn = self._cache.get(src)
        if fn is None:
            fn = compile_expression(src)
            if len(self._cache) > 1024:
                self._cache.clear()
            self._cache[src] = fn
        return fn

    @staticmethod
    def _old_object(store: APIStore, resource: str, obj):
        from ..api.serialize import CLUSTER_SCOPED, to_dict

        ns = getattr(obj.metadata, "namespace", "")
        key = obj.metadata.name if (resource in CLUSTER_SCOPED or not ns) \
            else f"{ns}/{obj.metadata.name}"
        try:
            return to_dict(store.get(resource, key))
        except Exception:
            return None

    def validate(self, store: APIStore, resource: str, operation: str, obj,
                 user: str = "") -> None:
        self._tl.warnings = []
        if resource in _SELF_RESOURCES:
            return
        try:
            policies, _ = store.list("validatingadmissionpolicies")
        except Exception:
            return
        if not policies:
            return
        bindings, _ = store.list("validatingadmissionpolicybindings")
        by_policy: Dict[str, List] = {}
        for b in bindings:
            by_policy.setdefault(b.policy_name, []).append(b)
        from ..api.serialize import to_dict

        wire = None
        old = None
        for pol in policies:
            bound = by_policy.get(pol.metadata.name)
            if not bound or not pol.matches(resource, operation):
                continue
            ns = getattr(obj.metadata, "namespace", "")
            active = []
            for b in bound:
                if b.namespace_match_labels is not None:
                    labels = _ns_labels(store, ns)
                    if any(labels.get(k) != v
                           for k, v in b.namespace_match_labels.items()):
                        continue
                active.append(b)
            if not active:
                continue
            if wire is None:
                wire = to_dict(obj)
                if operation == "UPDATE":
                    old = self._old_object(store, resource, obj)
            variables = {
                "object": wire,
                "oldObject": old,
                "request": {"operation": operation, "resource": resource,
                            "userInfo": {"username": user}},
            }
            for v in pol.validations:
                expr = v.get("expression", "")
                try:
                    ok = self._compiled(expr)(variables)
                except ExpressionError as e:
                    if pol.failure_policy == "Ignore":
                        continue
                    raise AdmissionError(
                        f"policy {pol.metadata.name}: expression error: {e}",
                        code=500, reason="InternalError")
                if ok:
                    continue
                message = v.get("message") or \
                    f"failed expression: {expr}"
                msg = f"ValidatingAdmissionPolicy {pol.metadata.name!r} " \
                      f"denied request: {message}"
                deny = any("Deny" in b.validation_actions for b in active)
                if not deny:
                    self.last_warnings.append(msg)
                    continue
                reason = v.get("reason", "Invalid")
                raise AdmissionError(msg,
                                     code=_REASON_CODES.get(reason, 422),
                                     reason=reason)


def apply_json_patch(doc: Dict, patch: List[Dict]) -> Dict:
    """Minimal RFC-6902: add / replace / remove over object keys and list
    indices ("-" appends). The reference's mutating webhooks respond with
    exactly this patch type."""
    doc = json.loads(json.dumps(doc))
    for op in patch:
        kind = op.get("op")
        path = op.get("path", "")
        if not path.startswith("/"):
            raise ValueError(f"bad patch path {path!r}")
        parts = [p.replace("~1", "/").replace("~0", "~")
                 for p in path[1:].split("/")]
        parent: Any = doc
        for p in parts[:-1]:
            parent = parent[int(p)] if isinstance(parent, list) else parent[p]
        last = parts[-1]
        if kind == "add":
            if isinstance(parent, list):
                if last == "-":
                    parent.append(op["value"])
                else:
                    parent.insert(int(last), op["value"])
            else:
                parent[last] = op["value"]
        elif kind == "replace":
            if isinstance(parent, list):
                parent[int(last)] = op["value"]
            else:
                if last not in parent:
                    raise ValueError(f"replace at missing path {path!r}")
                parent[last] = op["value"]
        elif kind == "remove":
            if isinstance(parent, list):
                del parent[int(last)]
            else:
                if last not in parent:
                    raise ValueError(f"remove at missing path {path!r}")
                del parent[last]
        else:
            raise ValueError(f"unsupported patch op {kind!r}")
    return doc


class WebhookAdmission:
    """Calls mutating then validating webhooks with AdmissionReview over
    HTTP. Runs OUTSIDE store transactions (see module docstring). Returns
    the accumulated mutating JSONPatches so PATCH-style handlers can
    re-apply them to the authoritative merged object inside the
    transaction."""

    def __init__(self, store: APIStore, timeout_cap: float = 10.0):
        self.store = store
        self.timeout_cap = timeout_cap

    def _configs(self):
        try:
            mut, _ = self.store.list("mutatingwebhookconfigurations")
            val, _ = self.store.list("validatingwebhookconfigurations")
        except Exception:
            return [], []
        return mut, val

    def active(self) -> bool:
        """Cheap pre-check so PATCH-style handlers skip the pre-read merge
        entirely when no webhook is configured (the common case)."""
        mut, val = self._configs()
        return bool(mut or val)

    def _call(self, hook: Dict, review: Dict) -> Dict:
        url = (hook.get("clientConfig") or {}).get("url", "")
        if not url:
            raise urllib.error.URLError("webhook has no clientConfig.url")
        timeout = min(float(hook.get("timeoutSeconds") or 10.0),
                      self.timeout_cap)
        req = urllib.request.Request(
            url, data=json.dumps(review).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read() or b"{}")

    def run(self, resource: str, operation: str, wire: Dict,
            user: str = "") -> Tuple[Dict, List[List[Dict]]]:
        """-> (possibly-mutated wire dict, list of applied JSONPatches).
        Raises AdmissionError on denial or Fail-policy errors."""
        if resource in _SELF_RESOURCES:
            return wire, []
        from ..api.admissionregistration import _rule_matches

        mut, val = self._configs()
        if not mut and not val:
            return wire, []
        applied: List[List[Dict]] = []

        def review_for(obj_wire: Dict) -> Dict:
            return {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {
                    "uid": (obj_wire.get("metadata") or {}).get("uid", ""),
                    "resource": {"resource": resource},
                    "operation": operation.capitalize(),
                    "name": (obj_wire.get("metadata") or {}).get("name", ""),
                    "namespace": (obj_wire.get("metadata") or {}).get(
                        "namespace", ""),
                    "object": obj_wire,
                    "userInfo": {"username": user},
                },
            }

        def each(configs, mutating: bool):
            nonlocal wire
            for cfg in configs:
                for hook in cfg.webhooks:
                    if not _rule_matches(hook.get("rules") or [],
                                         resource, operation):
                        continue
                    fail_open = (hook.get("failurePolicy") or "Fail") \
                        == "Ignore"
                    try:
                        out = self._call(hook, review_for(wire))
                    except Exception as e:
                        if fail_open:
                            continue
                        raise AdmissionError(
                            f"failed calling webhook "
                            f"{hook.get('name', '?')!r}: {e}",
                            code=500, reason="InternalError")
                    resp = out.get("response") or {}
                    if not resp.get("allowed", False):
                        status = resp.get("status") or {}
                        code = int(status.get("code", 403) or 403)
                        if not 400 <= code <= 599:
                            # a denial must be an error on the wire — the
                            # reference clamps webhook codes the same way
                            code = 403
                        raise AdmissionError(
                            f"admission webhook {hook.get('name', '?')!r} "
                            f"denied the request: "
                            f"{status.get('message', 'denied')}",
                            code=code,
                            reason=status.get("reason", "Forbidden"))
                    if mutating and resp.get("patch"):
                        try:
                            patch = json.loads(
                                base64.b64decode(resp["patch"]))
                            wire = apply_json_patch(wire, patch)
                            applied.append(patch)
                        except Exception as e:
                            if fail_open:
                                continue
                            raise AdmissionError(
                                f"webhook {hook.get('name', '?')!r} "
                                f"returned a bad patch: {e}",
                                code=500, reason="InternalError")

        each(mut, mutating=True)
        each(val, mutating=False)
        return wire, applied
