"""Server-side apply: field ownership, conflict detection, merge.

The reference's canonical write path is server-side apply (SSA): every write
records which *field manager* owns which fields (`metadata.managedFields`,
FieldsV1 trie), an Apply patch merges the applied configuration into the live
object, conflicts arise when an applier changes a field owned by someone
else, `force` steals ownership, and fields a manager previously applied but
dropped from its configuration are *removed* from the object.

Reference semantics reproduced here (file:line cites into /root/reference):
  - staging/src/k8s.io/apimachinery/pkg/util/managedfields/fieldmanager.go:68
    (`Update`) and :96 (`Apply`) — the two entry points below
    (`capture_update`, `apply_patch`).
  - apiserver/pkg/endpoints/handlers/patch.go:432 (`applyPatcher`) — the
    PATCH handler wiring (rest.py do_PATCH, apply-patch content type).
  - Conflict contract: structured-merge-diff merge.Update — changing a field
    owned by another manager without force => 409 listing every
    (manager, field); identical values co-own without conflict; force
    transfers ownership.
  - Removal contract: fields in a manager's previous Apply set, absent from
    the new applied configuration and co-owned by nobody else, are pruned
    from the object (merge.Update remove semantics).
  - Update (PUT/merge-PATCH) ownership: every field an update changes moves
    to the updating manager (fieldmanager.go:68 -> structured-merge-diff
    Updater.Update).

Representation: a field path is a tuple of steps — ("f", key) descends a
map field, ("k", canonical-json) selects a keyed list item, (".",) marks
item existence. A manager's field set is a frozenset of such paths; it
round-trips to the reference's FieldsV1 wire trie ({"f:spec": {"f:replicas":
{}}, "k:{\"name\":\"web\"}": {".": {}}}).

Lists whose items carry one of the reference's patch-merge keys merge
associatively (containers by name, ports by containerPort+protocol, ...);
all other lists are atomic — owned and replaced as a whole (the reference's
listType=atomic default).
"""

from __future__ import annotations

import json
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

Path = Tuple[Any, ...]

# patch-merge keys per field name — the reference's strategic-merge-patch
# tags / listType=map keys (api/core/v1/types.go `patchMergeKey`)
MERGE_KEYS: Dict[str, Tuple[str, ...]] = {
    "containers": ("name",),
    "initContainers": ("name",),
    "ephemeralContainers": ("name",),
    "env": ("name",),
    "volumes": ("name",),
    "volumeMounts": ("mountPath",),
    "volumeDevices": ("devicePath",),
    "ports": ("containerPort", "protocol"),
    "taints": ("key", "effect"),
    "hostAliases": ("ip",),
    "imagePullSecrets": ("name",),
    "topologySpreadConstraints": ("topologyKey", "whenUnsatisfiable"),
    "conditions": ("type",),
    "addresses": ("type",),
    "ownerReferences": ("uid",),
    "secrets": ("name",),
}

# object identity / server-managed bookkeeping is never owned or merged
# (managedfields/gvkparser + the apply strategy's reset fields)
_EXCLUDED_META = {"name", "namespace", "uid", "resourceVersion", "generation",
                  "creationTimestamp", "deletionTimestamp", "managedFields",
                  "selfLink"}
_EXCLUDED_TOP = {"apiVersion", "kind", "status"}


def _key_of(item: Dict, keys: Tuple[str, ...]) -> Optional[str]:
    """Canonical k: selector for a keyed-list item; None if keys missing."""
    if not isinstance(item, dict) or any(k not in item for k in keys):
        return None
    return json.dumps({k: item[k] for k in keys}, sort_keys=True,
                      separators=(",", ":"))


def _keyed(field: str, value: List) -> Optional[Tuple[str, ...]]:
    """Merge keys for this list field, when every item is selectable."""
    keys = MERGE_KEYS.get(field)
    if keys is None or not value:
        return keys if keys is not None and value else None
    if all(_key_of(it, keys) is not None for it in value):
        return keys
    return None


def fields_of(d: Dict, _top: bool = True) -> FrozenSet[Path]:
    """The set of field paths a wire-form object dict specifies."""
    out: List[Path] = []

    def walk(v: Any, prefix: Path, field: str) -> None:
        if isinstance(v, dict):
            if not v:
                out.append(prefix)
                return
            for k, sub in v.items():
                if prefix == () and k in _EXCLUDED_TOP:
                    continue
                if prefix == (("f", "metadata"),) and k in _EXCLUDED_META:
                    continue
                walk(sub, prefix + (("f", k),), k)
            return
        if isinstance(v, list):
            keys = _keyed(field, v)
            if keys is not None:
                for item in v:
                    sel = _key_of(item, keys)
                    item_prefix = prefix + (("k", sel),)
                    out.append(item_prefix + ((".",),))
                    for k, sub in item.items():
                        walk(sub, item_prefix + (("f", k),), k)
                return
        out.append(prefix)  # scalar or atomic list: one leaf

    walk(d, (), "")
    return frozenset(p for p in out if p)


def to_fields_v1(paths: FrozenSet[Path]) -> Dict:
    """Encode a path set as the reference's FieldsV1 trie."""
    root: Dict = {}
    for path in sorted(paths, key=lambda p: tuple(map(str, p))):
        node = root
        for step in path:
            if step == (".",):
                key = "."
            elif step[0] == "f":
                key = f"f:{step[1]}"
            else:
                key = f"k:{step[1]}"
            node = node.setdefault(key, {})
    return root


def from_fields_v1(trie: Dict) -> FrozenSet[Path]:
    out: List[Path] = []

    def walk(node: Dict, prefix: Path) -> None:
        if not node:
            if prefix:
                out.append(prefix)
            return
        for k, sub in node.items():
            if k == ".":
                out.append(prefix + ((".",),))
            elif k.startswith("f:"):
                walk(sub, prefix + (("f", k[2:]),))
            elif k.startswith("k:"):
                walk(sub, prefix + (("k", k[2:]),))

    walk(trie or {}, ())
    return frozenset(out)


def _entry(manager: str, operation: str, paths: FrozenSet[Path]) -> Dict:
    return {"manager": manager, "operation": operation,
            "fieldsType": "FieldsV1", "fieldsV1": to_fields_v1(paths)}


def _sets(managed: List[Dict]) -> List[Tuple[Dict, FrozenSet[Path]]]:
    return [(e, from_fields_v1(e.get("fieldsV1") or {})) for e in managed or []]


def _lookup(d: Dict, path: Path) -> Tuple[bool, Any]:
    """(present, value) of a field path in a wire dict."""
    node: Any = d
    for step in path:
        if step == (".",):
            return True, None
        if step[0] == "f":
            if not isinstance(node, dict) or step[1] not in node:
                return False, None
            node = node[step[1]]
        else:  # keyed item
            if not isinstance(node, list):
                return False, None
            found = None
            for item in node:
                if isinstance(item, dict):
                    sel = json.loads(step[1])
                    if all(item.get(k) == v for k, v in sel.items()):
                        found = item
                        break
            if found is None:
                return False, None
            node = found
    return True, node


class Conflict(Exception):
    """One or more applied fields are owned by other managers."""

    def __init__(self, conflicts: List[Tuple[str, Path]]):
        self.conflicts = conflicts
        msgs = [f"{path_str(p)} (owned by {m!r})" for m, p in conflicts]
        super().__init__("apply conflict: " + "; ".join(msgs))


def path_str(p: Path) -> str:
    parts = []
    for step in p:
        if step == (".",):
            continue
        parts.append(step[1] if step[0] == "f" else f"[{step[1]}]")
    return ".".join(parts)


def _merge(live: Any, applied: Any, field: str) -> Any:
    """Structural merge of the applied config into the live value."""
    if isinstance(applied, dict) and isinstance(live, dict):
        out = dict(live)
        for k, v in applied.items():
            out[k] = _merge(live.get(k), v, k) if k in live else v
        return out
    if isinstance(applied, list) and isinstance(live, list):
        keys = _keyed(field, applied)
        if keys is not None and _keyed(field, live) is not None:
            # associative merge: update matching items in live order,
            # append new items in applied order (structured-merge-diff
            # keeps the live relative order for existing keys)
            applied_by_key = {_key_of(it, keys): it for it in applied}
            out = []
            for item in live:
                sel = _key_of(item, keys)
                if sel in applied_by_key:
                    out.append(_merge(item, applied_by_key.pop(sel), field))
                else:
                    out.append(item)
            out.extend(applied_by_key.values())
            return out
    return applied  # scalars and atomic lists replace


def _remove_path(d: Dict, path: Path) -> None:
    """Delete a leaf path from a wire dict, pruning emptied parents."""
    parents: List[Tuple[Any, Any]] = []  # (container, key/selector)
    node: Any = d
    for step in path:
        if step == (".",):
            break
        if step[0] == "f":
            if not isinstance(node, dict) or step[1] not in node:
                return
            parents.append((node, step[1]))
            node = node[step[1]]
        else:
            if not isinstance(node, list):
                return
            sel = json.loads(step[1])
            idx = next((i for i, it in enumerate(node)
                        if isinstance(it, dict)
                        and all(it.get(k) == v for k, v in sel.items())), None)
            if idx is None:
                return
            parents.append((node, idx))
            node = node[idx]
    if not parents:
        return
    if path[-1] == (".",):
        # item-existence removal: drop the whole list item
        container, key = parents[-1]
        if isinstance(container, list):
            del container[key]
        else:
            container.pop(key, None)
    else:
        container, key = parents[-1]
        if isinstance(container, dict):
            container.pop(key, None)
        elif isinstance(container, list) and isinstance(key, int):
            del container[key]
    # prune parents that became empty (a dict the manager emptied out should
    # not linger as {}), but never the object root
    for container, key in reversed(parents[:-1]):
        child = container[key] if (isinstance(container, dict)
                                   and key in container) else None
        if child in ({}, []):
            if isinstance(container, dict):
                container.pop(key, None)


def apply_patch(live: Optional[Dict], applied: Dict, manager: str,
                force: bool = False) -> Dict:
    """SSA Apply: merge `applied` into `live`, enforce ownership, update
    managedFields. Returns the merged wire dict; raises Conflict.

    live=None is the create path: the applier owns everything it sent.
    Mirrors managedfields/fieldmanager.go:96 + structured-merge-diff
    merge.Update."""
    applied = json.loads(json.dumps(applied))  # defensive deep copy
    applied_set = fields_of(applied)
    if live is None:
        merged = applied
        merged.setdefault("metadata", {})["managedFields"] = [
            _entry(manager, "Apply", applied_set)]
        return merged

    managed = list((live.get("metadata") or {}).get("managedFields") or [])
    own_prev: FrozenSet[Path] = frozenset()
    self_updates: List[Tuple[Dict, FrozenSet[Path]]] = []
    others: List[Tuple[Dict, FrozenSet[Path]]] = []
    for e, s in _sets(managed):
        if e.get("manager") == manager and e.get("operation") == "Apply":
            own_prev = s
        elif e.get("manager") == manager:
            # same manager name via POST/PUT/merge-PATCH: no conflict — an
            # applier silently takes over fields it owned through updates
            # (the reference's documented update->apply takeover); fields it
            # does NOT apply stay in the Update entry (not pruned)
            self_updates.append((e, s))
        else:
            others.append((e, s))

    # conflicts: applied field differs from live AND another manager owns it
    conflicts: List[Tuple[str, Path]] = []
    changing: List[Path] = []
    for p in applied_set:
        present, live_v = _lookup(live, p)
        _, applied_v = _lookup(applied, p)
        if not present or live_v != applied_v:
            changing.append(p)
    for e, s in others:
        hit = s.intersection(changing)
        for p in sorted(hit, key=lambda p: tuple(map(str, p))):
            conflicts.append((e.get("manager", "unknown"), p))
    if conflicts and not force:
        raise Conflict(conflicts)

    merged = _merge(json.loads(json.dumps(live)), applied, "")

    # removal: fields this manager applied before, dropped now, owned by
    # nobody else (incl. its own Update entries)
    foreign: FrozenSet[Path] = frozenset().union(
        *[s for _, s in others + self_updates]) \
        if others or self_updates else frozenset()
    for p in sorted(own_prev - applied_set - foreign,
                    key=lambda p: (-len(p), tuple(map(str, p)))):
        if p[-1] == (".",):
            # a keyed item survives while ANY other entry owns a field
            # inside it (structured-merge-diff keeps items with foreign
            # descendants; only this manager's own fields get pruned)
            prefix = p[:-1]
            if any(q[:len(prefix)] == prefix for q in foreign):
                continue
        if len(p) >= 2 and p[-2][0] == "k" and p[-1][0] == "f" \
                and p[-1][1] in json.loads(p[-2][1]):
            # merge-key fields are the item's identity: they go only when
            # the whole item goes (the "." removal above sorts first)
            continue
        _remove_path(merged, p)

    # new managedFields: this manager's Apply entry is exactly applied_set;
    # forced conflicts move ownership away from the losers; applied fields
    # leave the manager's own Update entries (takeover)
    stolen = frozenset(p for _, p in conflicts)
    new_managed: List[Dict] = []
    for e, s in others:
        remaining = s - stolen
        if remaining:
            new_managed.append(_entry(e.get("manager", "unknown"),
                                      e.get("operation", "Update"), remaining))
    for e, s in self_updates:
        remaining = s - applied_set
        if remaining:
            new_managed.append(_entry(manager,
                                      e.get("operation", "Update"), remaining))
    new_managed.append(_entry(manager, "Apply", applied_set))
    merged.setdefault("metadata", {})["managedFields"] = new_managed
    return merged


def capture_update(before: Optional[Dict], after: Dict,
                   manager: str) -> List[Dict]:
    """Non-apply write (POST/PUT/merge-PATCH): every field the write changed
    moves to `manager` (operation Update); fields the write removed leave all
    managers. Returns the new managedFields list (fieldmanager.go:68).
    Status-subresource writes are not tracked (status is excluded from apply
    ownership outright — _EXCLUDED_TOP)."""
    after_set = fields_of(after)
    if before is None:
        return [_entry(manager, "Update", after_set)]
    managed = list((before.get("metadata") or {}).get("managedFields") or [])
    changed: List[Path] = []
    for p in after_set:
        present, before_v = _lookup(before, p)
        _, after_v = _lookup(after, p)
        if not present or before_v != after_v:
            changed.append(p)
    changed_set = frozenset(changed)
    removed = frozenset(p for p in fields_of(before)
                        if not _lookup(after, p)[0])

    new_managed: List[Dict] = []
    own: FrozenSet[Path] = frozenset()
    for e, s in _sets(managed):
        if e.get("manager") == manager and e.get("operation") == "Update":
            own = s
            continue
        remaining = s - changed_set - removed
        if remaining:
            new_managed.append(_entry(e.get("manager", "unknown"),
                                      e.get("operation", "Update"), remaining))
    mine = (own - removed) | changed_set
    if mine:
        new_managed.append(_entry(manager, "Update", mine))
    return new_managed
