"""Batch extender server: the TPU feasibility/score kernel behind the
scheduler-extender webhook protocol.

Serves the same JSON verbs a kube-scheduler's HTTPExtender POSTs to
(pkg/scheduler/extender.go:43; wire types
staging/src/k8s.io/kube-scheduler/extender/v1/types.go), so a stock scheduler
configured with `extenders: [{urlPrefix: http://this, filterVerb: filter,
prioritizeVerb: prioritize}]` gets its Filter/Score computed by the dense
TPU row kernel (ops/solver.py pod_row_feasibility_score) instead of the
per-node plugin loop.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

import numpy as np

from ..api import Pod
from ..scheduler.extender import MAX_EXTENDER_PRIORITY


class BatchExtenderServer:
    """ThreadingHTTPServer with POST /filter, /prioritize, /bind.

    snapshot_provider returns the current scheduler Snapshot (typically
    `cache.update_snapshot`); cluster tensors are rebuilt only when the
    snapshot object changes. bind_fn, when given, makes /bind available
    (delegating to the API store's Binding write).
    """

    def __init__(self, snapshot_provider: Callable, host: str = "127.0.0.1",
                 port: int = 0, bind_fn: Optional[Callable] = None):
        self.snapshot_provider = snapshot_provider
        self.bind_fn = bind_fn
        self._tensor_cache: Dict[int, object] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, payload: Dict, code: int = 200) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0) or 0)
                try:
                    args = json.loads(self.rfile.read(length).decode() or "{}")
                except json.JSONDecodeError as e:
                    self._reply({"error": f"bad json: {e}"}, code=400)
                    return
                verb = self.path.strip("/")
                try:
                    if verb == "filter":
                        self._reply(outer.handle_filter(args))
                    elif verb == "prioritize":
                        self._reply(outer.handle_prioritize(args))
                    elif verb == "bind" and outer.bind_fn is not None:
                        self._reply(outer.handle_bind(args))
                    else:
                        self._reply({"error": f"unknown verb {verb!r}"}, code=404)
                except Exception as e:  # surfaces as ExtenderFilterResult.error
                    self._reply({"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "BatchExtenderServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- solver plumbing -------------------------------------------------------

    def _row(self, pod: Pod):
        """(node_names, F[N] bool, C[N] int) for the pod against the current
        snapshot, or (node_names, None, None) when the pod's class needs the
        serial path (volumes / inter-pod affinity: not dense-encoded)."""
        from ..ops.solver import make_inputs, pod_row_feasibility_score
        from ..snapshot.tensorizer import build_cluster_tensors, build_pod_batch

        snapshot = self.snapshot_provider()
        with self._lock:
            # cache holds (snapshot, tensors): keeping the snapshot referenced
            # makes the identity check sound (no id() reuse after GC)
            cached = self._tensor_cache.get("latest")
            if cached is not None and cached[0] is snapshot:
                cluster = cached[1]
            else:
                cluster = build_cluster_tensors(snapshot)
                self._tensor_cache = {"latest": (snapshot, cluster)}
        batch = build_pod_batch([pod], snapshot, cluster)
        # pass-through for fallback classes AND pods whose feasibility/score
        # depends on dynamic count tensors (IPA, topology spread): the static
        # pod_row formula below carries neither, only the scan solver does
        if bool(batch.fallback_class[batch.class_of_pod[0]]) or batch.ipa.has_any \
                or batch.ct_class.size or batch.st_class.size:
            return cluster.node_names, None, None
        inputs, _d_max = make_inputs(cluster, batch)
        feas, score = pod_row_feasibility_score(
            inputs, batch.req[0], batch.req_nz[0],
            batch.class_of_pod[0], batch.balanced_active[0])
        n = len(cluster.node_names)
        return cluster.node_names, np.asarray(feas)[:n], np.asarray(score)[:n]

    # -- verbs -----------------------------------------------------------------

    @staticmethod
    def _parse_args(args: Dict):
        pod = Pod.from_dict(args.get("pod") or args.get("Pod") or {})
        requested = args.get("nodenames")
        if requested is None:
            requested = args.get("NodeNames")
        return pod, requested

    def handle_filter(self, args: Dict) -> Dict:
        pod, requested = self._parse_args(args)
        node_names, feas, _score = self._row(pod)
        universe = list(requested) if requested is not None else list(node_names)
        if feas is None:
            # pass-through: this pod's constraints need the serial plugin path;
            # the calling scheduler's own plugins still apply
            return {"nodenames": universe, "failedNodes": {}}
        index = {name: i for i, name in enumerate(node_names)}
        ok, failed = [], {}
        for name in universe:
            i = index.get(name)
            if i is not None and bool(feas[i]):
                ok.append(name)
            else:
                failed[name] = "batch solver: infeasible"
        return {"nodenames": ok, "failedNodes": failed}

    def handle_prioritize(self, args: Dict):
        """Returns a bare HostPriorityList array, the protocol's response body
        for prioritize (extender/v1/types.go:124)."""
        pod, requested = self._parse_args(args)
        node_names, feas, score = self._row(pod)
        universe = list(requested) if requested is not None else list(node_names)
        if score is None:
            return [{"host": n, "score": 0} for n in universe]
        index = {name: i for i, name in enumerate(node_names)}
        raw = {n: (int(score[index[n]]) if index.get(n) is not None and bool(feas[index[n]])
                   else 0)
               for n in universe}
        top = max(raw.values(), default=0)
        # scale to 0..MaxExtenderPriority (extender/v1/types.go:124)
        return [{"host": n, "score": (r * MAX_EXTENDER_PRIORITY // top) if top else 0}
                for n, r in raw.items()]

    def handle_bind(self, args: Dict) -> Dict:
        try:
            self.bind_fn(args.get("podNamespace") or args.get("PodNamespace") or "default",
                         args.get("podName") or args.get("PodName"),
                         args.get("node") or args.get("Node"))
            return {}
        except Exception as e:
            return {"error": str(e)}
