"""Critical-path attribution (ISSUE 18): decompose sampled pods'
submit→bound latency into additive per-stage components and name the
dominant one per window — `ktl sched why` and GET /debug/critpath.

Input is the existing podtrace span set (scheduler/podtrace.py): each
sampled span carries absolute-offset stamps (ms from enqueue) for the
lifecycle edges enqueue → pop → solve → assume → dispatch → bind_confirmed
→ watch_delivered. Consecutive-edge differences are additive BY
CONSTRUCTION, so the components sum exactly to the span's measured
submit_to_bound_ms — the property the acceptance test pins (within 10% at
the p50/p99 quantiles, exactly at the mean).

Components:

  queue_wait  enqueue → pop          time in the scheduling queue
  build       pop → solve, scaled    snapshot + tensorize + build_pod_batch
  solve       pop → solve, scaled    the solver proper
  assume      solve → assume         cache assume + gang quorum
  dispatch    assume → dispatch      handoff to the bind worker
  bind        dispatch → bind_confirmed   store.bind_many + confirm
  watch       bind_confirmed → watch_delivered   POST-bound propagation,
              reported but excluded from the submit→bound sum

The pop→solve edge covers tensorize+build_pod_batch+solve; podtrace stamps
only its ends (per-stage stamps per pod would violate HP001). The split
uses the flight recorder's AGGREGATE stage table — a ratio, not a per-batch
join: flight records are wall-clock stamped while span stamps ride the
scheduler clock, so a per-record time join is not sound. The ratio keeps
the component sum exact (build + solve == the measured edge).

This file is HP001-disciplined (analysis/rules/hotpath.py): pure
arithmetic over the ≤K-sampled span set, no instrumentation calls, no
per-pod taps.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["COMPONENTS", "decompose", "analyze"]

# (component, span stage that closes its edge) in lifecycle order; the
# pop→solve edge lands under "build+solve" and is split by the stage-table
# ratio afterwards.
_EDGES: Tuple[Tuple[str, str], ...] = (
    ("queue_wait", "pop"),
    ("build+solve", "solve"),
    ("assume", "assume"),
    ("dispatch", "dispatch"),
    ("bind", "bind_confirmed"),
)

COMPONENTS = ("queue_wait", "build", "solve", "assume", "dispatch", "bind")


def _nearest_rank(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def build_ratio(stage_table: Optional[Dict]) -> float:
    """Fraction of the pop→solve edge owned by batch construction
    (tensorize + build_pod_batch vs solve), from the aggregate stage table
    ({stage: {"total_ms": ...}}). 0.0 when the table is empty — the whole
    edge then reports as solve."""
    if not stage_table:
        return 0.0
    build_ms = 0.0
    for stage in ("tensorize", "build_pod_batch"):
        row = stage_table.get(stage)
        if row:
            build_ms += float(row.get("total_ms") or 0.0)
    solve_row = stage_table.get("solve") or {}
    solve_ms = float(solve_row.get("total_ms") or 0.0)
    denom = build_ms + solve_ms
    return build_ms / denom if denom > 0 else 0.0


def decompose(span: Dict, ratio: float = 0.0) -> Optional[Dict[str, float]]:
    """One span's additive component breakdown (ms), or None when the span
    never bound. Missing intermediate stamps fold into the next present
    edge, so sum(components) == submit_to_bound_ms always holds. The
    post-bound watch component rides along under "watch" and is NOT part
    of that sum."""
    stamps = span.get("stamps_ms") or {}
    total = span.get("submit_to_bound_ms")
    if total is None or "enqueue" not in stamps:
        return None
    comps: Dict[str, float] = {}
    prev = stamps["enqueue"]
    for comp, stage in _EDGES:
        at = stamps.get(stage)
        if at is None:
            continue
        comps[comp] = max(at - prev, 0.0)
        prev = at
    joint = comps.pop("build+solve", None)
    if joint is not None:
        comps["build"] = joint * ratio
        comps["solve"] = joint * (1.0 - ratio)
    delivered = stamps.get("watch_delivered")
    confirmed = stamps.get("bind_confirmed")
    if delivered is not None and confirmed is not None:
        comps["watch"] = max(delivered - confirmed, 0.0)
    return comps


def _rollup(rows: List[Tuple[Dict[str, float], float]]) -> Dict:
    """Aggregate decomposed rows [(components, total_ms)] into per-component
    p50/p99/mean plus the dominant component and the additivity check
    numbers the acceptance test reads."""
    per: Dict[str, List[float]] = {}
    totals: List[float] = []
    for comps, total in rows:
        totals.append(total)
        for comp, ms in comps.items():
            per.setdefault(comp, []).append(ms)
    totals.sort()
    n = len(totals)
    out_comps: Dict[str, Dict] = {}
    dominant, dominant_mean = None, -1.0
    sum_p50 = sum_p99 = sum_mean = 0.0
    for comp in COMPONENTS + ("watch",):
        vals = per.get(comp)
        if not vals:
            continue
        vals.sort()
        mean = sum(vals) / len(vals)
        row = {"p50_ms": round(_nearest_rank(vals, 0.50), 3),
               "p99_ms": round(_nearest_rank(vals, 0.99), 3),
               "mean_ms": round(mean, 4)}
        out_comps[comp] = row
        if comp == "watch":  # post-bound: excluded from the sum + dominance
            continue
        sum_p50 += row["p50_ms"]
        sum_p99 += row["p99_ms"]
        sum_mean += mean
        if mean > dominant_mean:
            dominant, dominant_mean = comp, mean
    total_mean = sum(totals) / n if n else 0.0
    return {
        "count": n,
        "components": out_comps,
        "dominant": dominant,
        "dominant_share": round(dominant_mean / total_mean, 4)
        if total_mean > 0 and dominant_mean >= 0 else None,
        "sum_p50_ms": round(sum_p50, 3),
        "total_p50_ms": round(_nearest_rank(totals, 0.50), 3),
        "sum_p99_ms": round(sum_p99, 3),
        "total_p99_ms": round(_nearest_rank(totals, 0.99), 3),
        "sum_mean_ms": round(sum_mean, 4),
        "total_mean_ms": round(total_mean, 4),
    }


def analyze(spans: List[Dict], stage_table: Optional[Dict] = None) -> Dict:
    """Group bound spans by rotation window, roll each window (and the
    whole set) up into component quantiles + the dominant component.
    `stage_table` is the flight recorder's aggregate table (stage_table())
    used for the build/solve split ratio."""
    ratio = build_ratio(stage_table)
    by_window: Dict[int, List[Tuple[Dict[str, float], float]]] = {}
    all_rows: List[Tuple[Dict[str, float], float]] = []
    skipped = 0
    for span in spans or ():
        comps = decompose(span, ratio)
        if comps is None:
            skipped += 1
            continue
        row = (comps, float(span.get("submit_to_bound_ms") or 0.0))
        by_window.setdefault(int(span.get("window") or 0), []).append(row)
        all_rows.append(row)
    return {
        "build_ratio": round(ratio, 4),
        "spans_analyzed": len(all_rows),
        "spans_skipped": skipped,
        "windows": {w: _rollup(rows)
                    for w, rows in sorted(by_window.items())},
        "overall": _rollup(all_rows) if all_rows else None,
    }
