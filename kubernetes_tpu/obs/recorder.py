"""Generic ring/stage recorder machinery — the reusable half of the flight
recorder (factored out of scheduler/flightrec.py, ISSUE 9).

Design constraints inherited from PR 3/PR 7, and binding on every consumer:

  - taps are O(1) per BATCH/loop/chunk, never per pod/key/event in a
    pod-scale loop (schedlint HP001 enforces this in the hot files);
  - `time.perf_counter()` is the only usable tap clock in this container
    (`time.thread_time()` ticks at 10ms);
  - everything is bounded: the record ring evicts oldest, the per-stage
    histograms survive eviction at fixed memory;
  - measured self-time accrues to a sink (note_self_time) so the <2%
    instrumentation budget is bounded from a measurement, not by
    differencing two noisy runs.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Windowed per-stage latency buckets (ISSUE 7): log-spaced 0.2ms..~42s so
# the p50/p99 estimates survive ring eviction at bounded memory. The ~1.55x
# bucket ratio bounds the interpolation error well inside the headroom any
# sane SLO ceiling carries; records still in the ring get EXACT nearest-rank
# percentiles instead (stage_table picks whichever source is lossless).
STAGE_P_BUCKETS = tuple(round(0.0002 * (1.55 ** i), 6) for i in range(28))


def nearest_rank(sorted_vals: List[float], q: float) -> float:
    """Exact nearest-rank percentile over a complete sample."""
    return sorted_vals[min(len(sorted_vals) - 1,
                           max(0, math.ceil(q * len(sorted_vals)) - 1))]


class StageClock:
    """Per-batch stage boundary marks. mark(name) attributes the time since
    the previous boundary; skip() moves the boundary without attributing
    (work another accumulator already claimed)."""

    __slots__ = ("t0", "_last", "stages")

    def __init__(self):
        self.t0 = self._last = time.perf_counter()
        self.stages: Dict[str, float] = {}

    def mark(self, name: str) -> float:
        now = time.perf_counter()
        dt = now - self._last
        self.stages[name] = self.stages.get(name, 0.0) + dt
        self._last = now
        return dt

    def skip(self) -> None:
        self._last = time.perf_counter()

    def add(self, name: str, seconds: float) -> None:
        if seconds > 0:
            self.stages[name] = self.stages.get(name, 0.0) + seconds

    def sub(self, name: str, seconds: float) -> None:
        """Remove sub-stage time another bucket owns (floored at 0)."""
        if seconds > 0 and name in self.stages:
            self.stages[name] = max(0.0, self.stages[name] - seconds)

    def total(self) -> float:
        return time.perf_counter() - self.t0


class RingRecorder:
    """Bounded ring of per-loop/per-batch records plus per-stage aggregate
    state: totals and counts since clear() (survive ring eviction), windowed
    per-stage latency histograms feeding the p50/p99 columns, outside-bucket
    accumulators for work that runs between records, and measured self-time.

    Subclasses (FlightRecorder, ReconcileRecorder) own the record SCHEMA:
    they build their dict and hand it to _append_record with the stage map.
    """

    DEFAULT_CAPACITY = 64

    def __init__(self, capacity: int = DEFAULT_CAPACITY, enabled: bool = True):
        self.capacity = capacity
        self.enabled = enabled
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=capacity)
        self._seq = 0
        # aggregate per-stage seconds since clear(), across ALL records —
        # survives ring eviction so the stage table covers the full window
        self._stage_totals: Dict[str, float] = {}
        self._stage_batches: Dict[str, int] = {}
        # per-stage seconds accrued outside any record (add_outside)
        self._outside: Dict[str, float] = {}
        # per-stage latency histograms: one observation per record (or per
        # outside-bucket call), never evicted with the ring. Built lazily;
        # metrics.Histogram carries its own lock but every write here
        # happens under self._lock anyway.
        self._stage_hist: Dict[str, object] = {}
        # instrumentation self-time: seconds spent building records,
        # observing histograms, and in the timing taps. Divided by wall it
        # bounds the overhead budget from a measurement.
        self._self_s = 0.0
        # optional TimeSeriesRecorder (obs/timeseries.py, ISSUE 13): when
        # set, per-record stage maps and outside-bucket observations are
        # forwarded so the windowed view covers the overlapped stages
        # (bind, bind_wait, queue_add) the per-batch clock never sees
        self.timeseries = None

    # -- ingest ----------------------------------------------------------------

    def _hist_observe(self, stage: str, seconds: float) -> None:
        """One per-stage latency observation (caller holds self._lock)."""
        h = self._stage_hist.get(stage)
        if h is None:
            from ..server.metrics import Histogram

            h = self._stage_hist[stage] = Histogram(
                stage, buckets=STAGE_P_BUCKETS)
        h.observe(seconds)

    def add_outside(self, stage: str, seconds: float) -> None:
        if not self.enabled or seconds <= 0:
            return
        with self._lock:
            self._outside[stage] = self._outside.get(stage, 0.0) + seconds
            self._hist_observe(stage, seconds)
        ts = self.timeseries
        if ts is not None:
            ts.note_stage(stage, seconds)

    def outside_seconds(self, *stages: str) -> float:
        """Sum of the named outside buckets (the scheduler differences this
        around a pump to keep 'ingest' disjoint from its sub-stages)."""
        with self._lock:
            return sum(self._outside.get(s, 0.0) for s in stages)

    def note_self_time(self, seconds: float) -> None:
        with self._lock:
            self._self_s += seconds

    def _append_record(self, rec: Dict, stages: Dict[str, float]) -> Dict:
        """Ring append + per-stage aggregate updates for one record (caller
        holds self._lock; stage values in SECONDS). Stamps seq/ts AND the
        record's rendered `stages` map (milliseconds) — derived here so a
        subclass cannot desync the in-ring percentile source (read as ms by
        stage_table's exact path) from the histogram source (seconds)."""
        self._seq += 1
        rec["seq"] = self._seq
        rec["ts"] = time.time()
        rec["stages"] = {k: round(v * 1000, 3) for k, v in stages.items()}
        self._records.append(rec)
        for k, v in stages.items():
            self._stage_totals[k] = self._stage_totals.get(k, 0.0) + v
            self._stage_batches[k] = self._stage_batches.get(k, 0) + 1
            self._hist_observe(k, v)
        return rec

    # -- read side -------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._records)

    def last(self) -> Optional[Dict]:
        with self._lock:
            return self._records[-1] if self._records else None

    @property
    def self_seconds(self) -> float:
        with self._lock:
            return self._self_s

    def stage_table(self, order=(), overlapped=frozenset()) -> Dict[str, Dict]:
        """Aggregate per-stage view across every record since clear() plus
        the outside buckets: {stage: {total_ms, mean_ms, p50_ms, p99_ms,
        batches, overlapped}}.

        Percentile source (ISSUE 7): nearest-rank over the per-record ring
        while every observation is still in it (exact); once eviction or
        per-call outside observations outgrow the ring, the windowed stage
        histogram takes over (bucket-interpolated, error bounded by the
        STAGE_P_BUCKETS ratio)."""
        with self._lock:
            totals = dict(self._stage_totals)
            batches = dict(self._stage_batches)
            outside = dict(self._outside)
            hists = dict(self._stage_hist)
            ring_vals: Dict[str, List[float]] = {}
            for rec in self._records:
                for k, ms in rec["stages"].items():
                    ring_vals.setdefault(k, []).append(ms)

        def pcts(name):
            h = hists.get(name)
            n_obs = h._total if h is not None else 0
            vals = ring_vals.get(name)
            if vals and len(vals) == n_obs:
                vals = sorted(vals)
                return (round(nearest_rank(vals, 0.50), 3),
                        round(nearest_rank(vals, 0.99), 3))
            if h is None or n_obs == 0:
                return None, None
            return (round(h.quantile(0.50) * 1000, 3),
                    round(h.quantile(0.99) * 1000, 3))

        out: Dict[str, Dict] = {}
        for name in order:
            sec = totals.get(name, 0.0) + outside.get(name, 0.0)
            n = batches.get(name, 0)
            if sec == 0.0 and n == 0:
                continue
            p50, p99 = pcts(name)
            out[name] = {
                "total_ms": round(sec * 1000, 3),
                "mean_ms": round(sec * 1000 / n, 3) if n else None,
                "p50_ms": p50,
                "p99_ms": p99,
                "batches": n,
                "overlapped": name in overlapped,
            }
        # anything recorded under a name the caller's order doesn't know
        # keeps rendering (forward compatibility for new stages)
        for name in set(totals) | set(outside):
            if name not in out:
                sec = totals.get(name, 0.0) + outside.get(name, 0.0)
                p50, p99 = pcts(name)
                out[name] = {"total_ms": round(sec * 1000, 3),
                             "mean_ms": None,
                             "p50_ms": p50,
                             "p99_ms": p99,
                             "batches": batches.get(name, 0),
                             "overlapped": False}
        return out

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._stage_totals.clear()
            self._stage_batches.clear()
            self._outside.clear()
            self._stage_hist.clear()
            self._self_s = 0.0
            self._clear_extra()

    def _clear_extra(self) -> None:
        """Subclass hook: clear subclass state (caller holds self._lock)."""
