"""Windowed time-series telemetry — the steady-state view (ISSUE 13).

Every observability surface so far (flight recorder, SLO gates, podtrace)
aggregates over a WHOLE run: perfect for single-shot rungs, blind for a
control plane that runs forever under churn. Two slow-growth defects proved
the blindness (the PR-11 parked-bind-worker heap pin, the PR-7 dead-worker
debt leak): neither moves an end-of-run p99, both are a straight line on a
per-window chart. This module is that chart.

  TimeSeriesRecorder — fixed-interval windows (default 5s) over the batch
      pipeline, ring-bounded. ONE tap per batch (HP001 discipline: never per
      pod): note_batch() folds the batch's StageClock map + counts into the
      OPEN window; when a batch (or a read) lands past the window end the
      window CLOSES — per-stage p50/p99 settle by nearest-rank over the
      window's per-batch samples (bounded by batches/window), probes fire
      ONCE (queue depth, breaker state, watch lag, partition counters,
      resource-sampler columns, and — ISSUE 16 — the "alloc" probe's
      pod_obj_allocs gauge: per-window pod-object materializations summed
      across the store and scheduler-cache columnar tables, 0 at the
      end-to-end columnar steady state), and the closed dict joins the ring.
      Measured settle/tap self-time accrues to stat_sink (the flight
      recorder's <2% instrumentation budget covers this layer too).

  fit_slope / drift_ratio — the trend math the leak/regression gates in
      scheduler/slo.py consume: least-squares slope over (t, value) points
      (RSS MB/min, live-object blocks/s) and a last-third vs first-third
      drift ratio for "is the p99 creeping" without modeling the noise.

Per-window records double as an offline training corpus for the direction-5
learned-scorer experiment (arxiv 2601.13579): each row is a labeled
(load, latency, resource) snapshot at fixed cadence.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .recorder import nearest_rank

# how many closed windows the ring keeps (default: 20 min of 5s windows)
DEFAULT_CAPACITY = 240
DEFAULT_WINDOW_S = 5.0


def extract_series(windows: List[Dict], *path: str
                   ) -> List[Tuple[float, float]]:
    """[(window end_ts, value)] for one dotted path across window records
    (e.g. ("stages", "solve", "p99_ms") or ("resource", "rss_mb")) — the
    shared feed of TimeSeriesRecorder.series() and the slo.py trend gates.
    Windows missing the path are skipped (honest gaps, not zeros)."""
    out = []
    for rec in windows:
        node = rec
        for p in path:
            if not isinstance(node, dict) or p not in node:
                node = None
                break
            node = node[p]
        if isinstance(node, (int, float)):
            out.append((rec.get("end_ts", 0.0), float(node)))
    return out


def fit_slope(points: List[Tuple[float, float]]) -> Optional[float]:
    """Least-squares slope (units/second) over (t, value) points; None with
    fewer than 2 distinct timestamps. Plain closed-form fit — the gates need
    'is this line going up', not a model of the noise."""
    if len(points) < 2:
        return None
    n = float(len(points))
    sx = sum(p[0] for p in points)
    sy = sum(p[1] for p in points)
    sxx = sum(p[0] * p[0] for p in points)
    sxy = sum(p[0] * p[1] for p in points)
    denom = n * sxx - sx * sx
    if denom <= 0.0:
        return None  # all samples share one timestamp
    return (n * sxy - sx * sy) / denom


def drift_ratio(values: List[float]) -> Optional[float]:
    """Median of the last third over median of the first third — the 'is
    the tail creeping up under steady load' detector. A flat series reads
    ~1.0; monotonic growth reads >1. Medians, not means: one co-scheduling
    stall in either third must not fake (or mask) a drift verdict — a real
    leak raises the median too. None under 3 samples or a zero/negative
    first-third median (ratio would be meaningless)."""
    if len(values) < 3:
        return None
    third = max(1, len(values) // 3)

    def med(vs: List[float]) -> float:
        s = sorted(vs)
        return s[len(s) // 2]

    h = med(values[:third])
    if h <= 0.0:
        return None
    return med(values[-third:]) / h


class _OpenWindow:
    """Accumulator for the window currently filling (private to the
    recorder; all access under its lock)."""

    __slots__ = ("start", "end", "stage_samples", "stage_totals", "batches",
                 "pods", "scheduled", "failed")

    def __init__(self, start: float, end: float):
        self.start = start
        self.end = end
        # per-stage per-batch seconds — bounded by batches/window, the
        # nearest-rank source for the window's p50/p99 at close
        self.stage_samples: Dict[str, List[float]] = {}
        self.stage_totals: Dict[str, float] = {}
        self.batches = 0
        self.pods = 0
        self.scheduled = 0
        self.failed = 0


class TimeSeriesRecorder:
    """Ring of closed fixed-interval windows over the batch pipeline.

    Write side: note_batch() once per schedule_batch (O(stages), never per
    pod). Read side: windows() / series() close an expired open window
    first, so an idle scheduler's last window still settles. Probes are
    callables fired once per window CLOSE returning a flat dict merged into
    the window record — the place queue depth, breaker state, watch lag and
    sampler columns enter without the hot path paying for them per batch.
    """

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 capacity: int = DEFAULT_CAPACITY, enabled: bool = True,
                 stat_sink=None):
        self.window_s = float(window_s)
        self.capacity = capacity
        self.enabled = enabled
        self.stat_sink = stat_sink  # FlightRecorder: self-time budget
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._open: Optional[_OpenWindow] = None
        self._probes: List[Tuple[str, Callable[[], Optional[Dict]]]] = []
        self._seq = 0
        self.windows_closed = 0
        self._self_s = 0.0

    # -- configuration ---------------------------------------------------------

    def add_probe(self, name: str, fn: Callable[[], Optional[Dict]]) -> None:
        """Register a window-close probe. fn() returns a flat dict merged
        into every closed window (or None to contribute nothing); it runs
        once per window, off the per-batch path, and an exception skips the
        probe rather than losing the window."""
        with self._lock:
            self._probes.append((name, fn))

    # -- write side ------------------------------------------------------------

    def note_batch(self, stages: Dict[str, float], pods: int = 0,
                   scheduled: int = 0, failed: int = 0,
                   now: Optional[float] = None) -> None:
        """Fold ONE batch into the open window (stage values in SECONDS —
        the StageClock map). The single hot-path tap: everything else this
        module does runs at window close or read time."""
        if not self.enabled:
            return
        t0 = time.perf_counter()
        now = t0 if now is None else now
        with self._lock:
            w = self._advance_locked(now)
            w.batches += 1
            w.pods += pods
            w.scheduled += scheduled
            w.failed += failed
            for name, sec in stages.items():
                w.stage_samples.setdefault(name, []).append(sec)
                w.stage_totals[name] = w.stage_totals.get(name, 0.0) + sec
        self._bill(time.perf_counter() - t0)

    def note_stage(self, name: str, seconds: float,
                   now: Optional[float] = None) -> None:
        """Fold one outside-bucket observation (bind worker wall, bind_wait
        stall, bulk queue_add) into the open window — the RingRecorder
        add_outside forwarding path. O(1), callable from the bind worker
        thread (the lock is the only shared state)."""
        if not self.enabled:
            return
        t0 = time.perf_counter()
        now = t0 if now is None else now
        with self._lock:
            w = self._advance_locked(now)
            w.stage_samples.setdefault(name, []).append(seconds)
            w.stage_totals[name] = w.stage_totals.get(name, 0.0) + seconds
        self._bill(time.perf_counter() - t0)

    def _bill(self, seconds: float) -> None:
        # under the lock: note_stage runs on the bind worker concurrently
        # with note_batch on the scheduling thread
        with self._lock:
            self._self_s += seconds
        sink = self.stat_sink
        if sink is not None:
            sink.note_self_time(seconds)

    def _advance_locked(self, now: float) -> _OpenWindow:
        """Close any expired open window and return the one covering `now`
        (caller holds self._lock). A long idle gap closes the single stale
        window and opens one fresh window at the current boundary — no
        fabricated empty windows in between (slope fits use real
        timestamps, so gaps are honest)."""
        w = self._open
        if w is not None and now < w.end:
            return w
        if w is not None:
            self._close_locked(w)
        # contiguous load: the next window abuts the closed one; after an
        # idle gap (or at birth) a fresh epoch starts AT `now` — either way
        # the new window covers `now`
        if w is None or now - w.end >= self.window_s:
            start = now
        else:
            start = w.end
        self._open = _OpenWindow(start, start + self.window_s)
        return self._open

    def _close_locked(self, w: _OpenWindow) -> None:
        """Settle one window into the ring (caller holds self._lock): per-
        stage nearest-rank p50/p99 over the window's per-batch samples plus
        one probe sweep. Cost is O(stages x batches-in-window log) once per
        window_s — never on the per-pod path."""
        self._seq += 1
        self.windows_closed += 1
        stages: Dict[str, Dict] = {}
        for name, samples in w.stage_samples.items():
            samples.sort()
            tot = w.stage_totals.get(name, 0.0)
            stages[name] = {
                "total_ms": round(tot * 1000, 3),
                "p50_ms": round(nearest_rank(samples, 0.50) * 1000, 3),
                "p99_ms": round(nearest_rank(samples, 0.99) * 1000, 3),
                "batches": len(samples),
            }
        span = max(w.end - w.start, 1e-9)
        rec = {
            "seq": self._seq,
            # start/end ride the perf_counter domain (slope math needs the
            # monotonic axis); ts is the wall clock for remote rendering
            "ts": round(time.time(), 3),
            # cumulative recorder self-time at close — consecutive windows
            # difference to "instrumentation paid THIS window" (ISSUE 13
            # acceptance: self-time measured and published per window)
            "self_s": round(self._self_s, 6),
            "start_ts": round(w.start, 6),
            "end_ts": round(w.end, 6),
            "window_s": round(self.window_s, 3),
            "batches": w.batches,
            "pods": w.pods,
            "scheduled": w.scheduled,
            "failed": w.failed,
            "pods_per_sec": round(w.scheduled / span, 1),
            "stages": stages,
        }
        for name, fn in self._probes:
            try:
                got = fn()
            except Exception:
                continue  # a wedged probe must not lose the window
            if got:
                rec[name] = got
        self._ring.append(rec)

    # -- read side -------------------------------------------------------------

    def windows(self, last: Optional[int] = None) -> List[Dict]:
        """Closed windows, oldest first (the ring's bound). Settles an
        expired open window first so an idle tail still rolls."""
        if not self.enabled:
            return []
        t0 = time.perf_counter()
        with self._lock:
            w = self._open
            if w is not None and t0 >= w.end:
                self._close_locked(w)
                self._open = None
            out = list(self._ring)
        self._bill(time.perf_counter() - t0)
        return out[-last:] if last else out

    def series(self, *path: str, last: Optional[int] = None
               ) -> List[Tuple[float, float]]:
        """extract_series over this recorder's closed windows — what the
        slope/drift gates consume live."""
        return extract_series(self.windows(last=last), *path)

    @property
    def self_seconds(self) -> float:
        return self._self_s

    def clear(self) -> None:
        """Drop every window AND the open accumulator — the bench's
        warmup-exclusion idiom (flightrec.clear() sibling)."""
        with self._lock:
            self._ring.clear()
            self._open = None
            self._seq = 0
            self.windows_closed = 0
            self._self_s = 0.0
