"""Reconcile-loop recorder: per-loop telemetry for every controller (ISSUE 9).

PR 3/PR 7 measured the scheduler half of "watch, reconcile, write status";
the ~20 controllers in kubernetes_tpu/controllers/ were dark — an
unmeasured controller or a backlogged watcher under priority-mixed churn
silently eats the SLO. This module gives controllers/base.py the same
machinery the scheduler has, built on the SAME RingRecorder base
(obs/recorder.py):

  ReconcileRecorder       — bounded ring of per-LOOP records (one record per
                            non-empty process() drain, one histogram
                            observation per pump that ingested events —
                            never per key or per event), with the p50/p99
                            stage table and running counters.
  registry                — weak registry of live controllers (the configz
                            pattern, same as flightrec's scheduler registry)
                            behind GET /debug/controlstats and
                            `ktl controller stats`.
  workqueue_depth_samples — render-time feed for the
                            controller_workqueue_depth GaugeFunc.

Taps are O(1) per loop: two perf_counter reads around the key drain, one
shared clock read per pump for first-marked timestamps, one record append.
The oldest-dirty-age scan is O(depth) and therefore THROTTLED to 1/s with a
cached value (the PR 7 queue-telemetry idiom).
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional, Tuple

from .recorder import RingRecorder

# per-loop stages: "pump" = watch drain + dirty marking, "sync" = the
# process() drain through sync(key)
RECONCILE_STAGES = ("pump", "sync")


class ReconcileRecorder(RingRecorder):
    """Per-controller reconcile-loop recorder (one instance per controller,
    created by controllers/base.py)."""

    def __init__(self, name: str,
                 capacity: int = RingRecorder.DEFAULT_CAPACITY,
                 enabled: bool = True):
        super().__init__(capacity=capacity, enabled=enabled)
        self.name = name
        self.loops = 0          # non-empty process() drains
        self.keys_total = 0     # keys handed to sync() across all loops
        self.errors_total = 0   # sync() exceptions (each also requeues)
        self.requeues_total = 0
        self.events_total = 0   # watch events ingested by pump()

    def pump(self, events: int, seconds: float) -> None:
        """One pump() drain: ONE histogram observation when events were
        ingested (empty polls are not ring-worthy — at daemon cadence they
        would be 95% of the ring)."""
        if not self.enabled or events <= 0:
            return
        with self._lock:
            self.events_total += events
            self._outside["pump"] = self._outside.get("pump", 0.0) + seconds
            self._hist_observe("pump", seconds)

    def loop(self, *, keys: int, errors: int, requeues: int,
             seconds: float, depth: int) -> Optional[Dict]:
        """One process() drain through sync() — per LOOP, never per key.
        Returns the appended record (None when disabled/empty)."""
        if not self.enabled or keys <= 0:
            return None
        from ..server import metrics as m

        m.controller_reconcile_duration.observe(seconds, self.name)
        if errors:
            m.controller_sync_errors.inc(errors, controller=self.name)
        with self._lock:
            self.loops += 1
            self.keys_total += keys
            self.errors_total += errors
            self.requeues_total += requeues
            rec = {
                "controller": self.name,
                "keys": keys,
                "errors": errors,
                "requeues": requeues,
                "depth": depth,
                "total_ms": round(seconds * 1000, 3),
            }
            return self._append_record(rec, {"sync": seconds})

    def _clear_extra(self) -> None:
        self.loops = 0
        self.keys_total = 0
        self.errors_total = 0
        self.requeues_total = 0
        self.events_total = 0

    def snapshot(self) -> Dict:
        """The per-controller /debug/controlstats payload."""
        table = self.stage_table(order=RECONCILE_STAGES)
        with self._lock:
            out = {
                "controller": self.name,
                "enabled": self.enabled,
                "loops": self.loops,
                "keys": self.keys_total,
                "errors": self.errors_total,
                "requeues": self.requeues_total,
                "events": self.events_total,
                "records": len(self._records),
                "capacity": self.capacity,
                "self_seconds": round(self._self_s, 6),
                "last": self._records[-1] if self._records else None,
            }
        out["stages"] = table
        sync = table.get("sync") or {}
        out["reconcile_p50_ms"] = sync.get("p50_ms")
        out["reconcile_p99_ms"] = sync.get("p99_ms")
        return out


# -- live-controller registry (the configz pattern, like flightrec's) -----------

_registry_lock = threading.Lock()
_controllers: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()


def register_controller(name: str, controller) -> None:
    """Register a live controller for /debug/controlstats. Weak + latest
    wins per name: a stopped and collected controller drops out without an
    unregister call, and the daemon's singletons keep stable names."""
    with _registry_lock:
        _controllers[name] = controller


def controlstats_snapshot() -> Dict[str, Dict]:
    """{controller name: reconcile_stats()} over every live registered
    controller — what GET /debug/controlstats and `ktl controller stats`
    serve."""
    with _registry_lock:
        live = dict(_controllers)
    out = {}
    for name, c in sorted(live.items()):
        stats = getattr(c, "reconcile_stats", None)
        if stats is None:
            continue
        try:
            out[name] = stats()
        except Exception as e:  # a wedged controller must not 500 the endpoint
            out[name] = {"error": str(e)}
    return out


def reconcile_rollup(snapshot: Optional[Dict[str, Dict]] = None) -> Dict:
    """The cross-controller rollup the reconcile_p99_ms SLO key gates: the
    WORST per-controller sync p99 (a single dark-slow controller must fail
    the ceiling, not be averaged away), plus totals."""
    snap = controlstats_snapshot() if snapshot is None else snapshot
    worst = None
    worst_name = None
    loops = keys = errors = 0
    for name, st in snap.items():
        if "error" in st and len(st) == 1:
            continue
        loops += st.get("loops", 0)
        keys += st.get("keys", 0)
        errors += st.get("errors", 0)
        p99 = st.get("reconcile_p99_ms")
        if p99 is not None and (worst is None or p99 > worst):
            worst, worst_name = p99, name
    return {"p99_ms": worst, "worst_controller": worst_name,
            "controllers": len(snap), "loops": loops, "keys": keys,
            "errors": errors}


def workqueue_depth_samples() -> List[Tuple[Dict[str, str], float]]:
    """Render-time samples for the controller_workqueue_depth GaugeFunc."""
    with _registry_lock:
        live = dict(_controllers)
    out = []
    for name, c in live.items():
        depth = getattr(c, "workqueue_depth", None)
        if depth is None:
            continue
        try:
            out.append(({"controller": name}, float(depth())))
        except Exception:
            continue
    return out
