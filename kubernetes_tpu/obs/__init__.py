"""Shared observability machinery (ISSUE 9).

The flight recorder (PR 3/PR 7) proved a set of idioms on the scheduler —
O(1) per-batch taps on perf_counter, bounded record rings, windowed
log-bucket stage histograms with exact-while-complete percentiles, measured
self-time against a <2% budget. This package factors the reusable half out
of scheduler/flightrec.py so the rest of the control plane (the ~20
reconcile controllers, the store's watch bus) can inherit the same
machinery instead of reinventing weaker copies:

  obs.recorder   — StageClock + RingRecorder (the generic bounded ring with
                   per-stage totals/histograms and the p50/p99 stage table).
  obs.reconcile  — ReconcileRecorder: per-loop reconcile spans for
                   controllers/base.py, plus the live-controller registry
                   behind GET /debug/controlstats and `ktl controller stats`.
  obs.timeseries — TimeSeriesRecorder: fixed-interval windows over the batch
                   pipeline (per-stage p50/p99, pods/s, probe columns) plus
                   the fit_slope/drift_ratio trend math the leak gates in
                   scheduler/slo.py consume (ISSUE 13).
  obs.resource   — ResourceSampler: RSS / GC / live-object / per-thread CPU
                   sampling with a measured-clock honesty flag — the
                   steady-state leak and GIL-overlap signal (ISSUE 13).
"""

from .recorder import (  # noqa: F401
    STAGE_P_BUCKETS,
    RingRecorder,
    StageClock,
    nearest_rank,
)
from .timeseries import (  # noqa: F401
    TimeSeriesRecorder,
    drift_ratio,
    fit_slope,
)
from .resource import ResourceSampler  # noqa: F401
