"""Shared observability machinery (ISSUE 9).

The flight recorder (PR 3/PR 7) proved a set of idioms on the scheduler —
O(1) per-batch taps on perf_counter, bounded record rings, windowed
log-bucket stage histograms with exact-while-complete percentiles, measured
self-time against a <2% budget. This package factors the reusable half out
of scheduler/flightrec.py so the rest of the control plane (the ~20
reconcile controllers, the store's watch bus) can inherit the same
machinery instead of reinventing weaker copies:

  obs.recorder   — StageClock + RingRecorder (the generic bounded ring with
                   per-stage totals/histograms and the p50/p99 stage table).
  obs.reconcile  — ReconcileRecorder: per-loop reconcile spans for
                   controllers/base.py, plus the live-controller registry
                   behind GET /debug/controlstats and `ktl controller stats`.
"""

from .recorder import (  # noqa: F401
    STAGE_P_BUCKETS,
    RingRecorder,
    StageClock,
    nearest_rank,
)
