"""Unified trace timeline (ISSUE 18): a bounded trace-event ring exported as
Chrome trace-event JSON, loadable in Perfetto / chrome://tracing.

The pipeline already times everything — flight-recorder stage clocks, sampled
podtrace spans, watch-propagation stamps, reconcile recorder, resource
sampler, rebalancer/gang/breaker stats — but each source renders its own
table. This module is the join: existing per-BATCH / per-window / per-cycle
instrumentation forwards ONE extra tap into a shared ring, and export()
emits the standard trace-event JSON (name/ph/ts/pid/tid) so a capture window
opens as a single causal timeline. Partition pipelines land on separate
tracks (tid = pipeline label, e.g. ``p0-sched`` / ``p1-sched``), so ≥2-core
overlap is *visible* as overlapping slices — the judge for the ROADMAP
direction-2 multi-process claim.

Discipline (HP001, analysis/rules/hotpath.py — this file is a hot file):

  * taps are per-batch / per-chunk / per-cycle / per-window ONLY, never
    per pod outside a sampled-set check;
  * disabled cost is ONE module-attribute check — hot sites guard with
    ``if tracebuf.ACTIVE is not None:`` exactly like chaos/faultinject.py;
    disabled_check_cost_ns() measures that guard so the bench asserts the
    budget from a measurement, not by differencing noisy runs;
  * armed cost is measured: every tap accumulates perf_counter time into
    self_seconds, the number the TraceTimeline rung holds under 1% of wall
    (with the 2ms absolute floor discipline, tests/test_bench_quick.py).

Event vocabulary (Chrome trace-event format, ts in MICROseconds):

  X  complete slice (dur)      — stage slices, bind chunks, reconcile drains
  B/E duration begin/end       — the enclosing per-batch envelope
  i  instant                   — breaker transitions, FaultInject firings,
                                 gang-preemption attempts, rebalance waves
  C  counter                   — RSS / GC-pause / alloc-blocks tracks
  s/f flow arrows              — evict→replace causal chains, synthesized at
                                 export time from podtrace span links (the
                                 links are sampled-only, so no per-pod tap)
  M  metadata                  — process/thread names for the Perfetto UI

Time domains: ring timestamps are time.perf_counter()-anchored (the
StageClock/Trace domain). Podtrace spans stamp the scheduler clock
(time.monotonic / FakeClock); attach_clock() captures the offset once so
export() can place span-derived flow anchors on the same axis.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

__all__ = [
    "TraceBuffer", "ACTIVE", "LAST", "arm", "disarm", "enabled",
    "current", "status", "disabled_check_cost_ns", "validate_export",
]

DEFAULT_CAPACITY = 65536
_PID = 1  # single-process orchestrator: one trace process, many tracks


class TraceBuffer:
    """Bounded ring of trace events with per-track (tid) bookkeeping.

    All taps are O(events emitted) with one lock acquisition per tap; a full
    ring drops the OLDEST event per append (deque maxlen) and counts the
    drop, so a long capture keeps the most recent window and the drop total
    is observable via /debug/schedstats (`trace_events_dropped_total`)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._tids: Dict[str, int] = {}
        self._last_breaker: Dict[str, str] = {}
        self._t0 = time.perf_counter()
        self._clock_off: Optional[float] = None
        self._flow_seq = 0
        self.events_total = 0
        self.dropped_total = 0
        self.self_seconds = 0.0

    # -- plumbing --------------------------------------------------------------

    def attach_clock(self, clock) -> None:
        """Capture the scheduler-clock → perf_counter offset (once; later
        calls are no-ops) so export() can place podtrace-span anchors on the
        ring's time axis. Cheap: two clock reads."""
        if self._clock_off is None and clock is not None:
            try:
                self._clock_off = time.perf_counter() - clock.now()
            except Exception:
                self._clock_off = None

    def _ts(self, t_perf: float) -> float:
        return (t_perf - self._t0) * 1e6  # µs

    def _tid_locked(self, track: str) -> int:
        tid = self._tids.get(track)
        if tid is None:
            tid = self._tids[track] = len(self._tids) + 1
        return tid

    def _push_locked(self, ev: Dict) -> None:
        if len(self._ring) == self.capacity:
            self.dropped_total += 1
        self._ring.append(ev)
        self.events_total += 1

    # -- taps (one call per batch / chunk / cycle / window) --------------------

    def note_batch(self, track: str, *, t_end: float,
                   stages: Dict[str, float], pods: int, scheduled: int,
                   outcome: str, solver: str,
                   breaker: Optional[str] = None) -> None:
        """One schedule_batch envelope: a B/E pair spanning the batch's
        serial stage time, with each stage as a back-to-back X slice inside
        it (StageClock insertion order = pipeline order). Breaker state is
        diffed against the track's last-seen state; a transition lands as an
        instant event. `t_end` is the perf_counter stamp at the tap site;
        stage values are SECONDS."""
        t0 = time.perf_counter()
        total = 0.0
        for sec in stages.values():
            total += sec
        begin = t_end - total
        state = breaker or "closed"
        with self._lock:
            tid = self._tid_locked(track)
            self._push_locked({
                "name": "batch", "cat": "sched", "ph": "B",
                "ts": self._ts(begin), "pid": _PID, "tid": tid,
                "args": {"pods": pods, "scheduled": scheduled,
                         "outcome": outcome, "solver": solver}})
            at = begin
            for name, sec in stages.items():
                dur = sec * 1e6
                if dur <= 0.0:
                    continue
                self._push_locked({
                    "name": name, "cat": "stage", "ph": "X",
                    "ts": self._ts(at), "dur": round(dur, 3),
                    "pid": _PID, "tid": tid})
                at += sec
            self._push_locked({
                "name": "batch", "cat": "sched", "ph": "E",
                "ts": self._ts(t_end), "pid": _PID, "tid": tid})
            prev = self._last_breaker.get(track, "closed")
            if state != prev:
                self._last_breaker[track] = state
                self._push_locked({
                    "name": "breaker:%s->%s" % (prev, state),
                    "cat": "breaker", "ph": "i", "s": "p",
                    "ts": self._ts(t_end), "pid": _PID, "tid": tid})
        self.self_seconds += time.perf_counter() - t0

    def note_span(self, track: str, name: str, t_begin: float, t_end: float,
                  cat: str = "span", args: Optional[Dict] = None) -> None:
        """One complete slice (X): bind-worker chunk, rebalance cycle,
        reconcile drain, watch settlement, a slow-Trace step. Timestamps are
        perf_counter values."""
        t0 = time.perf_counter()
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": self._ts(t_begin),
              "dur": round(max(t_end - t_begin, 0.0) * 1e6, 3),
              "pid": _PID}
        if args:
            ev["args"] = args
        with self._lock:
            ev["tid"] = self._tid_locked(track)
            self._push_locked(ev)
        self.self_seconds += time.perf_counter() - t0

    def instant(self, track: str, name: str, cat: str = "event",
                t: Optional[float] = None, args: Optional[Dict] = None,
                scope: str = "t") -> None:
        """One instant event (i): FaultInject firing, gang-preemption
        attempt, rebalance wave boundary."""
        t0 = time.perf_counter()
        ev = {"name": name, "cat": cat, "ph": "i", "s": scope,
              "ts": self._ts(t if t is not None else t0), "pid": _PID}
        if args:
            ev["args"] = args
        with self._lock:
            ev["tid"] = self._tid_locked(track)
            self._push_locked(ev)
        self.self_seconds += time.perf_counter() - t0

    def counter(self, track: str, name: str, values: Dict[str, float],
                t: Optional[float] = None) -> None:
        """One counter sample (C): RSS, GC pause, alloc blocks, per-window
        queue depth. `values` maps series name -> value (one C event renders
        them stacked in Perfetto)."""
        t0 = time.perf_counter()
        ev = {"name": name, "cat": "counter", "ph": "C",
              "ts": self._ts(t if t is not None else t0), "pid": _PID,
              "args": dict(values)}
        with self._lock:
            ev["tid"] = self._tid_locked(track)
            self._push_locked(ev)
        self.self_seconds += time.perf_counter() - t0

    # -- export ----------------------------------------------------------------

    def _span_anchor_us(self, span: Dict, stage_ms: Optional[float]) -> \
            Optional[float]:
        """µs position of a span's stage offset on the ring's axis, or None
        when the clock offset or span anchor is unknown."""
        t0 = span.get("t0")
        if t0 is None or self._clock_off is None or stage_ms is None:
            return None
        return self._ts(t0 + self._clock_off + stage_ms / 1000.0)

    def _flow_events(self, spans: List[Dict]) -> List[Dict]:
        """Synthesize evict→replace flow arrows (s/f pairs anchored to small
        X slices on a `lifecycle` track) from podtrace span links. Runs at
        EXPORT time over the sampled span set only — never on a hot path."""
        out: List[Dict] = []
        by_pod = {sp.get("pod"): sp for sp in spans}
        with self._lock:
            tid = self._tid_locked("lifecycle")
        for sp in spans:
            evicted_key = sp.get("replaces")
            if not evicted_key:
                continue
            src = by_pod.get(evicted_key)
            # source anchor: the evicted pod's last stamp (its death);
            # fall back to the replacement's own enqueue minus a tick so a
            # ring-evicted source span still draws an arrow
            src_us = None
            if src is not None:
                stamps = src.get("stamps_ms") or {}
                last_ms = max(stamps.values()) if stamps else 0.0
                src_us = self._span_anchor_us(src, last_ms)
            dst_us = self._span_anchor_us(sp, 0.0)
            if dst_us is None:
                continue
            if src_us is None or src_us >= dst_us:
                src_us = dst_us - 50.0
            self._flow_seq += 1
            fid = self._flow_seq
            dur = max((sp.get("submit_to_bound_ms") or 0.05) * 1000.0, 50.0)
            out.append({"name": "evicted:%s" % evicted_key,
                        "cat": "lifecycle", "ph": "X", "ts": src_us,
                        "dur": 50.0, "pid": _PID, "tid": tid})
            out.append({"name": "replace", "cat": "lifecycle", "ph": "s",
                        "id": fid, "ts": src_us, "pid": _PID, "tid": tid})
            out.append({"name": "replaced-by:%s" % sp.get("pod"),
                        "cat": "lifecycle", "ph": "X", "ts": dst_us,
                        "dur": round(dur, 3), "pid": _PID, "tid": tid,
                        "args": {"replaces": evicted_key}})
            out.append({"name": "replace", "cat": "lifecycle", "ph": "f",
                        "bp": "e", "id": fid, "ts": dst_us, "pid": _PID,
                        "tid": tid})
        return out

    def export(self, spans: Optional[List[Dict]] = None) -> Dict:
        """Chrome trace-event JSON: {"traceEvents": [...]} — metadata first,
        then every ring event plus span-derived flow arrows, sorted by ts.
        Load the serialized form in https://ui.perfetto.dev or
        chrome://tracing."""
        with self._lock:
            body = list(self._ring)
            tracks = dict(self._tids)
        if spans:
            body.extend(self._flow_events(spans))
        body.sort(key=lambda ev: (ev["ts"], ev.get("tid", 0)))
        meta: List[Dict] = [{
            "name": "process_name", "ph": "M", "ts": 0.0, "pid": _PID,
            "tid": 0, "args": {"name": "tpu-sched"}}]
        for track, tid in sorted(tracks.items(), key=lambda kv: kv[1]):
            meta.append({"name": "thread_name", "ph": "M", "ts": 0.0,
                         "pid": _PID, "tid": tid, "args": {"name": track}})
            meta.append({"name": "thread_sort_index", "ph": "M", "ts": 0.0,
                         "pid": _PID, "tid": tid,
                         "args": {"sort_index": tid}})
        return {"traceEvents": meta + body, "displayTimeUnit": "ms"}

    def status(self) -> Dict:
        with self._lock:
            return {
                "armed": ACTIVE is self,
                "capacity": self.capacity,
                "trace_events_total": self.events_total,
                "trace_events_dropped_total": self.dropped_total,
                "tracks": len(self._tids),
                "self_seconds": round(self.self_seconds, 6),
            }


# THE hot-path flag: None when disabled. Every instrumented site guards with
# `if tracebuf.ACTIVE is not None:` — one attribute load, no call (the
# chaos/faultinject.py pattern; measured by disabled_check_cost_ns).
ACTIVE: Optional[TraceBuffer] = None
# The last disarmed buffer: /debug/trace and `ktl sched trace --export`
# keep serving a finished capture window after disarm().
LAST: Optional[TraceBuffer] = None


def arm(capacity: int = DEFAULT_CAPACITY) -> TraceBuffer:
    """Install a fresh trace buffer (replacing any armed one), return it."""
    global ACTIVE
    ACTIVE = TraceBuffer(capacity=capacity)
    return ACTIVE


def disarm() -> Optional[TraceBuffer]:
    """Stop collection; the buffer stays readable as tracebuf.LAST."""
    global ACTIVE, LAST
    buf, ACTIVE = ACTIVE, None
    if buf is not None:
        LAST = buf
    return buf


def enabled() -> bool:
    return ACTIVE is not None


def current() -> Optional[TraceBuffer]:
    """The armed buffer, else the last disarmed one (read surfaces)."""
    return ACTIVE if ACTIVE is not None else LAST


def status() -> Dict:
    """Arm/drop counters for schedtrace_snapshot / /debug/schedstats —
    a full ring is observable without exporting anything."""
    buf = current()
    if buf is None:
        return {"armed": False, "trace_events_total": 0,
                "trace_events_dropped_total": 0}
    return buf.status()


def disabled_check_cost_ns(n: int = 50_000, passes: int = 5) -> float:
    """Measured per-check cost of the disabled-tracer guard (the exact
    expression hot paths use), in nanoseconds — the number the TraceTimeline
    rung publishes so the <1% overhead budget is asserted from a measurement
    instead of differencing two noisy runs. Best-of-`passes`: the minimum
    filters harness co-scheduling spikes on a contended rig."""
    best = float("inf")
    hits = 0
    for _ in range(passes):
        t0 = time.perf_counter()
        for _ in range(n):
            if ACTIVE is not None:  # the hot-path guard, verbatim
                hits += 1
        best = min(best, time.perf_counter() - t0)
    assert hits == 0 or ACTIVE is not None
    return best / n * 1e9


# -- export validation (shared by tests and the bench rung) ---------------------

def validate_export(doc: Dict) -> Dict:
    """Structural check of a Chrome trace-event export: required keys on
    every event, B/E balanced per (pid, tid) with stack discipline,
    non-decreasing ts per tid, matched s/f flow pairs. Returns
    {valid, errors, events, tracks, flow_pairs, counters, instants}."""
    errors: List[str] = []
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return {"valid": False, "errors": ["traceEvents missing"],
                "events": 0, "tracks": 0, "flow_pairs": 0,
                "counters": 0, "instants": 0}
    depth: Dict[Tuple[int, int], int] = {}
    last_ts: Dict[int, float] = {}
    flows_s: Dict[object, int] = {}
    flows_f: Dict[object, int] = {}
    track_names = set()
    counters = instants = 0
    for ev in evs:
        for field in ("name", "ph", "ts", "pid", "tid"):
            if field not in ev:
                errors.append("missing %s: %r" % (field, ev))
                break
        else:
            ph = ev["ph"]
            if ph == "M":
                if ev["name"] == "thread_name":
                    track_names.add(ev.get("args", {}).get("name"))
                continue
            tid = ev["tid"]
            prev = last_ts.get(tid)
            if prev is not None and ev["ts"] < prev - 1e-6:
                errors.append("ts regressed on tid %s: %.3f < %.3f"
                              % (tid, ev["ts"], prev))
            last_ts[tid] = ev["ts"]
            if ph == "B":
                depth[(ev["pid"], tid)] = depth.get((ev["pid"], tid), 0) + 1
            elif ph == "E":
                d = depth.get((ev["pid"], tid), 0) - 1
                if d < 0:
                    errors.append("E without B on tid %s at ts %.3f"
                                  % (tid, ev["ts"]))
                    d = 0
                depth[(ev["pid"], tid)] = d
            elif ph == "X":
                if "dur" not in ev:
                    errors.append("X without dur: %r" % ev.get("name"))
            elif ph == "s":
                flows_s[ev.get("id")] = flows_s.get(ev.get("id"), 0) + 1
            elif ph == "f":
                flows_f[ev.get("id")] = flows_f.get(ev.get("id"), 0) + 1
            elif ph == "i":
                instants += 1
            elif ph == "C":
                counters += 1
    for key, d in depth.items():
        if d != 0:
            errors.append("unbalanced B/E on %s: depth %d" % (key, d))
    flow_pairs = 0
    for fid, n_s in flows_s.items():
        n_f = flows_f.get(fid, 0)
        if n_f != n_s:
            errors.append("flow id %r: %d starts, %d finishes"
                          % (fid, n_s, n_f))
        flow_pairs += min(n_s, n_f)
    for fid in flows_f:
        if fid not in flows_s:
            errors.append("flow id %r: finish without start" % fid)
    return {
        "valid": not errors,
        "errors": errors[:20],
        "events": len(evs),
        "tracks": len(track_names),
        "flow_pairs": flow_pairs,
        "counters": counters,
        "instants": instants,
    }
