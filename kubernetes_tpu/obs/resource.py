"""Resource & GIL sampler — RSS, GC, live-object, and per-thread CPU time
for the long-running control plane (ISSUE 13).

A background thread samples at a fixed interval (default 1s):

  rss_mb        resident set from /proc/self/statm (one read + split);
  alloc_blocks  sys.getallocatedblocks() — the deterministic live-object
                signal the leak gates fit a slope over (RSS is noisy: the
                allocator keeps arenas; leaked OBJECTS always grow this);
  gc            gen counts (gc.get_count), collections/collected since
                start, and measured pause seconds via gc.callbacks
                (start/stop pairs around each collection);
  threads       per-REGISTERED-thread CPU seconds — the scheduling, bind,
                and partition drive threads register themselves so the
                partition A/B can be JUDGED when the rig regrows cores:
                overlap_cpu_s below measures CPU beyond wall, which only
                exists when one thread's GIL-releasing work (XLA solve,
                CDLL kernels) truly overlaps another's GIL-held host work.

Per-thread clock (ISSUE 13 satellite — the ROADMAP carryover says
time.thread_time() has ticked at 10ms in some containers, and it can only
read the CALLING thread anyway): where the platform allows it we read other
threads' CPU clocks through the Linux per-thread clockid encoding
(CPUCLOCK_SCHED | CPUCLOCK_PERTHREAD for a kernel tid: ``(~tid << 3) | 6``)
via time.clock_gettime; the fallback is /proc/self/task/<tid>/schedstat
(nanosecond-granular on CFS). Whichever source wins, the sampler MEASURES
its effective tick at startup and publishes it as an honesty flag
(clock_source / clock_resolution_s) right next to the attribution columns —
a 10ms-tick container cannot quietly publish microsecond claims.

Everything is bounded (sample ring, registered-thread map) and the
sampler's own cost is measured (self_seconds + overhead_frac vs elapsed),
so the <2% instrumentation budget covers it from a measurement.
"""

from __future__ import annotations

import gc
import itertools
import os
import sys
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional

from . import tracebuf as _tracebuf

DEFAULT_INTERVAL_S = 1.0
DEFAULT_CAPACITY = 600  # 10 min of 1s samples

_PAGE_MB = os.sysconf("SC_PAGE_SIZE") / (1024.0 * 1024.0) \
    if hasattr(os, "sysconf") else 4096 / (1024.0 * 1024.0)


def _thread_clock_id(native_id: int) -> int:
    """Linux kernel clockid encoding for another thread's CPU clock:
    CPUCLOCK_PERTHREAD | CPUCLOCK_SCHED over the kernel tid. An ABI detail,
    so probe_thread_clock() validates it once before the sampler trusts it."""
    return (~native_id << 3) | 6


def read_thread_cpu_s(native_id: int, source: str) -> Optional[float]:
    """One thread's cumulative CPU seconds via the probed source; None when
    the thread is gone or the source fails (a dead tid is normal churn)."""
    try:
        if source == "clockid":
            return time.clock_gettime(_thread_clock_id(native_id))
        if source == "schedstat":
            with open(f"/proc/self/task/{native_id}/schedstat") as f:
                return int(f.read().split()[0]) / 1e9
    except (OSError, ValueError, IndexError):
        return None
    return None


def probe_thread_clock() -> Dict:
    """Pick the per-thread CPU clock source and MEASURE its effective tick
    (the honesty flag): spin-read the chosen clock on this thread briefly
    and report the smallest observed positive increment. clock_getres lies
    on some containers (reports 1ns for a 10ms-tick clock), so the
    published resolution is measured, never queried."""
    tid = threading.get_native_id()
    source = None
    for cand in ("clockid", "schedstat"):
        if read_thread_cpu_s(tid, cand) is not None:
            source = cand
            break
    if source is None:
        return {"source": "unavailable", "resolution_s": None}
    seen = set()
    deadline = time.perf_counter() + 0.02
    while time.perf_counter() < deadline and len(seen) < 64:
        v = read_thread_cpu_s(tid, source)
        if v is not None:
            seen.add(v)
    vals = sorted(seen)
    deltas = [b - a for a, b in zip(vals, vals[1:]) if b > a]
    return {"source": source,
            "resolution_s": min(deltas) if deltas else None}


# weak registry of live samplers so /metrics GaugeFuncs can read the latest
# sample without per-instance wiring (the watch-source registry pattern)
_samplers_lock = threading.Lock()
_samplers: List = []
_sampler_seq = itertools.count()


def _register_sampler(sampler: "ResourceSampler") -> None:
    with _samplers_lock:
        _samplers[:] = [r for r in _samplers if r() is not None]
        _samplers.append(weakref.ref(sampler))


def live_samplers() -> List["ResourceSampler"]:
    with _samplers_lock:
        refs = list(_samplers)
    return [s for s in (r() for r in refs) if s is not None]


class ResourceSampler:
    """Bounded-ring resource/GIL sampler (see module docstring).

    Threads register by threading.Thread (native id resolves lazily — a
    not-yet-started worker registers fine) or by explicit native id. The
    sampling thread is daemon + stop()-able; sample_once() works without
    the thread for tests and one-shot reads."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 capacity: int = DEFAULT_CAPACITY, clock_probe: bool = True):
        self.interval_s = float(interval_s)
        self.capacity = capacity
        # stable identity for the /metrics series: several samplers can be
        # alive at once (tests, one per coordinator) and unlabeled
        # duplicate samples would corrupt the exposition
        self.id = f"sampler-{next(_sampler_seq)}"
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        # name -> Thread (weakly held) or resolved native id
        self._threads: Dict[str, object] = {}
        self._cpu0: Dict[str, float] = {}  # first-seen cumulative, per name
        self._cpu_last: Dict[str, float] = {}
        # seconds accumulated under this name by PREVIOUS thread
        # registrations (a restarted bind worker / per-round drive thread
        # keeps one monotonic column instead of resetting it)
        self._cpu_carry: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.clock = (probe_thread_clock() if clock_probe
                      else {"source": "unavailable", "resolution_s": None})
        # gc pause accounting via gc.callbacks (registered on start())
        self._gc_cb_installed = False
        self._gc_t0 = 0.0
        self._gc_pause_s = 0.0
        self._gc_pause_max_s = 0.0
        self._gc_collections = 0
        self.samples_taken = 0
        self.self_seconds = 0.0
        self._t_start = time.perf_counter()
        self._rss0_mb = self._read_rss_mb()
        self._alloc0 = sys.getallocatedblocks()
        _register_sampler(self)

    # -- thread registration ---------------------------------------------------

    def register_thread(self, name: str, thread=None,
                        native_id: Optional[int] = None) -> None:
        """Track one thread's CPU time under `name`. Re-registering a name
        replaces the target thread but KEEPS the column monotonic: the old
        thread's accumulated seconds carry over (restarted bind workers and
        per-round partition drive threads are one logical column)."""
        with self._lock:
            if name in self._cpu_last:
                self._cpu_carry[name] = (
                    self._cpu_carry.get(name, 0.0)
                    + self._cpu_last[name]
                    - self._cpu0.get(name, self._cpu_last[name]))
            if native_id is not None:
                self._threads[name] = native_id
            elif thread is not None:
                self._threads[name] = weakref.ref(thread)
            else:
                self._threads[name] = threading.get_native_id()
            self._cpu0.pop(name, None)
            self._cpu_last.pop(name, None)

    def _resolve_tid(self, target) -> Optional[int]:
        if isinstance(target, int):
            return target
        t = target() if isinstance(target, weakref.ref) else target
        if t is None:
            return None
        return getattr(t, "native_id", None)

    # -- gc pause hooks --------------------------------------------------------

    def _gc_callback(self, phase: str, info: Dict) -> None:
        if phase == "start":
            self._gc_t0 = time.perf_counter()
        elif phase == "stop" and self._gc_t0:
            dt = time.perf_counter() - self._gc_t0
            self._gc_pause_s += dt
            if dt > self._gc_pause_max_s:
                self._gc_pause_max_s = dt
            self._gc_collections += 1

    def _install_gc_cb(self) -> None:
        if not self._gc_cb_installed:
            gc.callbacks.append(self._gc_callback)
            self._gc_cb_installed = True

    def _remove_gc_cb(self) -> None:
        if self._gc_cb_installed:
            try:
                gc.callbacks.remove(self._gc_callback)
            except ValueError:
                pass
            self._gc_cb_installed = False

    # -- sampling --------------------------------------------------------------

    def _read_rss_mb(self) -> float:
        try:
            with open("/proc/self/statm") as f:
                return int(f.read().split()[1]) * _PAGE_MB
        except (OSError, ValueError, IndexError):
            return 0.0

    def rss_mb(self) -> float:
        """One fresh RSS read (no ring append) — the bench's warmup loop
        polls this until the allocator plateaus before the measured soak."""
        return self._read_rss_mb()

    def sample_once(self) -> Dict:
        """Take one sample, append it to the ring, return it. The per-call
        cost is measured into self_seconds (the budget feed)."""
        t0 = time.perf_counter()
        source = self.clock["source"]
        with self._lock:
            threads: Dict[str, Dict] = {}
            for name, target in self._threads.items():
                tid = self._resolve_tid(target)
                cpu = (read_thread_cpu_s(tid, source)
                       if tid is not None else None)
                if cpu is None:
                    continue
                base = self._cpu0.setdefault(name, cpu)
                prev = self._cpu_last.get(name, cpu)
                self._cpu_last[name] = cpu
                threads[name] = {
                    "cpu_s": round(self._cpu_carry.get(name, 0.0)
                                   + cpu - base, 6),
                    "cpu_delta_s": round(cpu - prev, 6),
                }
            counts = gc.get_count()
            rec = {
                "ts": t0,
                "rss_mb": round(self._read_rss_mb(), 3),
                "alloc_blocks": sys.getallocatedblocks(),
                "gc": {
                    "gen_counts": list(counts),
                    "collections": self._gc_collections,
                    "pause_s": round(self._gc_pause_s, 6),
                    "pause_max_s": round(self._gc_pause_max_s, 6),
                },
                "process_cpu_s": round(time.process_time(), 6),
                "threads": threads,
            }
            self._ring.append(rec)
            self.samples_taken += 1
        # trace timeline (ISSUE 18): one counter event per sample TICK —
        # the RSS / GC-pause / alloc tracks under the scheduling slices
        if _tracebuf.ACTIVE is not None:
            _tracebuf.ACTIVE.counter(
                "resource", "memory", {
                    "rss_mb": rec["rss_mb"],
                    "alloc_blocks": rec["alloc_blocks"]}, t=t0)
            _tracebuf.ACTIVE.counter(
                "resource", "gc", {
                    "pause_ms": rec["gc"]["pause_s"] * 1000.0,
                    "collections": rec["gc"]["collections"]}, t=t0)
        self.self_seconds += time.perf_counter() - t0
        return rec

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                # a torn /proc read or dying thread must not kill the
                # sampler; the next tick tries again
                continue

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._install_gc_cb()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="resource-sampler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None
        self._remove_gc_cb()

    def reset(self) -> None:
        """Drop history and re-baseline (the warmup-exclusion idiom): the
        soak rung's measured window must not inherit warmup RSS growth."""
        with self._lock:
            self._ring.clear()
            self._cpu0.clear()
            self._cpu_last.clear()
            self._cpu_carry.clear()
            self._gc_pause_s = 0.0
            self._gc_pause_max_s = 0.0
            self._gc_collections = 0
            self.samples_taken = 0
            self.self_seconds = 0.0
            self._t_start = time.perf_counter()
            self._rss0_mb = self._read_rss_mb()
            self._alloc0 = sys.getallocatedblocks()

    # -- read side -------------------------------------------------------------

    def samples(self, last: Optional[int] = None) -> List[Dict]:
        with self._lock:
            out = list(self._ring)
        return out[-last:] if last else out

    def latest(self) -> Optional[Dict]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def summary(self) -> Dict:
        """The columns sched_stats / the soak rung / window probes publish:
        latest absolutes, growth since baseline, per-thread CPU totals, the
        overlap measurement, and the honesty flags (clock source/resolution,
        measured sampler overhead)."""
        with self._lock:
            ring = list(self._ring)
            threads = {name: round(self._cpu_carry.get(name, 0.0)
                                   + self._cpu_last[name]
                                   - self._cpu0.get(name,
                                                    self._cpu_last[name]), 6)
                       for name in self._cpu_last}
            elapsed = time.perf_counter() - self._t_start
            gc_col = {
                "collections": self._gc_collections,
                "pause_s": round(self._gc_pause_s, 6),
                "pause_max_s": round(self._gc_pause_max_s, 6),
            }
        last = ring[-1] if ring else None
        # overlap: CPU beyond wall inside one sampling interval can only
        # come from threads truly running in parallel (GIL released) — the
        # direction-3 A/B's "measured, not inferred from bind_wait" number
        overlap = 0.0
        for a, b in zip(ring, ring[1:]):
            wall = b["ts"] - a["ts"]
            cpu = sum(t["cpu_delta_s"] for t in b["threads"].values())
            if cpu > wall > 0:
                overlap += cpu - wall
        return {
            "enabled": self._thread is not None or bool(ring),
            "interval_s": self.interval_s,
            "samples": self.samples_taken,
            "rss_mb": last["rss_mb"] if last else None,
            "rss_growth_mb": (round(last["rss_mb"] - self._rss0_mb, 3)
                              if last else None),
            "alloc_blocks": last["alloc_blocks"] if last else None,
            "alloc_growth_blocks": (last["alloc_blocks"] - self._alloc0
                                    if last else None),
            "gc": gc_col,
            "thread_cpu_s": threads,
            "overlap_cpu_s": round(overlap, 6),
            "clock_source": self.clock["source"],
            "clock_resolution_s": self.clock["resolution_s"],
            "self_seconds": round(self.self_seconds, 6),
            "overhead_frac": (round(self.self_seconds / elapsed, 6)
                              if elapsed > 0 else 0.0),
        }
