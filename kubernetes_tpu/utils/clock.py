"""Real and fake clocks (reference: k8s.io/utils/clock — the fake clock is injected
into the scheduler the same way scheduler.WithClock does, pkg/scheduler/scheduler.go:233)."""

from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    """Manually-stepped clock for deterministic tests."""

    def __init__(self, start: float = 0.0):
        self._now = start
        self._cond = threading.Condition()

    def now(self) -> float:
        with self._cond:
            return self._now

    def step(self, seconds: float) -> None:
        with self._cond:
            self._now += seconds
            self._cond.notify_all()

    def sleep(self, seconds: float) -> None:
        with self._cond:
            deadline = self._now + seconds
            while self._now < deadline:
                self._cond.wait(timeout=1.0)
