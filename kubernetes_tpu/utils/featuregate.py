"""Feature gates: named on/off switches with Alpha/Beta/GA lifecycle.

reference: staging/src/k8s.io/component-base/featuregate/feature_gate.go and
the gate catalog in pkg/features/kube_features.go (140 gates). The subset
registered here covers the behaviors this build implements; components read
gates via `FeatureGates.enabled(name)` and operators set them with the same
`--feature-gates=Name=true,Other=false` syntax.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Iterable, Mapping, Optional

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"
DEPRECATED = "DEPRECATED"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    stage: str = ALPHA
    lock_to_default: bool = False  # GA-locked gates cannot be turned off


class FeatureGates:
    """Thread-safe gate registry (featuregate.go featureGate)."""

    def __init__(self, specs: Optional[Mapping[str, FeatureSpec]] = None):
        self._lock = threading.Lock()
        self._specs: Dict[str, FeatureSpec] = dict(specs or {})
        self._overrides: Dict[str, bool] = {}

    def add(self, name: str, spec: FeatureSpec) -> None:
        with self._lock:
            if name in self._specs:
                raise ValueError(f"feature gate {name!r} already registered")
            self._specs[name] = spec

    def known(self) -> Iterable[str]:
        with self._lock:
            return sorted(self._specs)

    def enabled(self, name: str) -> bool:
        with self._lock:
            spec = self._specs.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name!r}")
            return self._overrides.get(name, spec.default)

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            spec = self._specs.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name!r}")
            if spec.lock_to_default and value != spec.default:
                raise ValueError(
                    f"cannot set feature gate {name} to {value}: locked to "
                    f"{spec.default}")
            self._overrides[name] = value

    def set_from_map(self, overrides: Mapping[str, bool]) -> None:
        for name, value in overrides.items():
            self.set(name, value)

    def parse(self, flag_value: str) -> None:
        """--feature-gates=A=true,B=false (featuregate.go Set)."""
        if not flag_value:
            return
        for pair in flag_value.split(","):
            if not pair.strip():
                continue
            name, sep, raw = pair.partition("=")
            if not sep:
                raise ValueError(f"missing '=' in feature gate spec {pair!r}")
            raw = raw.strip().lower()
            if raw not in ("true", "false"):
                raise ValueError(f"invalid bool {raw!r} for gate {name!r}")
            self.set(name.strip(), raw == "true")

    def snapshot(self) -> Dict[str, bool]:
        with self._lock:
            return {name: self._overrides.get(name, spec.default)
                    for name, spec in sorted(self._specs.items())}


# The build's gate catalog (scheduler gates: plugins/registry.go:45-60).
DEFAULT_FEATURE_GATES = {
    "SchedulerQueueingHints": FeatureSpec(True, BETA),
    "SchedulerAsyncPreemption": FeatureSpec(True, BETA),
    "DynamicResourceAllocation": FeatureSpec(False, BETA),
    "VolumeCapacityPriority": FeatureSpec(False, ALPHA),
    "PodSchedulingReadiness": FeatureSpec(True, GA, lock_to_default=True),
    "NodeInclusionPolicyInPodTopologySpread": FeatureSpec(True, BETA),
    "MatchLabelKeysInPodTopologySpread": FeatureSpec(True, BETA),
    # TPU-build-specific gates (the batch path is this build's headline)
    "TPUBatchScheduling": FeatureSpec(True, BETA),
    "TPUTransportSolvers": FeatureSpec(True, ALPHA),
}


def default_feature_gates() -> FeatureGates:
    return FeatureGates(DEFAULT_FEATURE_GATES)


# process-wide default instance (pkg/features DefaultFeatureGate)
feature_gates = default_feature_gates()
