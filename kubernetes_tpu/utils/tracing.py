"""Lightweight spans with threshold logging + structured JSON logging.

reference: k8s.io/utils/trace (the scheduler's utiltrace steps with a 100ms
log threshold — schedule_one.go:411) and component-base/logs (klog text/JSON
backends). OTel export is out of scope; the span model matches utiltrace so
call sites read the same.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class Step:
    msg: str
    at: float
    fields: Dict[str, Any] = field(default_factory=dict)


class Trace:
    """utiltrace.Trace: named steps; logged only if total exceeds threshold."""

    def __init__(self, name: str, logger: Optional["StructuredLogger"] = None,
                 clock=None, **fields):
        self.name = name
        self.fields = fields
        self.clock = clock
        self.logger = logger or default_logger
        self.start = self._now()
        self.steps: List[Step] = []
        self.end: Optional[float] = None

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else time.perf_counter()

    def step(self, msg: str, **fields) -> None:
        self.steps.append(Step(msg, self._now(), fields))

    def duration(self) -> float:
        return (self.end if self.end is not None else self._now()) - self.start

    def log_if_long(self, threshold: float) -> bool:
        """Log the whole trace when total duration exceeds threshold
        (utiltrace LogIfLong). Returns whether it logged."""
        self.end = self._now()
        total = self.end - self.start
        if total < threshold:
            return False
        prev = self.start
        steps = []
        for s in self.steps:
            steps.append({"msg": s.msg, "ms": round((s.at - prev) * 1000, 2),
                          **s.fields})
            prev = s.at
        self.logger.info(f"Trace {self.name!r} exceeded threshold",
                         total_ms=round(total * 1000, 2),
                         threshold_ms=round(threshold * 1000, 2),
                         steps=steps, **self.fields)
        # unified trace timeline (ISSUE 18): a slow trace's steps also land
        # on the armed trace buffer, so serial-path spikes show on the same
        # Perfetto timeline as the batch slices. Slow path only (we already
        # crossed the logging threshold), lazy import (no obs dependency on
        # the fast path), and perf_counter-domain traces only — a custom
        # clock has no place on the buffer's axis.
        from ..obs import tracebuf

        if tracebuf.ACTIVE is not None and self.clock is None:
            at = self.start
            for s in self.steps:
                tracebuf.ACTIVE.note_span(
                    "slowtrace", f"{self.name}:{s.msg}", at, s.at,
                    cat="slowtrace", args=dict(s.fields) or None)
                at = s.at
        return True

    def __enter__(self) -> "Trace":
        return self

    def __exit__(self, *exc) -> None:
        self.end = self._now()


class StructuredLogger:
    """klog-style leveled logger with a JSON backend (component-base/logs
    json format). Writes one JSON object per line."""

    LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}

    def __init__(self, component: str, stream=None, level: str = "info"):
        self.component = component
        self.stream = stream if stream is not None else sys.stderr
        self.level = self.LEVELS[level]
        self._lock = threading.Lock()

    def _emit(self, severity: str, msg: str, kv: Dict[str, Any]) -> None:
        if self.LEVELS[severity] < self.level:
            return
        record = {"ts": time.time(), "v": severity, "component": self.component,
                  "msg": msg, **kv}
        line = json.dumps(record, default=str)
        with self._lock:
            self.stream.write(line + "\n")

    def debug(self, msg: str, **kv) -> None:
        self._emit("debug", msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._emit("info", msg, kv)

    def warning(self, msg: str, **kv) -> None:
        self._emit("warning", msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._emit("error", msg, kv)


default_logger = StructuredLogger("kubernetes-tpu")


# -- configz (component-base/configz) ----------------------------------------

_configz_lock = threading.Lock()
_configz: Dict[str, Any] = {}


def register_config(name: str, config: Any) -> None:
    """Expose a component's live config at /configz (configz.InstallHandler)."""
    with _configz_lock:
        _configz[name] = config


def configz_snapshot() -> Dict[str, Any]:
    with _configz_lock:
        return dict(_configz)
