from .clock import Clock, FakeClock  # noqa: F401
from .leaderelection import LeaderElector  # noqa: F401
from .leakcheck import assert_no_thread_leaks  # noqa: F401
