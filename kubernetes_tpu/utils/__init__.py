from .clock import Clock, FakeClock  # noqa: F401
