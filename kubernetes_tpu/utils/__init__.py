from .clock import Clock, FakeClock  # noqa: F401
from .leaderelection import LeaderElector  # noqa: F401
