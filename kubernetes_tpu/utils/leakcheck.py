"""Thread-leak detection — the goleak analog for a threaded runtime.

reference: test/integration/framework/goleak.go (go.uber.org/goleak) — every
integration test asserts the goroutines it started are gone when it ends.
Here components run daemon threads (controllers, schedulers, kubelets, watch
pumps); a stop() that forgets to join leaks a thread that keeps mutating the
store under later tests. Wrap a component lifecycle in
`assert_no_thread_leaks()` to pin clean shutdown.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Iterable


@contextmanager
def assert_no_thread_leaks(grace: float = 3.0, allow: Iterable[str] = ()):
    """Fails if threads started inside the block outlive it (after a grace
    period for in-flight shutdowns, goleak's retry loop). `allow` names
    substrings of expected survivors (e.g. process-wide singletons)."""
    before = set(threading.enumerate())
    yield
    deadline = time.time() + grace
    leaked = []
    while True:
        leaked = [
            t for t in threading.enumerate()
            if t not in before and t.is_alive()
            and not any(a in (t.name or "") for a in allow)
        ]
        if not leaked or time.time() > deadline:
            break
        time.sleep(0.05)
    if leaked:
        raise AssertionError(
            "leaked threads: " + ", ".join(sorted(t.name for t in leaked)))
