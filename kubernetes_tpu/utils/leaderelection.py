"""Lease-based leader election (control-plane HA).

reference: staging/src/k8s.io/client-go/tools/leaderelection/leaderelection.go:31-87
— acquire/renew a coordination Lease; the standby takes over when the holder
stops renewing for LeaseDuration (~15s default, scheduler server.go:281).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..api.types import ObjectMeta, new_uid
from ..api.workloads import Lease
from ..store import AlreadyExistsError, APIStore, ConflictError, NotFoundError
from .clock import Clock


class LeaderElector:
    def __init__(self, store: APIStore, lock_name: str, identity: str,
                 lease_duration: float = 15.0, renew_deadline: float = 10.0,
                 retry_period: float = 2.0, namespace: str = "kube-system",
                 clock: Optional[Clock] = None,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None):
        self.store = store
        self.lock_name = lock_name
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.namespace = namespace
        self.clock = clock or Clock()
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.is_leader = False
        self._last_renew = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def _key(self) -> str:
        return f"{self.namespace}/{self.lock_name}"

    def try_acquire_or_renew(self) -> bool:
        """One election round; returns True while holding the lock."""
        now = self.clock.now()
        try:
            lease: Lease = self.store.get("leases", self._key)
        except NotFoundError:
            lease = Lease(
                metadata=ObjectMeta(name=self.lock_name, namespace=self.namespace, uid=new_uid()),
                holder_identity=self.identity,
                lease_duration_seconds=int(self.lease_duration),
                acquire_time=now, renew_time=now,
            )
            try:
                self.store.create("leases", lease)
                self._became(True)
                return True
            except AlreadyExistsError:
                return self.try_acquire_or_renew()

        # empty holder = voluntarily released; never treat it as alive
        holder_alive = bool(lease.holder_identity) and \
            (now - lease.renew_time) < self.lease_duration
        if lease.holder_identity != self.identity and holder_alive:
            self._became(False)
            return False

        class _LostRace(Exception):
            pass

        def mutate(obj: Lease) -> Lease:
            # guaranteed_update re-reads on conflict: liveness MUST be
            # re-evaluated on the fresh object, or two expired-holder observers
            # would both seize the lock (split-brain). client-go re-checks
            # observedRecord on every attempt the same way.
            fresh_alive = bool(obj.holder_identity) and \
                (self.clock.now() - obj.renew_time) < self.lease_duration
            if obj.holder_identity != self.identity and fresh_alive:
                raise _LostRace()
            if obj.holder_identity != self.identity:
                obj.acquire_time = now
            obj.holder_identity = self.identity
            obj.renew_time = now
            return obj

        try:
            self.store.guaranteed_update("leases", self._key, mutate)
            self._last_renew = now
            self._became(True)
            return True
        except _LostRace:
            self._became(False)  # someone else demonstrably holds the lock
            return False
        except (ConflictError, NotFoundError):
            # transient renew failure: a leader keeps leading until the
            # renewDeadline elapses (client-go renew-loop tolerance)
            if self.is_leader and now - self._last_renew <= self.renew_deadline:
                return False
            self._became(False)
            return False

    def _became(self, leader: bool) -> None:
        if leader and not self.is_leader:
            self.is_leader = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leader and self.is_leader:
            self.is_leader = False
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def run(self) -> None:
        """Blocking acquire/renew loop (LeaderElector.Run)."""
        while not self._stop.is_set():
            self.try_acquire_or_renew()
            self._stop.wait(self.retry_period)  # wakes immediately on stop()
        if self.is_leader:
            self.release()

    def start(self) -> "LeaderElector":
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def release(self) -> None:
        """Voluntarily give up the lock (graceful shutdown)."""
        try:
            def mutate(obj: Lease) -> Lease:
                if obj.holder_identity == self.identity:
                    obj.holder_identity = ""
                    obj.renew_time = 0.0
                return obj

            self.store.guaranteed_update("leases", self._key, mutate)
        except NotFoundError:
            pass
        self._became(False)
