"""Minimal 5-field cron schedule parser for the CronJob controller.

reference: the cronjob controller depends on robfig/cron
(pkg/controller/cronjob/utils.go); this covers the standard syntax that
controller accepts: *, */step, lists, ranges, and the @hourly-style macros.
"""

from __future__ import annotations

from datetime import datetime, timedelta, timezone
from typing import Set, Tuple

_MACROS = {
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
    "@monthly": "0 0 1 * *",
    "@weekly": "0 0 * * 0",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@hourly": "0 * * * *",
}

_BOUNDS = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 7))  # dow 7 = Sunday alias


def _parse_field(field: str, lo: int, hi: int) -> Set[int]:
    out: Set[int] = set()
    for part in field.split(","):
        step = 1
        has_step = "/" in part
        if has_step:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step <= 0:
                raise ValueError(f"invalid step {step}")
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = int(a), int(b)
        else:
            start = end = int(part)
            if has_step:
                # robfig/cron: N/step means the range N..hi stepped (for any
                # step value, including 1), not just {N}
                end = hi
        if not (lo <= start <= hi and lo <= end <= hi and start <= end):
            raise ValueError(f"field value out of range: {part!r} not in [{lo},{hi}]")
        out.update(range(start, end + 1, step))
    return out


class CronSchedule:
    def __init__(self, expr: str, tz: str = ""):
        expr = expr.strip()
        expr = _MACROS.get(expr, expr)
        fields = expr.split()
        if len(fields) != 5:
            raise ValueError(f"cron expression needs 5 fields, got {expr!r}")
        self.tz = timezone.utc
        if tz:
            # IANA zone (CronJob spec.timeZone; cronjob_controllerv2.go uses
            # time.LoadLocation) — schedule fields are evaluated in this zone
            from zoneinfo import ZoneInfo

            try:
                self.tz = ZoneInfo(tz)
            except Exception as e:
                raise ValueError(f"unknown timeZone {tz!r}") from e
        self.minutes, self.hours, self.dom, self.months, self.dow = (
            _parse_field(f, lo, hi) for f, (lo, hi) in zip(fields, _BOUNDS))
        if 7 in self.dow:  # 7 is an alias for Sunday (robfig/cron)
            self.dow = (self.dow - {7}) | {0}
        # day-of-month/day-of-week OR semantics when both are restricted
        self._dom_star = fields[2] == "*"
        self._dow_star = fields[4] == "*"

    def _day_matches(self, dt: datetime) -> bool:
        dom_ok = dt.day in self.dom
        dow_ok = dt.weekday() in self._to_cron_dow()
        if self._dom_star and self._dow_star:
            return True
        if self._dom_star:
            return dow_ok
        if self._dow_star:
            return dom_ok
        return dom_ok or dow_ok

    def _to_cron_dow(self) -> Set[int]:
        # cron: 0=Sunday; python weekday(): 0=Monday
        return {(d - 1) % 7 for d in self.dow}

    def matches(self, ts: float) -> bool:
        dt = datetime.fromtimestamp(ts, tz=self.tz)
        return (dt.minute in self.minutes and dt.hour in self.hours
                and dt.month in self.months and self._day_matches(dt))

    def next_after(self, ts: float, horizon_days: int = 366) -> float:
        """First scheduled time strictly after ts (cron.Next).

        The cursor advances in UTC — timedelta arithmetic on a zoned datetime
        silently drops the DST fold and can step BACKWARDS across fall-back
        (violating "strictly after"); only field matching happens in the
        schedule's zone. Spring-forward times that don't exist locally are
        skipped (the wall clock never shows them); during fall-back the
        repeated local hour can fire on both passes.
        """
        cur = datetime.fromtimestamp(ts, tz=timezone.utc)
        cur = cur.replace(second=0, microsecond=0) + timedelta(minutes=1)
        end = cur + timedelta(days=horizon_days)
        while cur < end:
            local = cur.astimezone(self.tz)
            if (local.month not in self.months
                    or not self._day_matches(local)
                    or local.hour not in self.hours):
                # jump to the next LOCAL hour start: offsets are whole
                # minutes, so adding (60 - local.minute) lands on :00
                cur += timedelta(minutes=60 - local.minute)
                continue
            if local.minute in self.minutes:
                return cur.timestamp()
            cur += timedelta(minutes=1)
        raise ValueError("no cron occurrence within horizon")

    def times_between(self, start: float, end: float) -> Tuple[float, ...]:
        """All scheduled times in (start, end] (getRecentUnmetScheduleTimes)."""
        out = []
        t = start
        while True:
            t = self.next_after(t)
            if t > end:
                break
            out.append(t)
            if len(out) > 1000:  # runaway guard (cronjob_controllerv2.go:100s cap)
                break
        return tuple(out)
