"""Object <-> k8s-style camelCase dict serialization for every API type.

The wire format matches kubernetes manifests (reference: the JSON forms of
staging/src/k8s.io/api types), so standard YAML round-trips through the HTTP
server and CLI.
"""

from __future__ import annotations

from dataclasses import asdict
from typing import Any, Dict

from .labels import (
    NodeSelector,
    NodeSelectorTerm,
    PreferredSchedulingTerm,
    Requirement,
    Selector,
)
from .types import (
    Affinity,
    Namespace,
    Node,
    Pod,
    PodAffinityTerm,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from .networking import (
    EndpointSlice,
    Ingress,
    IngressClass,
    NetworkPolicy,
    Service,
)
from .policy import (
    HorizontalPodAutoscaler,
    LimitRange,
    PodDisruptionBudget,
    PriorityClass,
    ResourceQuota,
    ServiceAccount,
)
from .admissionregistration import (
    MutatingWebhookConfiguration,
    ValidatingAdmissionPolicy,
    ValidatingAdmissionPolicyBinding,
    ValidatingWebhookConfiguration,
)
from .apiservice import APIService
from .certificates import CertificateSigningRequest
from .config import ConfigMap, Secret
from .crd import CustomResourceDefinition
from .flowcontrolapi import (
    FlowSchemaConfiguration,
    PriorityLevelConfiguration,
)
from .dra import DeviceClass, ResourceClaim, ResourceClaimTemplate, ResourceSlice
from .events import Event as CoreEvent, PodLog
from .execapi import PodExec, PodPortForward
from .storage import (
    CSINode,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
    VolumeAttachment,
)
from .podgroup import PodGroup
from .workloads import (
    CronJob,
    DaemonSet,
    Deployment,
    Job,
    Lease,
    ReplicaSet,
    StatefulSet,
)

KIND_TO_RESOURCE = {
    "Pod": "pods",
    "Node": "nodes",
    "Namespace": "namespaces",
    "ReplicaSet": "replicasets",
    "Deployment": "deployments",
    "StatefulSet": "statefulsets",
    "DaemonSet": "daemonsets",
    "Job": "jobs",
    "CronJob": "cronjobs",
    "Lease": "leases",
    "PersistentVolume": "persistentvolumes",
    "PersistentVolumeClaim": "persistentvolumeclaims",
    "StorageClass": "storageclasses",
    "CSINode": "csinodes",
    "Service": "services",
    "EndpointSlice": "endpointslices",
    "ResourceQuota": "resourcequotas",
    "LimitRange": "limitranges",
    "HorizontalPodAutoscaler": "horizontalpodautoscalers",
    "PodDisruptionBudget": "poddisruptionbudgets",
    "PriorityClass": "priorityclasses",
    "ServiceAccount": "serviceaccounts",
    "Event": "events",
    "ResourceClaim": "resourceclaims",
    "ResourceSlice": "resourceslices",
    "DeviceClass": "deviceclasses",
    "CustomResourceDefinition": "customresourcedefinitions",
    "CertificateSigningRequest": "certificatesigningrequests",
    "APIService": "apiservices",
    "VolumeAttachment": "volumeattachments",
    "ResourceClaimTemplate": "resourceclaimtemplates",
    "PodLog": "podlogs",
    "PodExec": "podexecs",
    "PodPortForward": "podportforwards",
    "ConfigMap": "configmaps",
    "Secret": "secrets",
    "Ingress": "ingresses",
    "IngressClass": "ingressclasses",
    "NetworkPolicy": "networkpolicies",
    "PriorityLevelConfiguration": "prioritylevelconfigurations",
    "FlowSchema": "flowschemas",
    "ValidatingAdmissionPolicy": "validatingadmissionpolicies",
    "ValidatingAdmissionPolicyBinding": "validatingadmissionpolicybindings",
    "MutatingWebhookConfiguration": "mutatingwebhookconfigurations",
    "ValidatingWebhookConfiguration": "validatingwebhookconfigurations",
    "PodGroup": "podgroups",
}
RESOURCE_TO_TYPE = {
    "pods": Pod,
    "nodes": Node,
    "namespaces": Namespace,
    "replicasets": ReplicaSet,
    "deployments": Deployment,
    "statefulsets": StatefulSet,
    "daemonsets": DaemonSet,
    "jobs": Job,
    "cronjobs": CronJob,
    "leases": Lease,
    "persistentvolumes": PersistentVolume,
    "persistentvolumeclaims": PersistentVolumeClaim,
    "storageclasses": StorageClass,
    "csinodes": CSINode,
    "services": Service,
    "endpointslices": EndpointSlice,
    "resourcequotas": ResourceQuota,
    "limitranges": LimitRange,
    "horizontalpodautoscalers": HorizontalPodAutoscaler,
    "poddisruptionbudgets": PodDisruptionBudget,
    "priorityclasses": PriorityClass,
    "serviceaccounts": ServiceAccount,
    "events": CoreEvent,
    "resourceclaims": ResourceClaim,
    "resourceslices": ResourceSlice,
    "deviceclasses": DeviceClass,
    "customresourcedefinitions": CustomResourceDefinition,
    "certificatesigningrequests": CertificateSigningRequest,
    "apiservices": APIService,
    "volumeattachments": VolumeAttachment,
    "resourceclaimtemplates": ResourceClaimTemplate,
    "podlogs": PodLog,
    "podexecs": PodExec,
    "podportforwards": PodPortForward,
    "configmaps": ConfigMap,
    "secrets": Secret,
    "ingresses": Ingress,
    "ingressclasses": IngressClass,
    "networkpolicies": NetworkPolicy,
    "prioritylevelconfigurations": PriorityLevelConfiguration,
    "flowschemas": FlowSchemaConfiguration,
    "validatingadmissionpolicies": ValidatingAdmissionPolicy,
    "validatingadmissionpolicybindings": ValidatingAdmissionPolicyBinding,
    "mutatingwebhookconfigurations": MutatingWebhookConfiguration,
    "validatingwebhookconfigurations": ValidatingWebhookConfiguration,
    "podgroups": PodGroup,
}
CLUSTER_SCOPED = {"nodes", "namespaces", "persistentvolumes", "storageclasses",
                  "volumeattachments", "apiservices",
                  "csinodes", "resourceslices", "deviceclasses",
                  "priorityclasses", "customresourcedefinitions",
                  "certificatesigningrequests", "ingressclasses",
                  "prioritylevelconfigurations", "flowschemas",
                  "validatingadmissionpolicies",
                  "validatingadmissionpolicybindings",
                  "mutatingwebhookconfigurations",
                  "validatingwebhookconfigurations"}
GROUP_PREFIX = {
    "pods": "/api/v1",
    "nodes": "/api/v1",
    "namespaces": "/api/v1",
    "replicasets": "/apis/apps/v1",
    "deployments": "/apis/apps/v1",
    "statefulsets": "/apis/apps/v1",
    "daemonsets": "/apis/apps/v1",
    "jobs": "/apis/batch/v1",
    "cronjobs": "/apis/batch/v1",
    "leases": "/apis/coordination.k8s.io/v1",
    "persistentvolumes": "/api/v1",
    "persistentvolumeclaims": "/api/v1",
    "storageclasses": "/apis/storage.k8s.io/v1",
    "csinodes": "/apis/storage.k8s.io/v1",
    "volumeattachments": "/apis/storage.k8s.io/v1",
    "apiservices": "/apis/apiregistration.k8s.io/v1",
    "services": "/api/v1",
    "endpointslices": "/apis/discovery.k8s.io/v1",
    "resourcequotas": "/api/v1",
    "limitranges": "/api/v1",
    "horizontalpodautoscalers": "/apis/autoscaling/v2",
    "poddisruptionbudgets": "/apis/policy/v1",
    "priorityclasses": "/apis/scheduling.k8s.io/v1",
    "serviceaccounts": "/api/v1",
    "events": "/api/v1",
    "resourceclaims": "/apis/resource.k8s.io/v1beta1",
    "resourceclaimtemplates": "/apis/resource.k8s.io/v1beta1",
    "resourceslices": "/apis/resource.k8s.io/v1beta1",
    "deviceclasses": "/apis/resource.k8s.io/v1beta1",
    "customresourcedefinitions": "/apis/apiextensions.k8s.io/v1",
    "certificatesigningrequests": "/apis/certificates.k8s.io/v1",
    "podlogs": "/api/v1",
    "podexecs": "/api/v1",
    "podportforwards": "/api/v1",
    "configmaps": "/api/v1",
    "secrets": "/api/v1",
    "ingresses": "/apis/networking.k8s.io/v1",
    "ingressclasses": "/apis/networking.k8s.io/v1",
    "networkpolicies": "/apis/networking.k8s.io/v1",
    "prioritylevelconfigurations": "/apis/flowcontrol.apiserver.k8s.io/v1",
    "flowschemas": "/apis/flowcontrol.apiserver.k8s.io/v1",
    "validatingadmissionpolicies": "/apis/admissionregistration.k8s.io/v1",
    "validatingadmissionpolicybindings":
        "/apis/admissionregistration.k8s.io/v1",
    "mutatingwebhookconfigurations": "/apis/admissionregistration.k8s.io/v1",
    "validatingwebhookconfigurations":
        "/apis/admissionregistration.k8s.io/v1",
    "podgroups": "/apis/scheduling.x-k8s.io/v1alpha1",
}


def from_dict(resource: str, d: Dict) -> Any:
    t = RESOURCE_TO_TYPE[resource]
    if hasattr(t, "from_dict"):
        return t.from_dict(d)
    raise ValueError(f"cannot deserialize {resource}")


def _requirements_to_list(reqs) -> list:
    out = []
    for r in reqs:
        e: Dict[str, Any] = {"key": r.key, "operator": r.op}
        if r.values:
            e["values"] = list(r.values)
        out.append(e)
    return out


def _selector_to_dict(sel: Selector) -> Dict:
    return {"matchExpressions": _requirements_to_list(sel.requirements)} if sel.requirements else {}


def _node_selector_to_dict(ns: NodeSelector) -> Dict:
    return {"nodeSelectorTerms": [
        {
            **({"matchExpressions": _requirements_to_list(t.match_expressions)}
               if t.match_expressions else {}),
            **({"matchFields": _requirements_to_list(t.match_fields)}
               if t.match_fields else {}),
        }
        for t in ns.terms
    ]}


def _pod_affinity_term_to_dict(t: PodAffinityTerm) -> Dict:
    d: Dict[str, Any] = {"topologyKey": t.topology_key}
    if t.selector is not None:
        d["labelSelector"] = _selector_to_dict(t.selector)
    if t.namespaces:
        d["namespaces"] = list(t.namespaces)
    if t.namespace_selector is not None:
        d["namespaceSelector"] = _selector_to_dict(t.namespace_selector)
    if t.match_label_keys:
        d["matchLabelKeys"] = list(t.match_label_keys)
    return d


def _affinity_to_dict(a: Affinity) -> Dict:
    d: Dict[str, Any] = {}
    na: Dict[str, Any] = {}
    if a.node_affinity_required is not None:
        na["requiredDuringSchedulingIgnoredDuringExecution"] = _node_selector_to_dict(
            a.node_affinity_required)
    if a.node_affinity_preferred:
        na["preferredDuringSchedulingIgnoredDuringExecution"] = [
            {"weight": p.weight, "preference": {
                **({"matchExpressions": _requirements_to_list(p.term.match_expressions)}
                   if p.term.match_expressions else {}),
                **({"matchFields": _requirements_to_list(p.term.match_fields)}
                   if p.term.match_fields else {}),
            }}
            for p in a.node_affinity_preferred
        ]
    if na:
        d["nodeAffinity"] = na
    for attr, key in (("pod_affinity_required", "podAffinity"),
                      ("pod_anti_affinity_required", "podAntiAffinity")):
        terms = getattr(a, attr)
        pref = getattr(a, attr.replace("_required", "_preferred"))
        sub: Dict[str, Any] = {}
        if terms:
            sub["requiredDuringSchedulingIgnoredDuringExecution"] = [
                _pod_affinity_term_to_dict(t) for t in terms]
        if pref:
            sub["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": w.weight, "podAffinityTerm": _pod_affinity_term_to_dict(w.term)}
                for w in pref]
        if sub:
            d[key] = sub
    return d


def pod_to_dict(pod: Pod) -> Dict:
    spec: Dict[str, Any] = {
        "containers": [c.to_dict() for c in pod.spec.containers],
    }
    if pod.spec.init_containers:
        spec["initContainers"] = [c.to_dict() for c in pod.spec.init_containers]
    if pod.spec.node_name:
        spec["nodeName"] = pod.spec.node_name
    if pod.spec.scheduler_name != "default-scheduler":
        spec["schedulerName"] = pod.spec.scheduler_name
    if pod.spec.node_selector:
        spec["nodeSelector"] = dict(pod.spec.node_selector)
    if pod.spec.affinity:
        aff = _affinity_to_dict(pod.spec.affinity)
        if aff:
            spec["affinity"] = aff
    if pod.spec.tolerations:
        spec["tolerations"] = [
            {k: v for k, v in (("key", t.key), ("operator", t.operator), ("value", t.value),
                               ("effect", t.effect), ("tolerationSeconds", t.toleration_seconds))
             if v not in ("", None)}
            for t in pod.spec.tolerations
        ]
    if pod.spec.topology_spread_constraints:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": c.max_skew,
                "topologyKey": c.topology_key,
                "whenUnsatisfiable": c.when_unsatisfiable,
                **({"labelSelector": _selector_to_dict(c.selector)} if c.selector is not None else {}),
                **({"minDomains": c.min_domains} if c.min_domains else {}),
                **({"matchLabelKeys": list(c.match_label_keys)} if c.match_label_keys else {}),
            }
            for c in pod.spec.topology_spread_constraints
        ]
    if pod.spec.priority:
        spec["priority"] = pod.spec.priority
    if pod.spec.priority_class_name:
        spec["priorityClassName"] = pod.spec.priority_class_name
    if pod.spec.scheduling_gates:
        spec["schedulingGates"] = [{"name": g} for g in pod.spec.scheduling_gates]
    if pod.spec.overhead:
        spec["overhead"] = pod.spec.overhead
    if pod.spec.volumes:
        spec["volumes"] = [v.to_dict() for v in pod.spec.volumes]
    if pod.spec.resource_claims or pod.spec.resource_claim_templates:
        spec["resourceClaims"] = [
            {"name": n, "resourceClaimName": rc}
            for n, rc in pod.spec.resource_claims
        ] + [
            {"name": n, "resourceClaimTemplateName": t}
            for n, t in pod.spec.resource_claim_templates
        ]
    if pod.spec.service_account_name:
        spec["serviceAccountName"] = pod.spec.service_account_name
    # non-default scalars must round-trip, or read-modify-write paths (PATCH,
    # apply) silently reset them to from_dict defaults
    if pod.spec.restart_policy != "Always":
        spec["restartPolicy"] = pod.spec.restart_policy
    if pod.spec.termination_grace_period_seconds != 30:
        spec["terminationGracePeriodSeconds"] = pod.spec.termination_grace_period_seconds
    if pod.spec.preemption_policy != "PreemptLowerPriority":
        spec["preemptionPolicy"] = pod.spec.preemption_policy
    if pod.spec.host_network:
        spec["hostNetwork"] = True
    if pod.spec.host_pid:
        spec["hostPID"] = True
    if pod.spec.host_ipc:
        spec["hostIPC"] = True
    if pod.spec.security_context:
        spec["securityContext"] = pod.spec.security_context
    status: Dict[str, Any] = {"phase": pod.status.phase}
    if pod.status.nominated_node_name:
        status["nominatedNodeName"] = pod.status.nominated_node_name
    if pod.status.resource_claim_statuses:
        status["resourceClaimStatuses"] = [
            {"name": n, "resourceClaimName": c}
            for n, c in pod.status.resource_claim_statuses.items()]
    if pod.status.conditions:
        status["conditions"] = [
            {"type": c.type, "status": c.status,
             **({"reason": c.reason} if c.reason else {}),
             **({"message": c.message} if c.message else {}),
             **({"lastTransitionTime": c.last_transition_time}
                if c.last_transition_time else {})}
            for c in pod.status.conditions
        ]
    return {"apiVersion": "v1", "kind": "Pod", "metadata": pod.metadata.to_dict(),
            "spec": spec, "status": status}


def node_to_dict(node: Node) -> Dict:
    spec: Dict[str, Any] = {}
    if node.spec.unschedulable:
        spec["unschedulable"] = True
    if node.spec.taints:
        spec["taints"] = [
            {"key": t.key, **({"value": t.value} if t.value else {}), "effect": t.effect}
            for t in node.spec.taints
        ]
    status: Dict[str, Any] = {
        "capacity": dict(node.status.capacity),
        "allocatable": dict(node.status.allocatable),
    }
    if node.status.images:
        status["images"] = [{"names": list(i.names), "sizeBytes": i.size_bytes}
                            for i in node.status.images]
    if node.status.conditions:
        status["conditions"] = [
            {"type": c.type, "status": c.status,
             **({"reason": c.reason} if c.reason else {})}
            for c in node.status.conditions
        ]
    meta = node.metadata.to_dict()
    meta.pop("namespace", None)
    return {"apiVersion": "v1", "kind": "Node", "metadata": meta, "spec": spec, "status": status}


def _template_to_dict(t) -> Dict:
    pod = Pod(metadata=t.metadata, spec=t.spec)
    d = pod_to_dict(pod)
    return {"metadata": {k: v for k, v in d["metadata"].items()
                         if k in ("labels", "annotations", "name")},
            "spec": d["spec"]}


def replicaset_to_dict(rs: ReplicaSet) -> Dict:
    return {
        "apiVersion": "apps/v1", "kind": "ReplicaSet",
        "metadata": rs.metadata.to_dict(),
        "spec": {
            "replicas": rs.spec.replicas,
            **({"selector": _selector_to_dict(rs.spec.selector)}
               if rs.spec.selector is not None else {}),
            "template": _template_to_dict(rs.spec.template),
        },
        "status": {
            "replicas": rs.status.replicas,
            "readyReplicas": rs.status.ready_replicas,
            "observedGeneration": rs.status.observed_generation,
        },
    }


def deployment_to_dict(dep: Deployment) -> Dict:
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": dep.metadata.to_dict(),
        "spec": {
            "replicas": dep.spec.replicas,
            **({"selector": _selector_to_dict(dep.spec.selector)}
               if dep.spec.selector is not None else {}),
            "template": _template_to_dict(dep.spec.template),
            "strategy": {"type": dep.spec.strategy,
                         **({"rollingUpdate": {"maxSurge": dep.spec.max_surge,
                                               "maxUnavailable": dep.spec.max_unavailable}}
                            if dep.spec.strategy == "RollingUpdate" else {})},
        },
        "status": {
            "replicas": dep.status.replicas,
            "updatedReplicas": dep.status.updated_replicas,
            "readyReplicas": dep.status.ready_replicas,
            "observedGeneration": dep.status.observed_generation,
        },
    }


def job_to_dict(job: Job) -> Dict:
    spec: Dict[str, Any] = {
        "parallelism": job.spec.parallelism,
        "backoffLimit": job.spec.backoff_limit,
        "template": _template_to_dict(job.spec.template),
    }
    if job.spec.completions is not None:
        spec["completions"] = job.spec.completions
    if job.spec.active_deadline_seconds is not None:
        spec["activeDeadlineSeconds"] = job.spec.active_deadline_seconds
    if job.spec.completion_mode != "NonIndexed":
        spec["completionMode"] = job.spec.completion_mode
    if job.spec.selector is not None:
        spec["selector"] = _selector_to_dict(job.spec.selector)
    if job.spec.suspend:
        spec["suspend"] = True
    if job.spec.ttl_seconds_after_finished is not None:
        spec["ttlSecondsAfterFinished"] = job.spec.ttl_seconds_after_finished
    status: Dict[str, Any] = {
        "active": job.status.active,
        "succeeded": job.status.succeeded,
        "failed": job.status.failed,
    }
    if job.status.conditions:
        status["conditions"] = job.status.conditions
    if job.status.completed_indexes:
        status["completedIndexes"] = job.status.completed_indexes
    return {"apiVersion": "batch/v1", "kind": "Job",
            "metadata": job.metadata.to_dict(), "spec": spec, "status": status}


def cronjob_to_dict(cj: CronJob) -> Dict:
    job_spec = job_to_dict(Job(spec=cj.spec.job_template))["spec"]
    status: Dict[str, Any] = {}
    if cj.status.last_schedule_time is not None:
        status["lastScheduleTime"] = cj.status.last_schedule_time
    return {
        "apiVersion": "batch/v1", "kind": "CronJob",
        "metadata": cj.metadata.to_dict(),
        "spec": {
            "schedule": cj.spec.schedule,
            **({"timeZone": cj.spec.time_zone} if cj.spec.time_zone else {}),
            "concurrencyPolicy": cj.spec.concurrency_policy,
            **({"suspend": True} if cj.spec.suspend else {}),
            **({"startingDeadlineSeconds": cj.spec.starting_deadline_seconds}
               if cj.spec.starting_deadline_seconds is not None else {}),
            "successfulJobsHistoryLimit": cj.spec.successful_jobs_history_limit,
            "failedJobsHistoryLimit": cj.spec.failed_jobs_history_limit,
            "jobTemplate": {"spec": job_spec},
        },
        "status": status,
    }


def statefulset_to_dict(sts: StatefulSet) -> Dict:
    return {
        "apiVersion": "apps/v1", "kind": "StatefulSet",
        "metadata": sts.metadata.to_dict(),
        "spec": {
            "replicas": sts.spec.replicas,
            **({"selector": _selector_to_dict(sts.spec.selector)}
               if sts.spec.selector is not None else {}),
            "serviceName": sts.spec.service_name,
            "podManagementPolicy": sts.spec.pod_management_policy,
            "template": _template_to_dict(sts.spec.template),
            **({"volumeClaimTemplates": sts.spec.volume_claim_templates}
               if sts.spec.volume_claim_templates else {}),
            "updateStrategy": {
                "type": sts.spec.update_strategy,
                **({"rollingUpdate": {"partition": sts.spec.partition}}
                   if sts.spec.partition else {}),
            },
        },
        "status": {
            "replicas": sts.status.replicas,
            "readyReplicas": sts.status.ready_replicas,
            "currentReplicas": sts.status.current_replicas,
            "updatedReplicas": sts.status.updated_replicas,
            "observedGeneration": sts.status.observed_generation,
            **({"updateRevision": sts.status.update_revision}
               if sts.status.update_revision else {}),
        },
    }


def daemonset_to_dict(ds: DaemonSet) -> Dict:
    return {
        "apiVersion": "apps/v1", "kind": "DaemonSet",
        "metadata": ds.metadata.to_dict(),
        "spec": {
            **({"selector": _selector_to_dict(ds.spec.selector)}
               if ds.spec.selector is not None else {}),
            "template": _template_to_dict(ds.spec.template),
            "updateStrategy": {
                "type": ds.spec.update_strategy,
                **({"rollingUpdate": {"maxUnavailable": ds.spec.max_unavailable}}
                   if ds.spec.update_strategy == "RollingUpdate" else {}),
            },
        },
        "status": {
            "desiredNumberScheduled": ds.status.desired_number_scheduled,
            "currentNumberScheduled": ds.status.current_number_scheduled,
            "numberReady": ds.status.number_ready,
            "numberMisscheduled": ds.status.number_misscheduled,
            "updatedNumberScheduled": ds.status.updated_number_scheduled,
            "observedGeneration": ds.status.observed_generation,
        },
    }


def lease_to_dict(lease: Lease) -> Dict:
    return {
        "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
        "metadata": lease.metadata.to_dict(),
        "spec": {
            "holderIdentity": lease.holder_identity,
            "leaseDurationSeconds": lease.lease_duration_seconds,
            "acquireTime": lease.acquire_time,
            "renewTime": lease.renew_time,
        },
    }


def namespace_to_dict(ns: Namespace) -> Dict:
    meta = ns.metadata.to_dict()
    meta.pop("namespace", None)
    return {"apiVersion": "v1", "kind": "Namespace", "metadata": meta}


_SERIALIZERS = {
    Pod: pod_to_dict,
    Node: node_to_dict,
    ReplicaSet: replicaset_to_dict,
    Deployment: deployment_to_dict,
    StatefulSet: statefulset_to_dict,
    DaemonSet: daemonset_to_dict,
    Job: job_to_dict,
    CronJob: cronjob_to_dict,
    Lease: lease_to_dict,
    Namespace: namespace_to_dict,
}


def to_dict(obj: Any) -> Dict:
    fn = _SERIALIZERS.get(type(obj))
    if fn is None:
        if hasattr(obj, "to_dict"):
            return obj.to_dict()
        raise ValueError(f"cannot serialize {type(obj).__name__}")
    return fn(obj)
