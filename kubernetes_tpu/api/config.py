"""ConfigMap + Secret — the core configuration-payload types.

reference: staging/src/k8s.io/api/core/v1/types.go (ConfigMap ~line 4650,
Secret ~line 4450). Secrets carry base64 `data` on the wire with a write-only
`stringData` convenience field folded into `data` on ingest
(pkg/apis/core/v1/conversion + registry strategy); both support `immutable`,
enforced on update (pkg/apis/core/validation/validation.go
ValidateConfigMapUpdate/ValidateSecretUpdate) — the immutability check here
lives in the admission chain so every write path shares it.
"""

from __future__ import annotations

import base64
from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional

from .types import ObjectMeta

SECRET_OPAQUE = "Opaque"
SECRET_SERVICE_ACCOUNT_TOKEN = "kubernetes.io/service-account-token"


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)
    binary_data: Dict[str, str] = field(default_factory=dict)  # b64 values
    immutable: bool = False

    kind = "ConfigMap"

    @staticmethod
    def from_dict(d: Mapping) -> "ConfigMap":
        return ConfigMap(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            data={k: str(v) for k, v in (d.get("data") or {}).items()},
            binary_data=dict(d.get("binaryData") or {}),
            immutable=bool(d.get("immutable", False)),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"apiVersion": "v1", "kind": "ConfigMap",
                               "metadata": self.metadata.to_dict()}
        if self.data:
            out["data"] = dict(self.data)
        if self.binary_data:
            out["binaryData"] = dict(self.binary_data)
        if self.immutable:
            out["immutable"] = True
        return out


@dataclass
class Secret:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    type: str = SECRET_OPAQUE
    data: Dict[str, str] = field(default_factory=dict)  # b64-encoded values
    immutable: bool = False

    kind = "Secret"

    @staticmethod
    def from_dict(d: Mapping) -> "Secret":
        data = {k: str(v) for k, v in (d.get("data") or {}).items()}
        # stringData is WRITE-ONLY plaintext convenience: folded into data
        # (base64) on ingest, wins over a same-key data entry, never echoed
        for k, v in (d.get("stringData") or {}).items():
            data[k] = base64.b64encode(str(v).encode()).decode()
        return Secret(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            type=d.get("type", SECRET_OPAQUE),
            data=data,
            immutable=bool(d.get("immutable", False)),
        )

    def decoded(self, key: str) -> Optional[str]:
        raw = self.data.get(key)
        if raw is None:
            return None
        return base64.b64decode(raw).decode()

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"apiVersion": "v1", "kind": "Secret",
                               "metadata": self.metadata.to_dict(),
                               "type": self.type}
        if self.data:
            out["data"] = dict(self.data)
        if self.immutable:
            out["immutable"] = True
        return out
