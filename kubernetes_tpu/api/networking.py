"""Networking API types: Service, EndpointSlice.

reference: staging/src/k8s.io/api/core/v1/types.go (Service, ServicePort) and
staging/src/k8s.io/api/discovery/v1/types.go (EndpointSlice, Endpoint).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from .types import ObjectMeta


@dataclass(frozen=True)
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: int = 0  # 0 = same as port
    protocol: str = "TCP"
    node_port: int = 0

    def resolved_target(self) -> int:
        return self.target_port or self.port


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""
    type: str = "ClusterIP"  # ClusterIP | NodePort | LoadBalancer | ExternalName
    external_name: str = ""
    session_affinity: str = "None"


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)

    kind = "Service"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "Service":
        sp = d.get("spec") or {}
        return Service(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=ServiceSpec(
                selector=dict(sp.get("selector") or {}),
                ports=[ServicePort(
                    name=p.get("name", ""),
                    port=int(p.get("port", 0) or 0),
                    target_port=int(p.get("targetPort", 0) or 0),
                    protocol=p.get("protocol", "TCP"),
                    node_port=int(p.get("nodePort", 0) or 0),
                ) for p in sp.get("ports") or []],
                cluster_ip=sp.get("clusterIP", ""),
                type=sp.get("type", "ClusterIP"),
                external_name=sp.get("externalName", ""),
                session_affinity=sp.get("sessionAffinity", "None"),
            ),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "v1", "kind": "Service",
            "metadata": self.metadata.to_dict(),
            "spec": {
                **({"selector": dict(self.spec.selector)} if self.spec.selector else {}),
                "ports": [
                    {**({"name": p.name} if p.name else {}),
                     "port": p.port,
                     **({"targetPort": p.target_port} if p.target_port else {}),
                     "protocol": p.protocol,
                     **({"nodePort": p.node_port} if p.node_port else {})}
                    for p in self.spec.ports
                ],
                **({"clusterIP": self.spec.cluster_ip} if self.spec.cluster_ip else {}),
                "type": self.spec.type,
                **({"externalName": self.spec.external_name}
                   if self.spec.external_name else {}),
            },
        }


@dataclass
class Endpoint:
    addresses: List[str] = field(default_factory=list)
    ready: bool = True
    node_name: str = ""
    target_ref: str = ""  # "ns/pod-name"


@dataclass
class EndpointSlice:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    address_type: str = "IPv4"
    endpoints: List[Endpoint] = field(default_factory=list)
    ports: List[ServicePort] = field(default_factory=list)

    kind = "EndpointSlice"

    LABEL_SERVICE_NAME = "kubernetes.io/service-name"
    MAX_ENDPOINTS = 100  # discovery default maxEndpointsPerSlice

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "EndpointSlice":
        return EndpointSlice(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            address_type=d.get("addressType", "IPv4"),
            endpoints=[Endpoint(
                addresses=list(e.get("addresses") or []),
                ready=bool((e.get("conditions") or {}).get("ready", True)),
                node_name=e.get("nodeName", ""),
                target_ref=(f"{(e.get('targetRef') or {}).get('namespace', 'default')}/"
                            f"{(e.get('targetRef') or {}).get('name', '')}"
                            if e.get("targetRef") else ""),
            ) for e in d.get("endpoints") or []],
            ports=[ServicePort(
                name=p.get("name", ""),
                port=int(p.get("port", 0) or 0),
                protocol=p.get("protocol", "TCP"),
            ) for p in d.get("ports") or []],
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "discovery.k8s.io/v1", "kind": "EndpointSlice",
            "metadata": self.metadata.to_dict(),
            "addressType": self.address_type,
            "endpoints": [
                {"addresses": list(e.addresses),
                 "conditions": {"ready": e.ready},
                 **({"nodeName": e.node_name} if e.node_name else {}),
                 **({"targetRef": {"kind": "Pod",
                                   "namespace": e.target_ref.split("/", 1)[0],
                                   "name": e.target_ref.split("/", 1)[1]}}
                    if e.target_ref else {})}
                for e in self.endpoints
            ],
            "ports": [{**({"name": p.name} if p.name else {}),
                       "port": p.port, "protocol": p.protocol}
                      for p in self.ports],
        }
