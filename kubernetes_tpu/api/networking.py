"""Networking API types: Service, EndpointSlice.

reference: staging/src/k8s.io/api/core/v1/types.go (Service, ServicePort) and
staging/src/k8s.io/api/discovery/v1/types.go (EndpointSlice, Endpoint).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from .types import ObjectMeta


@dataclass(frozen=True)
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: int = 0  # 0 = same as port
    protocol: str = "TCP"
    node_port: int = 0

    def resolved_target(self) -> int:
        return self.target_port or self.port


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""
    type: str = "ClusterIP"  # ClusterIP | NodePort | LoadBalancer | ExternalName
    external_name: str = ""
    session_affinity: str = "None"


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)

    kind = "Service"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "Service":
        sp = d.get("spec") or {}
        return Service(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=ServiceSpec(
                selector=dict(sp.get("selector") or {}),
                ports=[ServicePort(
                    name=p.get("name", ""),
                    port=int(p.get("port", 0) or 0),
                    target_port=int(p.get("targetPort", 0) or 0),
                    protocol=p.get("protocol", "TCP"),
                    node_port=int(p.get("nodePort", 0) or 0),
                ) for p in sp.get("ports") or []],
                cluster_ip=sp.get("clusterIP", ""),
                type=sp.get("type", "ClusterIP"),
                external_name=sp.get("externalName", ""),
                session_affinity=sp.get("sessionAffinity", "None"),
            ),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "v1", "kind": "Service",
            "metadata": self.metadata.to_dict(),
            "spec": {
                **({"selector": dict(self.spec.selector)} if self.spec.selector else {}),
                "ports": [
                    {**({"name": p.name} if p.name else {}),
                     "port": p.port,
                     **({"targetPort": p.target_port} if p.target_port else {}),
                     "protocol": p.protocol,
                     **({"nodePort": p.node_port} if p.node_port else {})}
                    for p in self.spec.ports
                ],
                **({"clusterIP": self.spec.cluster_ip} if self.spec.cluster_ip else {}),
                "type": self.spec.type,
                **({"externalName": self.spec.external_name}
                   if self.spec.external_name else {}),
            },
        }


@dataclass
class Endpoint:
    addresses: List[str] = field(default_factory=list)
    ready: bool = True
    node_name: str = ""
    target_ref: str = ""  # "ns/pod-name"


@dataclass
class EndpointSlice:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    address_type: str = "IPv4"
    endpoints: List[Endpoint] = field(default_factory=list)
    ports: List[ServicePort] = field(default_factory=list)

    kind = "EndpointSlice"

    LABEL_SERVICE_NAME = "kubernetes.io/service-name"
    MAX_ENDPOINTS = 100  # discovery default maxEndpointsPerSlice

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "EndpointSlice":
        return EndpointSlice(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            address_type=d.get("addressType", "IPv4"),
            endpoints=[Endpoint(
                addresses=list(e.get("addresses") or []),
                ready=bool((e.get("conditions") or {}).get("ready", True)),
                node_name=e.get("nodeName", ""),
                target_ref=(f"{(e.get('targetRef') or {}).get('namespace', 'default')}/"
                            f"{(e.get('targetRef') or {}).get('name', '')}"
                            if e.get("targetRef") else ""),
            ) for e in d.get("endpoints") or []],
            ports=[ServicePort(
                name=p.get("name", ""),
                port=int(p.get("port", 0) or 0),
                protocol=p.get("protocol", "TCP"),
            ) for p in d.get("ports") or []],
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "discovery.k8s.io/v1", "kind": "EndpointSlice",
            "metadata": self.metadata.to_dict(),
            "addressType": self.address_type,
            "endpoints": [
                {"addresses": list(e.addresses),
                 "conditions": {"ready": e.ready},
                 **({"nodeName": e.node_name} if e.node_name else {}),
                 **({"targetRef": {"kind": "Pod",
                                   "namespace": e.target_ref.split("/", 1)[0],
                                   "name": e.target_ref.split("/", 1)[1]}}
                    if e.target_ref else {})}
                for e in self.endpoints
            ],
            "ports": [{**({"name": p.name} if p.name else {}),
                       "port": p.port, "protocol": p.protocol}
                      for p in self.ports],
        }


# ---- Ingress + NetworkPolicy ---------------------------------------------------
#
# reference: staging/src/k8s.io/api/networking/v1/types.go. Like the
# reference, these are API surface served by the control plane and consumed
# by OUT-OF-TREE dataplanes (ingress controllers, CNI plugins): the apiserver
# stores/validates/watches them; nothing in-tree programs the packets.


@dataclass
class IngressClass:
    """Cluster-scoped; the is_default annotation drives DefaultIngressClass
    admission (ingressclass.kubernetes.io/is-default-class)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    controller: str = ""

    kind = "IngressClass"
    DEFAULT_ANNOTATION = "ingressclass.kubernetes.io/is-default-class"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped

    @property
    def is_default(self) -> bool:
        return self.metadata.annotations.get(self.DEFAULT_ANNOTATION) == "true"

    @staticmethod
    def from_dict(d) -> "IngressClass":
        return IngressClass(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            controller=(d.get("spec") or {}).get("controller", ""),
        )

    def to_dict(self):
        return {"apiVersion": "networking.k8s.io/v1", "kind": "IngressClass",
                "metadata": self.metadata.to_dict(),
                "spec": {"controller": self.controller}}


@dataclass
class IngressRule:
    host: str = ""
    # [(path, pathType, serviceName, servicePort)]
    paths: list = field(default_factory=list)

    @staticmethod
    def from_dict(d) -> "IngressRule":
        paths = []
        for p in ((d.get("http") or {}).get("paths") or []):
            svc = ((p.get("backend") or {}).get("service") or {})
            paths.append((p.get("path", "/"), p.get("pathType", "Prefix"),
                          svc.get("name", ""),
                          int((svc.get("port") or {}).get("number", 0) or 0)))
        return IngressRule(host=d.get("host", ""), paths=paths)

    def to_dict(self):
        return {
            **({"host": self.host} if self.host else {}),
            "http": {"paths": [
                {"path": path, "pathType": ptype,
                 "backend": {"service": {"name": name,
                                         "port": {"number": port}}}}
                for path, ptype, name, port in self.paths]},
        }


@dataclass
class Ingress:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    ingress_class_name: Optional[str] = None
    rules: List[IngressRule] = field(default_factory=list)
    default_backend: str = ""  # service name

    kind = "Ingress"

    @staticmethod
    def from_dict(d) -> "Ingress":
        spec = d.get("spec") or {}
        db = (((spec.get("defaultBackend") or {}).get("service")) or {})
        return Ingress(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            ingress_class_name=spec.get("ingressClassName"),
            rules=[IngressRule.from_dict(r) for r in spec.get("rules") or []],
            default_backend=db.get("name", ""),
        )

    def to_dict(self):
        spec = {}
        if self.ingress_class_name is not None:
            spec["ingressClassName"] = self.ingress_class_name
        if self.rules:
            spec["rules"] = [r.to_dict() for r in self.rules]
        if self.default_backend:
            spec["defaultBackend"] = {"service": {"name": self.default_backend}}
        return {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
                "metadata": self.metadata.to_dict(), "spec": spec}


@dataclass
class NetworkPolicy:
    """Stored + watched; enforcement belongs to the CNI (out of tree in the
    reference too). Ingress/egress rules kept as raw dicts — the policy
    grammar (peers, ports, ipBlock) round-trips without loss."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    pod_selector: dict = field(default_factory=dict)  # raw LabelSelector
    policy_types: List[str] = field(default_factory=list)
    ingress: list = field(default_factory=list)
    egress: list = field(default_factory=list)

    kind = "NetworkPolicy"

    @staticmethod
    def from_dict(d) -> "NetworkPolicy":
        spec = d.get("spec") or {}
        return NetworkPolicy(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            pod_selector=dict(spec.get("podSelector") or {}),
            policy_types=list(spec.get("policyTypes") or []),
            ingress=list(spec.get("ingress") or []),
            egress=list(spec.get("egress") or []),
        )

    def to_dict(self):
        spec = {"podSelector": self.pod_selector}
        if self.policy_types:
            spec["policyTypes"] = list(self.policy_types)
        if self.ingress:
            spec["ingress"] = self.ingress
        if self.egress:
            spec["egress"] = self.egress
        return {"apiVersion": "networking.k8s.io/v1", "kind": "NetworkPolicy",
                "metadata": self.metadata.to_dict(), "spec": spec}
