"""Exec / attach / port-forward session objects — the streaming channel.

reference: pkg/kubelet/server/server.go serves exec/attach/portforward over
SPDY/websocket streams and kubectl dials them through the apiserver proxy
(kubectl/pkg/cmd/exec/exec.go). This build replaces the byte-stream
transport with STORE-CHANNEL sessions, the same pattern the PodLog channel
proved for `ktl logs`: the client POSTs the pod's exec subresource, the API
server creates a PodExec session object, the kubelet that owns the pod
watches sessions, runs the command against its CRI runtime, and writes the
result into the session; the API server long-polls the session and returns
stdout/stderr/exitCode. stdin rides in the session spec (bidirectional:
client bytes in spec, container bytes in status). Sessions are owned by
their pod (GC'd with it) and deleted by the server after the round-trip.

PodPortForward is the same channel carrying opaque bytes for one
connection round: local socket bytes -> spec.data, remote answer ->
status.data (kubectl port-forward's data channel, one exchange per
request).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from .types import ObjectMeta

# the command marking "attach to the running container" instead of spawning
# one (kubelet server.go attach handler); the kubelet answers with the
# container's recent output and feeds stdin to the container
ATTACH_COMMAND = "__attach__"


@dataclass
class PodExec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    pod_name: str = ""
    container: str = ""
    command: List[str] = field(default_factory=list)
    stdin: str = ""
    tty: bool = False
    # status
    stdout: str = ""
    stdout_b64: str = ""  # byte-faithful copy (text stdout is lossy for
    # binary content — ktl cp reads this)
    stderr: str = ""
    exit_code: Optional[int] = None
    done: bool = False
    error: str = ""

    kind = "PodExec"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "PodExec":
        spec = d.get("spec") or {}
        st = d.get("status") or {}
        return PodExec(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            pod_name=spec.get("podName", ""),
            container=spec.get("container", ""),
            command=list(spec.get("command") or []),
            stdin=spec.get("stdin", ""),
            tty=bool(spec.get("tty", False)),
            stdout=st.get("stdout", ""),
            stdout_b64=st.get("stdoutB64", ""),
            stderr=st.get("stderr", ""),
            exit_code=st.get("exitCode"),
            done=bool(st.get("done", False)),
            error=st.get("error", ""),
        )

    def to_dict(self) -> Dict[str, Any]:
        status: Dict[str, Any] = {"done": self.done}
        if self.stdout:
            status["stdout"] = self.stdout
        if self.stdout_b64:
            status["stdoutB64"] = self.stdout_b64
        if self.stderr:
            status["stderr"] = self.stderr
        if self.exit_code is not None:
            status["exitCode"] = self.exit_code
        if self.error:
            status["error"] = self.error
        return {"apiVersion": "v1", "kind": self.kind,
                "metadata": self.metadata.to_dict(),
                "spec": {"podName": self.pod_name,
                         "container": self.container,
                         "command": list(self.command),
                         **({"stdin": self.stdin} if self.stdin else {}),
                         **({"tty": True} if self.tty else {})},
                "status": status}


@dataclass
class PodPortForward:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    pod_name: str = ""
    port: int = 0
    data: str = ""  # base64 request bytes (one connection round)
    # status
    response: str = ""  # base64 response bytes
    done: bool = False
    error: str = ""

    kind = "PodPortForward"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "PodPortForward":
        spec = d.get("spec") or {}
        st = d.get("status") or {}
        return PodPortForward(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            pod_name=spec.get("podName", ""),
            port=int(spec.get("port", 0) or 0),
            data=spec.get("data", ""),
            response=st.get("data", ""),
            done=bool(st.get("done", False)),
            error=st.get("error", ""),
        )

    def to_dict(self) -> Dict[str, Any]:
        status: Dict[str, Any] = {"done": self.done}
        if self.response:
            status["data"] = self.response
        if self.error:
            status["error"] = self.error
        return {"apiVersion": "v1", "kind": self.kind,
                "metadata": self.metadata.to_dict(),
                "spec": {"podName": self.pod_name, "port": self.port,
                         **({"data": self.data} if self.data else {})},
                "status": status}
