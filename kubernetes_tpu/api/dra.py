"""Dynamic Resource Allocation (DRA) API types — resource.k8s.io/v1beta1 subset.

reference: staging/src/k8s.io/api/resource/v1beta1/types.go (ResourceClaim,
DeviceClass, ResourceSlice, structured parameters) and
staging/src/k8s.io/dynamic-resource-allocation/structured (the allocator these
types feed). The reference selects devices with CEL expressions over device
attributes; this build carries the same shape with declarative attribute
requirements (key op value) — the bounded-vocabulary stance the tensorizer
uses for label selectors (SURVEY.md §7 hard part 4).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Tuple

from .types import ObjectMeta


@dataclass(frozen=True)
class DeviceAttributeRequirement:
    """One attribute requirement: key op value. Ops: ==, !=, in, exists,
    >=, <= (numeric). The analog of one CEL comparison in
    device.attributes (resource/v1beta1 CELDeviceSelector)."""

    key: str
    op: str = "=="
    value: Any = None

    def matches(self, attributes: Mapping[str, Any]) -> bool:
        have = attributes.get(self.key)
        if self.op == "exists":
            return have is not None
        if self.op == "==":
            return have == self.value
        if self.op == "!=":
            return have != self.value
        if self.op == "in":
            return have in (self.value or ())
        try:
            if self.op == ">=":
                return have is not None and float(have) >= float(self.value)
            if self.op == "<=":
                return have is not None and float(have) <= float(self.value)
        except (TypeError, ValueError):
            return False
        return False

    @staticmethod
    def from_dict(d: Mapping) -> "DeviceAttributeRequirement":
        return DeviceAttributeRequirement(
            key=d.get("key", ""), op=d.get("op", "=="), value=d.get("value"))


@dataclass
class Device:
    """One allocatable device in a ResourceSlice (resource/v1beta1 Device:
    name + basic.attributes + basic.capacity)."""

    name: str
    attributes: Dict[str, Any] = field(default_factory=dict)
    capacity: Dict[str, str] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Mapping) -> "Device":
        basic = d.get("basic") or d
        return Device(
            name=d.get("name", ""),
            attributes=dict(basic.get("attributes") or {}),
            capacity=dict(basic.get("capacity") or {}),
        )


@dataclass
class ResourceSlice:
    """Per-node (or per-pool) inventory of devices published by a driver.
    reference: resource/v1beta1 ResourceSlice (spec.nodeName, spec.pool,
    spec.devices)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    node_name: str = ""
    driver: str = ""
    pool: str = ""
    devices: List[Device] = field(default_factory=list)

    kind = "ResourceSlice"

    @staticmethod
    def from_dict(d: Mapping) -> "ResourceSlice":
        spec = d.get("spec") or {}
        return ResourceSlice(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            node_name=spec.get("nodeName", ""),
            driver=spec.get("driver", ""),
            pool=(spec.get("pool") or {}).get("name", "") if isinstance(
                spec.get("pool"), Mapping) else spec.get("pool", ""),
            devices=[Device.from_dict(x) for x in spec.get("devices") or []],
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "apiVersion": "resource.k8s.io/v1beta1",
            "metadata": self.metadata.to_dict(),
            "spec": {
                "nodeName": self.node_name,
                "driver": self.driver,
                "pool": {"name": self.pool},
                "devices": [
                    {"name": dv.name, "basic": {
                        "attributes": dict(dv.attributes),
                        "capacity": dict(dv.capacity)}}
                    for dv in self.devices
                ],
            },
        }


@dataclass
class DeviceClass:
    """Admin-defined device category (resource/v1beta1 DeviceClass):
    selectors every device of the class must satisfy."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selectors: List[DeviceAttributeRequirement] = field(default_factory=list)

    kind = "DeviceClass"

    def matches(self, device: Device) -> bool:
        return all(s.matches(device.attributes) for s in self.selectors)

    @staticmethod
    def from_dict(d: Mapping) -> "DeviceClass":
        spec = d.get("spec") or {}
        return DeviceClass(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            selectors=[DeviceAttributeRequirement.from_dict(s)
                       for s in spec.get("selectors") or []],
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "apiVersion": "resource.k8s.io/v1beta1",
            "metadata": self.metadata.to_dict(),
            "spec": {"selectors": [
                {"key": s.key, "op": s.op, "value": s.value}
                for s in self.selectors]},
        }


@dataclass
class DeviceRequest:
    """One request inside a claim (resource/v1beta1 DeviceRequest):
    `count` devices of `device_class_name` matching extra `selectors`."""

    name: str
    device_class_name: str
    count: int = 1
    selectors: List[DeviceAttributeRequirement] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Mapping) -> "DeviceRequest":
        return DeviceRequest(
            name=d.get("name", ""),
            device_class_name=d.get("deviceClassName", ""),
            count=int(d.get("count", 1) or 1),
            selectors=[DeviceAttributeRequirement.from_dict(s)
                       for s in d.get("selectors") or []],
        )


@dataclass
class AllocationResult:
    """status.allocation (resource/v1beta1 AllocationResult): which devices on
    which node satisfy the claim."""

    node_name: str = ""
    # request name -> [device names] (all from this node's slices)
    devices: Dict[str, List[str]] = field(default_factory=dict)

    def all_devices(self) -> List[str]:
        return [d for ds in self.devices.values() for d in ds]


@dataclass
class ResourceClaim:
    """resource/v1beta1 ResourceClaim: devices.requests + allocation status +
    reservedFor (the pods allowed to use it)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    requests: List[DeviceRequest] = field(default_factory=list)
    allocation: Optional[AllocationResult] = None
    reserved_for: List[str] = field(default_factory=list)  # pod UIDs or keys

    kind = "ResourceClaim"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "ResourceClaim":
        spec = d.get("spec") or {}
        devices = spec.get("devices") or {}
        st = d.get("status") or {}
        alloc = None
        if st.get("allocation"):
            a = st["allocation"]
            alloc = AllocationResult(
                node_name=a.get("nodeName", ""),
                devices={k: list(v) for k, v in (a.get("devices") or {}).items()},
            )
        return ResourceClaim(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            requests=[DeviceRequest.from_dict(r)
                      for r in devices.get("requests") or []],
            allocation=alloc,
            reserved_for=[r.get("name", r) if isinstance(r, Mapping) else r
                          for r in st.get("reservedFor") or []],
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "kind": self.kind,
            "apiVersion": "resource.k8s.io/v1beta1",
            "metadata": self.metadata.to_dict(),
            "spec": {"devices": {"requests": [
                {"name": r.name, "deviceClassName": r.device_class_name,
                 "count": r.count,
                 **({"selectors": [{"key": s.key, "op": s.op, "value": s.value}
                                   for s in r.selectors]} if r.selectors else {})}
                for r in self.requests]}},
        }
        status: Dict[str, Any] = {}
        if self.allocation is not None:
            status["allocation"] = {
                "nodeName": self.allocation.node_name,
                "devices": {k: list(v) for k, v in self.allocation.devices.items()},
            }
        if self.reserved_for:
            status["reservedFor"] = [{"name": n} for n in self.reserved_for]
        if status:
            out["status"] = status
        return out


@dataclass
class ResourceClaimTemplate:
    """resource/v1beta1 ResourceClaimTemplate: spec stamped into generated
    ResourceClaims by the resourceclaim controller (reference:
    pkg/controller/resourceclaim/controller.go — pods reference templates
    via PodSpec.resourceClaims[].resourceClaimTemplateName)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    requests: List[DeviceRequest] = field(default_factory=list)

    kind = "ResourceClaimTemplate"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "ResourceClaimTemplate":
        spec = d.get("spec") or {}
        devices = (spec.get("spec") or spec).get("devices") or {}
        return ResourceClaimTemplate(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            requests=[DeviceRequest.from_dict(r)
                      for r in devices.get("requests") or []],
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "apiVersion": "resource.k8s.io/v1beta1",
            "metadata": self.metadata.to_dict(),
            "spec": {"spec": {"devices": {"requests": [
                {"name": r.name, "deviceClassName": r.device_class_name,
                 "count": r.count,
                 **({"selectors": [{"key": s.key, "op": s.op,
                                    "value": s.value}
                                   for s in r.selectors]}
                    if r.selectors else {})}
                for r in self.requests]}}},
        }
