"""core/v1 Event + the EventRecorder analog.

reference: staging/src/k8s.io/api/core/v1/types.go (Event), client-go
tools/record (EventRecorder + aggregation): components narrate what they did
to an object ("Scheduled", "FailedScheduling", "Preempted", "Killing") and
repeated identical events fold into one object with a bumped `count` instead
of flooding the store.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional

from .types import ObjectMeta, new_uid

NORMAL = "Normal"
WARNING = "Warning"


@dataclass
class Event:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_name: str = ""
    involved_namespace: str = ""
    reason: str = ""
    message: str = ""
    type: str = NORMAL  # Normal | Warning
    count: int = 1
    source: str = ""  # reporting component
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0

    kind = "Event"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "Event":
        inv = d.get("involvedObject") or {}
        return Event(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            involved_kind=inv.get("kind", ""),
            involved_name=inv.get("name", ""),
            involved_namespace=inv.get("namespace", ""),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            type=d.get("type", NORMAL),
            count=int(d.get("count", 1) or 1),
            source=(d.get("source") or {}).get("component", "")
            if isinstance(d.get("source"), Mapping) else d.get("source", ""),
            first_timestamp=float(d.get("firstTimestamp", 0.0) or 0.0),
            last_timestamp=float(d.get("lastTimestamp", 0.0) or 0.0),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind, "apiVersion": "v1",
            "metadata": self.metadata.to_dict(),
            "involvedObject": {"kind": self.involved_kind,
                               "name": self.involved_name,
                               "namespace": self.involved_namespace},
            "reason": self.reason,
            "message": self.message,
            "type": self.type,
            "count": self.count,
            **({"source": {"component": self.source}} if self.source else {}),
            **({"firstTimestamp": self.first_timestamp}
               if self.first_timestamp else {}),
            **({"lastTimestamp": self.last_timestamp}
               if self.last_timestamp else {}),
        }


class EventRecorder:
    """client-go tools/record analog: record(obj, type, reason, message).

    Identical (involved, reason, message) events within the aggregation
    window fold into one Event with count += 1 (EventAggregator behavior) —
    a failing pod retrying every second must not mint thousands of objects.
    Failures to write are swallowed: events are best-effort narration and
    must never break the component emitting them."""

    def __init__(self, store, component: str = "", clock=None):
        from ..utils import Clock

        self.store = store
        self.component = component
        self.clock = clock or Clock()
        self._lock = threading.Lock()
        self._known: Dict[str, str] = {}  # aggregation key -> event object name

    def _agg_key(self, kind: str, namespace: str, name: str,
                 reason: str, message: str) -> str:
        h = hashlib.sha1(
            f"{kind}|{namespace}|{name}|{reason}|{message}|{self.component}"
            .encode()).hexdigest()[:16]
        return h

    def event(self, obj, etype: str, reason: str, message: str) -> None:
        kind = getattr(obj, "kind", type(obj).__name__)
        namespace = getattr(obj.metadata, "namespace", "") or "default"
        name = obj.metadata.name
        now = self.clock.now()
        agg = self._agg_key(kind, namespace, name, reason, message)
        ev_name = f"{name}.{agg}"
        key = f"{namespace}/{ev_name}"

        def bump(cur: Event) -> Event:
            cur.count += 1
            cur.last_timestamp = now
            return cur

        try:
            with self._lock:
                # create-first for unseen keys (the common case pays ONE store
                # op); _known remembers aggregation keys we already created so
                # repeats go straight to the count bump
                if agg in self._known:
                    try:
                        self.store.guaranteed_update("events", key, bump)
                        return
                    except Exception:
                        self._known.pop(agg, None)  # deleted (TTL): recreate
                # consume=True: the recorder never touches the object
                # again, so the store takes ownership without paying
                # create()'s isolation deepcopy (events are emitted per
                # victim under preemption storms)
                _created, errs = self.store.create_many(
                    "events", [Event(
                        metadata=ObjectMeta(name=ev_name,
                                            namespace=namespace,
                                            uid=new_uid()),
                        involved_kind=kind, involved_name=name,
                        involved_namespace=namespace,
                        reason=reason, message=message, type=etype,
                        source=self.component,
                        first_timestamp=now, last_timestamp=now)],
                    consume=True)
                if errs:
                    # already exists (evicted from _known): bump the count
                    self.store.guaranteed_update("events", key, bump)
                self._known[agg] = ev_name
                if len(self._known) > 10_000:
                    self._known.clear()  # bounded memory; worst case re-create
        except Exception:
            pass  # best effort


def events_for(store, kind: str, namespace: str, name: str):
    """All events about one object, oldest first (ktl describe's Events:)."""
    evs, _ = store.list(
        "events",
        lambda e: (e.involved_kind == kind and e.involved_name == name
                   and e.involved_namespace == namespace))
    return sorted(evs, key=lambda e: e.last_timestamp)


# ---- pod logs -----------------------------------------------------------------


@dataclass
class PodLog:
    """The kubelet->apiserver log channel for one pod.

    The reference serves `kubectl logs` by proxying the apiserver to the
    kubelet, which reads per-container log files written by the CRI runtime
    (pkg/kubelet/kuberuntime/kuberuntime_logs.go; registry/core/pod/rest/
    log.go). This build's transport is the store: node agents append lines
    here (in-process kubelets directly, HTTP-joined nodes via PATCH) and the
    server renders GET /api/v1/namespaces/{ns}/pods/{name}/log from it.
    Named after the pod; bounded to MAX_LINES (oldest dropped), the log-file
    rotation analog."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    entries: list = field(default_factory=list)  # "ts container msg" strings

    kind = "PodLog"
    MAX_LINES = 1000

    @staticmethod
    def from_dict(d: Mapping) -> "PodLog":
        return PodLog(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            entries=list(d.get("entries") or []),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"apiVersion": "v1", "kind": "PodLog",
                "metadata": self.metadata.to_dict(),
                "entries": list(self.entries)}


def append_pod_log(store, namespace: str, name: str, container: str,
                   message: str, now: float, pod_uid: str = "") -> None:
    """Best-effort append of one log line (store transport; see PodLog).
    With pod_uid, the created channel carries an ownerReference to its pod so
    the garbage collector reaps it after pod deletion."""
    from ..store import NotFoundError

    line = f"{now:.3f} [{container}] {message}"
    key = f"{namespace}/{name}"
    try:
        def bump(obj):
            refs = obj.metadata.owner_references
            if pod_uid and refs and refs[0].get("uid") not in ("", pod_uid):
                # same-name pod was recreated: this is a NEW log stream (the
                # log-file-per-pod-UID analog) — reset content and re-own, or
                # the GC would reap the live pod's lines as an orphan
                obj.metadata.owner_references = [
                    {"kind": "Pod", "name": name, "uid": pod_uid}]
                obj.entries = [line]
                return obj
            obj.entries.append(line)
            if len(obj.entries) > PodLog.MAX_LINES:
                del obj.entries[:len(obj.entries) - PodLog.MAX_LINES]
            return obj

        store.guaranteed_update("podlogs", key, bump)
    except NotFoundError:
        meta = ObjectMeta(name=name, namespace=namespace)
        if pod_uid:
            meta.owner_references = [{"kind": "Pod", "name": name,
                                      "uid": pod_uid}]
        try:
            store.create("podlogs", PodLog(metadata=meta, entries=[line]))
        except Exception:
            pass  # lost race with another writer: next append lands
    except Exception:
        pass  # logging must never break pod lifecycle
