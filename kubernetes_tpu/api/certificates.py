"""CertificateSigningRequest API — the credential-issuance object.

reference: staging/src/k8s.io/api/certificates/v1/types.go
(CertificateSigningRequest{Spec,Status,Condition}) and the kubeadm TLS
bootstrap flow (node: bootstrap token -> CSR -> approval -> signed cert ->
real identity). This build's "certificate" is an HMAC-signed bearer
credential (server/auth.py SignedTokenAuthenticator) rather than x509 — the
object model, signer names, approval conditions, and the controller split
(approver / signer / cleaner) mirror the reference; only the crypto container
differs, because the transport here is bearer tokens, not mTLS.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from .types import ObjectMeta

# the two signers the reference's kubelet bootstrap uses
# (pkg/apis/certificates/well_known.go)
KUBE_APISERVER_CLIENT_KUBELET = "kubernetes.io/kube-apiserver-client-kubelet"
KUBE_APISERVER_CLIENT = "kubernetes.io/kube-apiserver-client"

APPROVED = "Approved"
DENIED = "Denied"
FAILED = "Failed"


@dataclass
class CSRCondition:
    type: str  # Approved | Denied | Failed
    reason: str = ""
    message: str = ""
    last_update_time: float = 0.0

    @staticmethod
    def from_dict(d: Mapping) -> "CSRCondition":
        return CSRCondition(
            type=d.get("type", ""),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_update_time=float(d.get("lastUpdateTime", 0.0) or 0.0),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"type": self.type}
        if self.reason:
            out["reason"] = self.reason
        if self.message:
            out["message"] = self.message
        if self.last_update_time:
            out["lastUpdateTime"] = self.last_update_time
        return out


@dataclass
class CertificateSigningRequest:
    """Cluster-scoped. spec.request carries the requested identity
    ({"user": ..., "groups": [...]}) — the CSR subject/SAN analog.
    spec.username/groups are the REQUESTOR identity, set by the server from
    the authenticated user (clients cannot forge them, certificates/v1
    semantics)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    request: Dict[str, Any] = field(default_factory=dict)
    signer_name: str = KUBE_APISERVER_CLIENT_KUBELET
    usages: List[str] = field(default_factory=lambda: ["client auth"])
    expiration_seconds: Optional[int] = None
    username: str = ""  # requestor (server-populated)
    groups: List[str] = field(default_factory=list)  # requestor groups
    conditions: List[CSRCondition] = field(default_factory=list)
    certificate: str = ""  # issued credential (signer-populated)

    kind = "CertificateSigningRequest"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped: one store key scheme

    def condition(self, ctype: str) -> Optional[CSRCondition]:
        for c in self.conditions:
            if c.type == ctype:
                return c
        return None

    @property
    def approved(self) -> bool:
        return self.condition(APPROVED) is not None

    @property
    def denied(self) -> bool:
        return self.condition(DENIED) is not None

    @staticmethod
    def from_dict(d: Mapping) -> "CertificateSigningRequest":
        spec = d.get("spec") or {}
        st = d.get("status") or {}
        return CertificateSigningRequest(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            request=dict(spec.get("request") or {}),
            signer_name=spec.get("signerName", KUBE_APISERVER_CLIENT_KUBELET),
            usages=list(spec.get("usages") or ["client auth"]),
            expiration_seconds=spec.get("expirationSeconds"),
            username=spec.get("username", ""),
            groups=list(spec.get("groups") or []),
            conditions=[CSRCondition.from_dict(c) for c in st.get("conditions") or []],
            certificate=st.get("certificate", ""),
        )

    def to_dict(self) -> Dict[str, Any]:
        spec: Dict[str, Any] = {
            "request": self.request,
            "signerName": self.signer_name,
            "usages": list(self.usages),
        }
        if self.expiration_seconds is not None:
            spec["expirationSeconds"] = self.expiration_seconds
        if self.username:
            spec["username"] = self.username
        if self.groups:
            spec["groups"] = list(self.groups)
        status: Dict[str, Any] = {}
        if self.conditions:
            status["conditions"] = [c.to_dict() for c in self.conditions]
        if self.certificate:
            status["certificate"] = self.certificate
        return {
            "apiVersion": "certificates.k8s.io/v1",
            "kind": "CertificateSigningRequest",
            "metadata": self.metadata.to_dict(),
            "spec": spec,
            "status": status,
        }
