"""Workload + coordination API types: ReplicaSet, Deployment, Lease.

reference: staging/src/k8s.io/api/apps/v1/types.go (ReplicaSet, Deployment),
staging/src/k8s.io/api/coordination/v1/types.go (Lease).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from .labels import Selector
from .types import ObjectMeta, Pod, PodSpec


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)

    @staticmethod
    def from_dict(d: Mapping) -> "PodTemplateSpec":
        return PodTemplateSpec(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodSpec.from_dict(d.get("spec") or {}),
        )

    def make_pod(self, name: str, namespace: str, owner: Optional[Dict[str, Any]] = None) -> Pod:
        pod = Pod(
            metadata=ObjectMeta(
                name=name,
                namespace=namespace,
                labels=dict(self.metadata.labels),
                annotations=dict(self.metadata.annotations),
            ),
            spec=copy.deepcopy(self.spec),
        )
        from .types import new_uid

        pod.metadata.uid = new_uid()
        if owner:
            pod.metadata.owner_references = [owner]
        return pod


@dataclass
class ReplicaSetSpec:
    replicas: int = 1
    selector: Optional[Selector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class ReplicaSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicaSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicaSetSpec = field(default_factory=ReplicaSetSpec)
    status: ReplicaSetStatus = field(default_factory=ReplicaSetStatus)

    kind = "ReplicaSet"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "ReplicaSet":
        sp = d.get("spec") or {}
        return ReplicaSet(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=ReplicaSetSpec(
                replicas=int(sp.get("replicas", 1)),
                selector=Selector.from_label_selector(sp.get("selector")),
                template=PodTemplateSpec.from_dict(sp.get("template") or {}),
            ),
        )


@dataclass
class DeploymentSpec:
    replicas: int = 1
    selector: Optional[Selector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: str = "RollingUpdate"  # or "Recreate"
    max_surge: int = 1
    max_unavailable: int = 0


@dataclass
class DeploymentStatus:
    replicas: int = 0
    updated_replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class Deployment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)

    kind = "Deployment"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "Deployment":
        sp = d.get("spec") or {}
        strat = sp.get("strategy") or {}
        ru = strat.get("rollingUpdate") or {}
        return Deployment(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=DeploymentSpec(
                replicas=int(sp.get("replicas", 1)),
                selector=Selector.from_label_selector(sp.get("selector")),
                template=PodTemplateSpec.from_dict(sp.get("template") or {}),
                strategy=strat.get("type", "RollingUpdate"),
                max_surge=int(ru.get("maxSurge", 1) or 0),
                max_unavailable=int(ru.get("maxUnavailable", 0) or 0),
            ),
        )


@dataclass
class Lease:
    """coordination/v1 Lease — node heartbeats and leader election locks."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: int = 40
    acquire_time: float = 0.0
    renew_time: float = 0.0

    kind = "Lease"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "Lease":
        sp = d.get("spec") or {}
        return Lease(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            holder_identity=sp.get("holderIdentity", ""),
            lease_duration_seconds=int(sp.get("leaseDurationSeconds", 40) or 40),
            acquire_time=_parse_time(sp.get("acquireTime")),
            renew_time=_parse_time(sp.get("renewTime")),
        )


def _parse_time(v) -> float:
    """Seconds-float internally; accepts the RFC3339 MicroTime strings real
    coordination/v1 manifests carry."""
    if v in (None, ""):
        return 0.0
    if isinstance(v, (int, float)):
        return float(v)
    from datetime import datetime

    return datetime.fromisoformat(str(v).replace("Z", "+00:00")).timestamp()


# ---------------------------------------------------------------------------
# batch/v1 Job + CronJob (staging/src/k8s.io/api/batch/v1/types.go)
# ---------------------------------------------------------------------------


@dataclass
class JobSpec:
    parallelism: int = 1
    completions: Optional[int] = None  # None: any single success completes (non-indexed)
    backoff_limit: int = 6
    active_deadline_seconds: Optional[int] = None
    completion_mode: str = "NonIndexed"  # or "Indexed"
    selector: Optional[Selector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    ttl_seconds_after_finished: Optional[int] = None
    suspend: bool = False


@dataclass
class JobStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    conditions: List[Dict[str, Any]] = field(default_factory=list)  # Complete/Failed
    # Indexed completion mode: compressed ranges of succeeded indexes,
    # e.g. "0-2,5" (batch/v1 Job.status.completedIndexes)
    completed_indexes: str = ""


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    kind = "Job"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def is_finished(self) -> bool:
        """JobFinished: a Complete or Failed condition with status True."""
        return any(c.get("type") in ("Complete", "Failed") and c.get("status") == "True"
                   for c in self.status.conditions)

    @staticmethod
    def from_dict(d: Mapping) -> "Job":
        sp = d.get("spec") or {}
        st = d.get("status") or {}
        return Job(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=JobSpec(
                parallelism=(int(sp["parallelism"])
                             if sp.get("parallelism") is not None else 1),
                completions=sp.get("completions"),
                backoff_limit=int(sp.get("backoffLimit", 6) if sp.get("backoffLimit") is not None else 6),
                active_deadline_seconds=sp.get("activeDeadlineSeconds"),
                completion_mode=sp.get("completionMode", "NonIndexed"),
                selector=Selector.from_label_selector(sp.get("selector")),
                template=PodTemplateSpec.from_dict(sp.get("template") or {}),
                ttl_seconds_after_finished=sp.get("ttlSecondsAfterFinished"),
                suspend=bool(sp.get("suspend", False)),
            ),
            status=JobStatus(
                active=int(st.get("active", 0) or 0),
                succeeded=int(st.get("succeeded", 0) or 0),
                failed=int(st.get("failed", 0) or 0),
                conditions=list(st.get("conditions") or []),
                completed_indexes=st.get("completedIndexes", ""),
            ),
        )


@dataclass
class CronJobSpec:
    schedule: str = "* * * * *"
    time_zone: str = ""  # IANA name; empty = the controller's local/UTC
    suspend: bool = False
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    starting_deadline_seconds: Optional[int] = None
    successful_jobs_history_limit: int = 3
    failed_jobs_history_limit: int = 1
    job_template: JobSpec = field(default_factory=JobSpec)


@dataclass
class CronJobStatus:
    last_schedule_time: Optional[float] = None
    last_successful_time: Optional[float] = None
    active: List[str] = field(default_factory=list)  # job keys


@dataclass
class CronJob:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronJobSpec = field(default_factory=CronJobSpec)
    status: CronJobStatus = field(default_factory=CronJobStatus)

    kind = "CronJob"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "CronJob":
        sp = d.get("spec") or {}
        jt = (sp.get("jobTemplate") or {}).get("spec") or {}
        return CronJob(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=CronJobSpec(
                schedule=sp.get("schedule", "* * * * *"),
                time_zone=sp.get("timeZone") or "",
                suspend=bool(sp.get("suspend", False)),
                concurrency_policy=sp.get("concurrencyPolicy", "Allow"),
                starting_deadline_seconds=sp.get("startingDeadlineSeconds"),
                successful_jobs_history_limit=int(sp.get("successfulJobsHistoryLimit", 3)
                                                  if sp.get("successfulJobsHistoryLimit") is not None else 3),
                failed_jobs_history_limit=int(sp.get("failedJobsHistoryLimit", 1)
                                              if sp.get("failedJobsHistoryLimit") is not None else 1),
                job_template=Job.from_dict({"spec": jt}).spec,
            ),
        )


# ---------------------------------------------------------------------------
# apps/v1 StatefulSet + DaemonSet (staging/src/k8s.io/api/apps/v1/types.go)
# ---------------------------------------------------------------------------


@dataclass
class StatefulSetSpec:
    replicas: int = 1
    selector: Optional[Selector] = None
    service_name: str = ""
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    pod_management_policy: str = "OrderedReady"  # or "Parallel"
    volume_claim_templates: List[Dict[str, Any]] = field(default_factory=list)
    update_strategy: str = "RollingUpdate"  # or "OnDelete"
    # RollingUpdate only touches ordinals >= partition (canary staging;
    # apps/v1 RollingUpdateStatefulSetStrategy.partition)
    partition: int = 0


@dataclass
class StatefulSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    current_replicas: int = 0
    updated_replicas: int = 0
    observed_generation: int = 0
    update_revision: str = ""


@dataclass
class StatefulSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: StatefulSetSpec = field(default_factory=StatefulSetSpec)
    status: StatefulSetStatus = field(default_factory=StatefulSetStatus)

    kind = "StatefulSet"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "StatefulSet":
        sp = d.get("spec") or {}
        return StatefulSet(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=StatefulSetSpec(
                replicas=int(sp.get("replicas", 1) if sp.get("replicas") is not None else 1),
                selector=Selector.from_label_selector(sp.get("selector")),
                service_name=sp.get("serviceName", ""),
                template=PodTemplateSpec.from_dict(sp.get("template") or {}),
                pod_management_policy=sp.get("podManagementPolicy", "OrderedReady"),
                volume_claim_templates=list(sp.get("volumeClaimTemplates") or []),
                update_strategy=(sp.get("updateStrategy") or {}).get(
                    "type", "RollingUpdate"),
                partition=int(((sp.get("updateStrategy") or {})
                               .get("rollingUpdate") or {}).get("partition", 0) or 0),
            ),
        )


@dataclass
class DaemonSetSpec:
    selector: Optional[Selector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    update_strategy: str = "RollingUpdate"  # or "OnDelete"
    max_unavailable: int = 1  # rollingUpdate.maxUnavailable (absolute count)


@dataclass
class DaemonSetStatus:
    desired_number_scheduled: int = 0
    current_number_scheduled: int = 0
    number_ready: int = 0
    number_misscheduled: int = 0
    updated_number_scheduled: int = 0
    observed_generation: int = 0


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)

    kind = "DaemonSet"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "DaemonSet":
        sp = d.get("spec") or {}
        return DaemonSet(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=DaemonSetSpec(
                selector=Selector.from_label_selector(sp.get("selector")),
                template=PodTemplateSpec.from_dict(sp.get("template") or {}),
                update_strategy=(sp.get("updateStrategy") or {}).get(
                    "type", "RollingUpdate"),
                max_unavailable=int(((sp.get("updateStrategy") or {})
                                     .get("rollingUpdate") or {})
                                    .get("maxUnavailable", 1) or 1),
            ),
        )
