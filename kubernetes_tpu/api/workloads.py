"""Workload + coordination API types: ReplicaSet, Deployment, Lease.

reference: staging/src/k8s.io/api/apps/v1/types.go (ReplicaSet, Deployment),
staging/src/k8s.io/api/coordination/v1/types.go (Lease).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional

from .labels import Selector
from .types import ObjectMeta, Pod, PodSpec


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)

    @staticmethod
    def from_dict(d: Mapping) -> "PodTemplateSpec":
        return PodTemplateSpec(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodSpec.from_dict(d.get("spec") or {}),
        )

    def make_pod(self, name: str, namespace: str, owner: Optional[Dict[str, Any]] = None) -> Pod:
        pod = Pod(
            metadata=ObjectMeta(
                name=name,
                namespace=namespace,
                labels=dict(self.metadata.labels),
                annotations=dict(self.metadata.annotations),
            ),
            spec=copy.deepcopy(self.spec),
        )
        from .types import new_uid

        pod.metadata.uid = new_uid()
        if owner:
            pod.metadata.owner_references = [owner]
        return pod


@dataclass
class ReplicaSetSpec:
    replicas: int = 1
    selector: Optional[Selector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class ReplicaSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicaSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicaSetSpec = field(default_factory=ReplicaSetSpec)
    status: ReplicaSetStatus = field(default_factory=ReplicaSetStatus)

    kind = "ReplicaSet"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "ReplicaSet":
        sp = d.get("spec") or {}
        return ReplicaSet(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=ReplicaSetSpec(
                replicas=int(sp.get("replicas", 1)),
                selector=Selector.from_label_selector(sp.get("selector")),
                template=PodTemplateSpec.from_dict(sp.get("template") or {}),
            ),
        )


@dataclass
class DeploymentSpec:
    replicas: int = 1
    selector: Optional[Selector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: str = "RollingUpdate"  # or "Recreate"
    max_surge: int = 1
    max_unavailable: int = 0


@dataclass
class DeploymentStatus:
    replicas: int = 0
    updated_replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class Deployment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)

    kind = "Deployment"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "Deployment":
        sp = d.get("spec") or {}
        strat = sp.get("strategy") or {}
        ru = strat.get("rollingUpdate") or {}
        return Deployment(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=DeploymentSpec(
                replicas=int(sp.get("replicas", 1)),
                selector=Selector.from_label_selector(sp.get("selector")),
                template=PodTemplateSpec.from_dict(sp.get("template") or {}),
                strategy=strat.get("type", "RollingUpdate"),
                max_surge=int(ru.get("maxSurge", 1) or 0),
                max_unavailable=int(ru.get("maxUnavailable", 0) or 0),
            ),
        )


@dataclass
class Lease:
    """coordination/v1 Lease — node heartbeats and leader election locks."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: int = 40
    acquire_time: float = 0.0
    renew_time: float = 0.0

    kind = "Lease"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "Lease":
        sp = d.get("spec") or {}
        return Lease(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            holder_identity=sp.get("holderIdentity", ""),
            lease_duration_seconds=int(sp.get("leaseDurationSeconds", 40) or 40),
            acquire_time=_parse_time(sp.get("acquireTime")),
            renew_time=_parse_time(sp.get("renewTime")),
        )


def _parse_time(v) -> float:
    """Seconds-float internally; accepts the RFC3339 MicroTime strings real
    coordination/v1 manifests carry."""
    if v in (None, ""):
        return 0.0
    if isinstance(v, (int, float)):
        return float(v)
    from datetime import datetime

    return datetime.fromisoformat(str(v).replace("Z", "+00:00")).timestamp()
