"""apiregistration.k8s.io APIService — the aggregation layer's routing
record.

reference: kube-aggregator (cmd/kube-apiserver delegation chain
apiextensions→core→aggregator, server.go:173 CreateServerChain;
staging/src/k8s.io/kube-aggregator). An APIService claims one API group:
requests under /apis/{group}/... that no built-in or CRD serves are
reverse-proxied to the extension apiserver named in spec.service (here a
plain URL — the reference resolves a Service to endpoints; this build's
Services have no real network backend, so the URL is explicit).
Local=true entries (no service) mark groups served by this server itself.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional

from .types import ObjectMeta


@dataclass
class APIService:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    group: str = ""
    version: str = "v1"
    # extension server base URL (e.g. http://127.0.0.1:9443); empty = Local
    service_url: str = ""
    group_priority_minimum: int = 1000
    # status condition Available (set by the availability checker)
    available: bool = False
    available_message: str = ""

    kind = "APIService"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped
        if not self.metadata.name and self.group:
            self.metadata.name = f"{self.version}.{self.group}"

    @property
    def local(self) -> bool:
        return not self.service_url

    @staticmethod
    def from_dict(d: Mapping) -> "APIService":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        spec = d.get("spec") or {}
        st = d.get("status") or {}
        conds = {c.get("type"): c for c in st.get("conditions") or []}
        avail = conds.get("Available") or {}
        return APIService(
            metadata=meta,
            group=spec.get("group", ""),
            version=spec.get("version", "v1"),
            service_url=(spec.get("service") or {}).get("url", ""),
            group_priority_minimum=int(
                spec.get("groupPriorityMinimum", 1000) or 1000),
            available=avail.get("status") == "True",
            available_message=avail.get("message", ""),
        )

    def to_dict(self) -> Dict[str, Any]:
        meta = self.metadata.to_dict()
        meta.pop("namespace", None)
        spec: Dict[str, Any] = {
            "group": self.group,
            "version": self.version,
            "groupPriorityMinimum": self.group_priority_minimum,
        }
        if self.service_url:
            spec["service"] = {"url": self.service_url}
        return {
            "apiVersion": "apiregistration.k8s.io/v1",
            "kind": self.kind,
            "metadata": meta,
            "spec": spec,
            "status": {"conditions": [{
                "type": "Available",
                "status": "True" if self.available else "False",
                **({"message": self.available_message}
                   if self.available_message else {}),
            }]},
        }
