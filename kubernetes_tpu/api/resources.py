"""Resource quantities and per-pod/node resource accounting.

Re-provides the semantics of k8s resource.Quantity parsing
(reference: staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go) and the
scheduler's Resource struct (reference: pkg/scheduler/framework/types.go:1027
`Resource` with MilliCPU/Memory/EphemeralStorage/AllowedPodNumber/ScalarResources),
including the pod-request aggregation rule
max(sum(containers), max(initContainers)) + overhead
(reference: pkg/scheduler/framework/plugins/noderesources/fit.go:218
`computePodResourceRequest`) and the non-zero defaults used for scoring
(reference: pkg/scheduler/util/pod_resources.go DefaultMilliCPURequest=100m,
DefaultMemoryRequest=200Mi).

Internal canonical unit: integer *milli* base-units (1 CPU = 1000 mCPU; 1 byte of
memory = 1000 milli-bytes) so fractional quantities like "0.5" and "100m" stay exact.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, Mapping, Optional

# Well-known resource names (reference: staging/src/k8s.io/api/core/v1/types.go
# ResourceCPU/ResourceMemory/ResourceEphemeralStorage/ResourcePods).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

# Defaults for scoring best-effort containers (reference:
# pkg/scheduler/util/pod_resources.go:29-35).
DEFAULT_MILLI_CPU_REQUEST = 100  # 0.1 core
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024  # 200 MiB

_BINARY_SUFFIX = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIX = {
    "n": 10**-9,
    "u": 10**-6,
    "m": 10**-3,
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<exp>[eE][+-]?\d+)|(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E))?$"
)


def parse_quantity_milli(s) -> int:
    """Parse a k8s quantity string into integer milli base-units.

    "100m" -> 100; "1" -> 1000; "1Gi" -> 1024**3 * 1000; 2.5 -> 2500.
    Accepts int/float for convenience (interpreted as whole base-units).
    """
    if isinstance(s, bool):
        raise ValueError(f"invalid quantity: {s!r}")
    if isinstance(s, int):
        return s * 1000
    if isinstance(s, float):
        return round(s * 1000)
    s = s.strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    sign = -1 if m.group("sign") == "-" else 1
    num = m.group("num")
    if m.group("exp"):
        mult = 10 ** int(m.group("exp")[1:])
    elif m.group("suffix") in _BINARY_SUFFIX:
        mult = _BINARY_SUFFIX[m.group("suffix")]
    else:
        mult = _DECIMAL_SUFFIX[m.group("suffix") or ""]
    # Exact integer math: split decimal part to avoid float error.
    if "." in num:
        int_part, frac_part = num.split(".")
        int_part = int(int_part or "0")
        frac_den = 10 ** len(frac_part)
        frac_num = int(frac_part or "0")
        # value = (int_part + frac_num/frac_den) * mult * 1000
        if isinstance(mult, float):
            return sign * round((int_part + frac_num / frac_den) * mult * 1000)
        total = int_part * mult * 1000 + frac_num * mult * 1000 // frac_den
        return sign * total
    if isinstance(mult, float):
        return sign * round(int(num) * mult * 1000)
    return sign * int(num) * mult * 1000


def _ceil_div(a: int, b: int) -> int:
    return -((-a) // b)


def quantity_value(s) -> int:
    """Whole base-units, rounded up (k8s Quantity.Value semantics)."""
    return _ceil_div(parse_quantity_milli(s), 1000)


def quantity_milli_value(s) -> int:
    """Milli base-units (k8s Quantity.MilliValue semantics)."""
    return parse_quantity_milli(s)


def is_scalar_resource_name(name: str) -> bool:
    """Extended/attachable resources tracked in ScalarResources
    (reference: pkg/apis/core/v1/helper/helpers.go IsScalarResourceName)."""
    return name not in (CPU, MEMORY, EPHEMERAL_STORAGE, PODS)


@dataclass
class Resource:
    """Scheduler-internal resource vector.

    Mirrors the semantics of framework.Resource (reference:
    pkg/scheduler/framework/types.go:1027): CPU in millicores, memory and
    ephemeral-storage in bytes, pod-count slot, and a map of scalar resources.
    """

    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalar: Dict[str, int] = field(default_factory=dict)

    def clone(self) -> "Resource":
        return Resource(
            self.milli_cpu,
            self.memory,
            self.ephemeral_storage,
            self.allowed_pod_number,
            dict(self.scalar),
        )

    def add(self, other: "Resource") -> None:
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.ephemeral_storage += other.ephemeral_storage
        for k, v in other.scalar.items():
            self.scalar[k] = self.scalar.get(k, 0) + v

    def sub(self, other: "Resource") -> None:
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.ephemeral_storage -= other.ephemeral_storage
        for k, v in other.scalar.items():
            self.scalar[k] = self.scalar.get(k, 0) - v

    def set_max(self, other: "Resource") -> None:
        """Component-wise max (used for init-container aggregation)."""
        self.milli_cpu = max(self.milli_cpu, other.milli_cpu)
        self.memory = max(self.memory, other.memory)
        self.ephemeral_storage = max(self.ephemeral_storage, other.ephemeral_storage)
        for k, v in other.scalar.items():
            self.scalar[k] = max(self.scalar.get(k, 0), v)

    @staticmethod
    def from_resource_list(rl: Optional[Mapping[str, object]]) -> "Resource":
        """Build from a k8s ResourceList mapping (e.g. {"cpu": "500m", "memory": "1Gi"}).

        CPU -> MilliValue; everything else -> Value (bytes / counts), matching
        framework.Resource.Add (reference: pkg/scheduler/framework/types.go:1060).
        """
        r = Resource()
        if not rl:
            return r
        for name, q in rl.items():
            if name == CPU:
                r.milli_cpu += quantity_milli_value(q)
            elif name == MEMORY:
                r.memory += quantity_value(q)
            elif name == EPHEMERAL_STORAGE:
                r.ephemeral_storage += quantity_value(q)
            elif name == PODS:
                r.allowed_pod_number += quantity_value(q)
            else:
                r.scalar[name] = r.scalar.get(name, 0) + quantity_value(q)
        return r

    def get(self, name: str) -> int:
        if name == CPU:
            return self.milli_cpu
        if name == MEMORY:
            return self.memory
        if name == EPHEMERAL_STORAGE:
            return self.ephemeral_storage
        if name == PODS:
            return self.allowed_pod_number
        return self.scalar.get(name, 0)

    def resource_names(self) -> Iterable[str]:
        names = []
        if self.milli_cpu:
            names.append(CPU)
        if self.memory:
            names.append(MEMORY)
        if self.ephemeral_storage:
            names.append(EPHEMERAL_STORAGE)
        names.extend(self.scalar.keys())
        return names


def compute_pod_resource_request(pod, non_zero: bool = False) -> Resource:
    """Aggregate a pod's resource request.

    max(sum(app containers), max(init containers)) + overhead — the rule in
    fit.go:218 `computePodResourceRequest` / resource_helpers. With non_zero=True,
    best-effort cpu/memory get the scoring defaults (reference:
    pkg/scheduler/util/pod_resources.go GetNonzeroRequests), used for
    NonZeroRequested accounting in NodeInfo.
    """
    total = Resource()
    for c in pod.spec.containers:
        total.add(_container_request(c, non_zero))
    # Non-zero defaults apply to init containers too (reference:
    # pkg/scheduler/framework/types.go:1131-1146 NonMissingContainerRequests).
    init_max = Resource()
    for c in pod.spec.init_containers:
        init_max.set_max(_container_request(c, non_zero))
    total.set_max(init_max)
    if pod.spec.overhead:
        total.add(Resource.from_resource_list(pod.spec.overhead))
    return total


def _container_request(container, non_zero: bool) -> Resource:
    r = Resource.from_resource_list(container.resources.get("requests"))
    if non_zero:
        if r.milli_cpu == 0:
            r.milli_cpu = DEFAULT_MILLI_CPU_REQUEST
        if r.memory == 0:
            r.memory = DEFAULT_MEMORY_REQUEST
    return r
