"""admissionregistration.k8s.io API objects.

reference: staging/src/k8s.io/api/admissionregistration/v1 —
ValidatingAdmissionPolicy(+Binding) carry expression-based policy evaluated
in-process (plugin/policy/validating/plugin.go); Mutating/Validating
WebhookConfiguration call out to HTTP admission webhooks
(plugin/webhook/mutating, plugin/webhook/validating). All four are live API
objects: creating one changes admission behavior on the next write, no
server restart.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .types import ObjectMeta


def _rule_matches(rules: List[Dict], resource: str, operation: str) -> bool:
    """MatchConstraints / webhook rules: [{resources: [...], operations:
    [...]}] with "*" wildcards (admissionregistration/v1 types.go Rule)."""
    for r in rules or []:
        resources = r.get("resources") or ["*"]
        operations = r.get("operations") or ["*"]
        if ("*" in resources or resource in resources) and \
                ("*" in operations or operation in operations
                 or operation.capitalize() in operations
                 or operation.upper() in operations):
            return True
    return False


class ValidatingAdmissionPolicy:
    """spec.matchConstraints.resourceRules + spec.validations[].expression
    (+ message/reason), spec.failurePolicy Fail|Ignore. Expressions run on
    the restricted evaluator (server/celexpr.py) over `object`, `oldObject`,
    `request`."""

    kind = "ValidatingAdmissionPolicy"

    def __init__(self, metadata: Optional[ObjectMeta] = None,
                 resource_rules: Optional[List[Dict]] = None,
                 validations: Optional[List[Dict]] = None,
                 failure_policy: str = "Fail"):
        self.metadata = metadata or ObjectMeta()
        self.metadata.namespace = ""  # cluster-scoped
        self.resource_rules = resource_rules or []
        self.validations = validations or []
        self.failure_policy = failure_policy or "Fail"

    def matches(self, resource: str, operation: str) -> bool:
        return _rule_matches(self.resource_rules, resource, operation)

    @staticmethod
    def from_dict(d: Dict) -> "ValidatingAdmissionPolicy":
        spec = d.get("spec") or {}
        mc = spec.get("matchConstraints") or {}
        return ValidatingAdmissionPolicy(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            resource_rules=[dict(r) for r in mc.get("resourceRules") or []],
            validations=[dict(v) for v in spec.get("validations") or []],
            failure_policy=spec.get("failurePolicy", "Fail"),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": {
                "matchConstraints": {"resourceRules": self.resource_rules},
                "validations": self.validations,
                "failurePolicy": self.failure_policy,
            },
        }


class ValidatingAdmissionPolicyBinding:
    """spec.policyName + optional spec.matchResources.namespaceSelector
    (matchLabels subset) + spec.validationActions ([Deny] default). A policy
    without a binding is inert (plugin/policy/validating: definitions are
    matched through bindings)."""

    kind = "ValidatingAdmissionPolicyBinding"

    def __init__(self, metadata: Optional[ObjectMeta] = None,
                 policy_name: str = "",
                 namespace_match_labels: Optional[Dict[str, str]] = None,
                 validation_actions: Optional[List[str]] = None):
        self.metadata = metadata or ObjectMeta()
        self.metadata.namespace = ""  # cluster-scoped
        self.policy_name = policy_name
        self.namespace_match_labels = namespace_match_labels
        self.validation_actions = validation_actions or ["Deny"]

    @staticmethod
    def from_dict(d: Dict) -> "ValidatingAdmissionPolicyBinding":
        spec = d.get("spec") or {}
        mr = spec.get("matchResources") or {}
        ns_sel = (mr.get("namespaceSelector") or {}).get("matchLabels")
        return ValidatingAdmissionPolicyBinding(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            policy_name=spec.get("policyName", ""),
            namespace_match_labels=dict(ns_sel) if ns_sel else None,
            validation_actions=list(spec.get("validationActions") or ["Deny"]),
        )

    def to_dict(self) -> Dict[str, Any]:
        spec: Dict[str, Any] = {"policyName": self.policy_name,
                                "validationActions": self.validation_actions}
        if self.namespace_match_labels is not None:
            spec["matchResources"] = {"namespaceSelector": {
                "matchLabels": self.namespace_match_labels}}
        return {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": spec,
        }


class _WebhookConfiguration:
    """Shared shape: webhooks: [{name, clientConfig.url, rules,
    failurePolicy, timeoutSeconds}]."""

    kind = ""

    def __init__(self, metadata: Optional[ObjectMeta] = None,
                 webhooks: Optional[List[Dict]] = None):
        self.metadata = metadata or ObjectMeta()
        self.metadata.namespace = ""  # cluster-scoped
        self.webhooks = webhooks or []

    @classmethod
    def from_dict(cls, d: Dict):
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   webhooks=[dict(w) for w in d.get("webhooks") or []])

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "webhooks": self.webhooks,
        }


class MutatingWebhookConfiguration(_WebhookConfiguration):
    kind = "MutatingWebhookConfiguration"


class ValidatingWebhookConfiguration(_WebhookConfiguration):
    kind = "ValidatingWebhookConfiguration"
