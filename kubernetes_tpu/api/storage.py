"""Storage API types: PersistentVolume, PersistentVolumeClaim, StorageClass,
CSINode.

reference: staging/src/k8s.io/api/core/v1/types.go (PersistentVolume,
PersistentVolumeClaim), staging/src/k8s.io/api/storage/v1/types.go
(StorageClass, CSINode). Only the fields the scheduler's volume plugins and
the PV controller consume are modeled.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from .labels import NodeSelector
from .resources import quantity_value
from .types import ObjectMeta

# volumeBindingMode (storage/v1/types.go VolumeBindingMode)
BINDING_IMMEDIATE = "Immediate"
BINDING_WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"

# PV/PVC phases
VOLUME_AVAILABLE = "Available"
VOLUME_BOUND = "Bound"
VOLUME_RELEASED = "Released"
CLAIM_PENDING = "Pending"
CLAIM_BOUND = "Bound"


def _node_selector_to_dict(ns: NodeSelector) -> Dict[str, Any]:
    def reqs(rs):
        return [{"key": r.key, "operator": r.op,
                 **({"values": list(r.values)} if r.values else {})} for r in rs]

    return {"nodeSelectorTerms": [
        {**({"matchExpressions": reqs(t.match_expressions)} if t.match_expressions else {}),
         **({"matchFields": reqs(t.match_fields)} if t.match_fields else {})}
        for t in ns.terms
    ]}

# Access modes (core/v1/types.go PersistentVolumeAccessMode)
READ_WRITE_ONCE = "ReadWriteOnce"
READ_ONLY_MANY = "ReadOnlyMany"
READ_WRITE_MANY = "ReadWriteMany"
READ_WRITE_ONCE_POD = "ReadWriteOncePod"


@dataclass
class PersistentVolumeSpec:
    capacity: int = 0  # storage bytes
    access_modes: List[str] = field(default_factory=list)
    storage_class_name: str = ""
    # persistentVolumeReclaimPolicy: Retain (manual default) | Delete
    reclaim_policy: str = "Retain"
    node_affinity: Optional[NodeSelector] = None  # spec.nodeAffinity.required
    claim_ref: str = ""  # "ns/name" of the bound PVC
    csi_driver: str = ""  # spec.csi.driver (for NodeVolumeLimits counting)
    volume_handle: str = ""  # spec.csi.volumeHandle


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    phase: str = VOLUME_AVAILABLE

    kind = "PersistentVolume"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped

    @staticmethod
    def from_dict(d: Mapping) -> "PersistentVolume":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        spec = d.get("spec") or {}
        csi = spec.get("csi") or {}
        claim = spec.get("claimRef") or {}
        na = (spec.get("nodeAffinity") or {}).get("required")
        return PersistentVolume(
            metadata=meta,
            spec=PersistentVolumeSpec(
                capacity=quantity_value((spec.get("capacity") or {}).get("storage", 0)),
                access_modes=list(spec.get("accessModes") or []),
                storage_class_name=spec.get("storageClassName", ""),
                reclaim_policy=spec.get("persistentVolumeReclaimPolicy",
                                        "Retain"),
                node_affinity=NodeSelector.from_dict(na),
                claim_ref=(f"{claim.get('namespace', 'default')}/{claim['name']}"
                           if claim.get("name") else ""),
                csi_driver=csi.get("driver", ""),
                volume_handle=csi.get("volumeHandle", ""),
            ),
            phase=(d.get("status") or {}).get("phase", VOLUME_AVAILABLE),
        )

    def to_dict(self) -> Dict[str, Any]:
        meta = self.metadata.to_dict()
        meta.pop("namespace", None)
        spec: Dict[str, Any] = {
            "capacity": {"storage": self.spec.capacity},
            "accessModes": list(self.spec.access_modes),
        }
        if self.spec.storage_class_name:
            spec["storageClassName"] = self.spec.storage_class_name
        if self.spec.reclaim_policy != "Retain":
            spec["persistentVolumeReclaimPolicy"] = self.spec.reclaim_policy
        if self.spec.claim_ref:
            ns, _, name = self.spec.claim_ref.partition("/")
            spec["claimRef"] = {"namespace": ns, "name": name}
        if self.spec.csi_driver:
            spec["csi"] = {"driver": self.spec.csi_driver,
                           "volumeHandle": self.spec.volume_handle}
        if self.spec.node_affinity is not None:
            spec["nodeAffinity"] = {
                "required": _node_selector_to_dict(self.spec.node_affinity)}
        return {"apiVersion": "v1", "kind": "PersistentVolume", "metadata": meta,
                "spec": spec, "status": {"phase": self.phase}}


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: List[str] = field(default_factory=list)
    request: int = 0  # resources.requests.storage, bytes
    storage_class_name: Optional[str] = None  # None = cluster default class
    volume_name: str = ""  # bound PV name


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)
    phase: str = CLAIM_PENDING

    kind = "PersistentVolumeClaim"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def is_bound(self) -> bool:
        return bool(self.spec.volume_name) and self.phase == CLAIM_BOUND

    @staticmethod
    def from_dict(d: Mapping) -> "PersistentVolumeClaim":
        spec = d.get("spec") or {}
        req = ((spec.get("resources") or {}).get("requests") or {}).get("storage", 0)
        return PersistentVolumeClaim(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PersistentVolumeClaimSpec(
                access_modes=list(spec.get("accessModes") or []),
                request=quantity_value(req),
                storage_class_name=spec.get("storageClassName"),
                volume_name=spec.get("volumeName", ""),
            ),
            phase=(d.get("status") or {}).get("phase", CLAIM_PENDING),
        )

    def to_dict(self) -> Dict[str, Any]:
        spec: Dict[str, Any] = {
            "accessModes": list(self.spec.access_modes),
            "resources": {"requests": {"storage": self.spec.request}},
        }
        if self.spec.storage_class_name is not None:
            spec["storageClassName"] = self.spec.storage_class_name
        if self.spec.volume_name:
            spec["volumeName"] = self.spec.volume_name
        return {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
                "metadata": self.metadata.to_dict(), "spec": spec,
                "status": {"phase": self.phase}}


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = BINDING_IMMEDIATE
    allowed_topologies: Optional[NodeSelector] = None  # terms ORed, like PV affinity
    is_default: bool = False

    kind = "StorageClass"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped

    @staticmethod
    def from_dict(d: Mapping) -> "StorageClass":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        topo = d.get("allowedTopologies")
        ns = None
        if topo:
            # allowedTopologies is a list of TopologySelectorTerms; model as a
            # NodeSelector whose requirements use the In operator.
            ns = NodeSelector.from_dict({"nodeSelectorTerms": [
                {"matchExpressions": [
                    {"key": e["key"], "operator": "In", "values": list(e.get("values") or [])}
                    for e in t.get("matchLabelExpressions") or []
                ]}
                for t in topo
            ]})
        return StorageClass(
            metadata=meta,
            provisioner=d.get("provisioner", ""),
            volume_binding_mode=d.get("volumeBindingMode", BINDING_IMMEDIATE),
            allowed_topologies=ns,
            is_default=(meta.annotations.get(
                "storageclass.kubernetes.io/is-default-class") == "true"),
        )

    def to_dict(self) -> Dict[str, Any]:
        meta = self.metadata.to_dict()
        meta.pop("namespace", None)
        d: Dict[str, Any] = {
            "apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
            "metadata": meta, "provisioner": self.provisioner,
            "volumeBindingMode": self.volume_binding_mode,
        }
        if self.allowed_topologies is not None:
            d["allowedTopologies"] = [
                {"matchLabelExpressions": [
                    {"key": r.key, "values": list(r.values)}
                    for r in t.match_expressions
                ]}
                for t in self.allowed_topologies.terms
            ]
        return d


@dataclass
class CSINode:
    """Per-node CSI driver registry with attach limits (storage/v1/types.go
    CSINode; consumed by the NodeVolumeLimits plugin)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # driver name -> allocatable count; None = registered but unenforced
    # (nil Allocatable.Count in the reference means "no limit")
    drivers: Dict[str, Optional[int]] = field(default_factory=dict)

    kind = "CSINode"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped, named after the node

    @staticmethod
    def from_dict(d: Mapping) -> "CSINode":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        drivers = {}
        for drv in (d.get("spec") or {}).get("drivers") or []:
            count = (drv.get("allocatable") or {}).get("count")
            drivers[drv["name"]] = int(count) if count is not None else None
        return CSINode(metadata=meta, drivers=drivers)

    def to_dict(self) -> Dict[str, Any]:
        meta = self.metadata.to_dict()
        meta.pop("namespace", None)
        return {"apiVersion": "storage.k8s.io/v1", "kind": "CSINode", "metadata": meta,
                "spec": {"drivers": [
                    {"name": name,
                     **({"allocatable": {"count": count}} if count is not None else {})}
                    for name, count in sorted(self.drivers.items())
                ]}}


@dataclass
class VolumeAttachment:
    """storage.k8s.io/v1 VolumeAttachment: the attach/detach controller's
    record that a PV is attached to a node (reference:
    pkg/controller/volume/attachdetach/attach_detach_controller.go; the
    external CSI attacher flips status.attached — here the controller is
    the attach backend for the fake runtime and attaches synchronously)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    attacher: str = ""
    node_name: str = ""
    pv_name: str = ""  # spec.source.persistentVolumeName
    attached: bool = False

    kind = "VolumeAttachment"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped

    @staticmethod
    def from_dict(d: Mapping) -> "VolumeAttachment":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        spec = d.get("spec") or {}
        return VolumeAttachment(
            metadata=meta,
            attacher=spec.get("attacher", ""),
            node_name=spec.get("nodeName", ""),
            pv_name=(spec.get("source") or {}).get("persistentVolumeName", ""),
            attached=bool((d.get("status") or {}).get("attached", False)),
        )

    def to_dict(self) -> Dict[str, Any]:
        meta = self.metadata.to_dict()
        meta.pop("namespace", None)
        return {"apiVersion": "storage.k8s.io/v1", "kind": self.kind,
                "metadata": meta,
                "spec": {"attacher": self.attacher,
                         "nodeName": self.node_name,
                         "source": {"persistentVolumeName": self.pv_name}},
                "status": {"attached": self.attached}}
