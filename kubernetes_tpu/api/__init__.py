"""L0 — typed API object model (reference: staging/src/k8s.io/api + apimachinery)."""

from .labels import (  # noqa: F401
    NodeSelector,
    NodeSelectorTerm,
    PreferredSchedulingTerm,
    Requirement,
    Selector,
)
from .podgroup import (  # noqa: F401
    LABEL_TPU_SLICE,
    POD_GROUP_LABEL,
    PodGroup,
    PodGroupSpec,
    PodGroupStatus,
    pod_group_key,
)
from .resources import (  # noqa: F401
    Resource,
    compute_pod_resource_request,
    parse_quantity_milli,
    quantity_milli_value,
    quantity_value,
)
from .types import (  # noqa: F401
    Affinity,
    Binding,
    Container,
    ContainerImage,
    ContainerPort,
    Namespace,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodCondition,
    PodSpec,
    PodStatus,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
    find_matching_untolerated_taint,
    new_uid,
)
