"""Label selectors and node-selector terms.

Re-provides the matching semantics of k8s labels.Selector
(reference: staging/src/k8s.io/apimachinery/pkg/labels/selector.go) and
NodeSelector/NodeSelectorTerm matching
(reference: staging/src/k8s.io/component-helpers/scheduling/corev1/nodeaffinity/nodeaffinity.go).

Key semantic points preserved:
  - A LabelSelector of `None` matches nothing; an empty selector matches everything.
  - NotIn / DoesNotExist match when the key is absent.
  - Gt/Lt parse the node label value as an integer; absent or non-integer => no match.
  - NodeSelector is an OR of terms; each term is an AND of requirements; an empty
    term list matches nothing, a term with no requirements matches nothing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

_OPS = {IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT}


@dataclass(frozen=True)
class Requirement:
    key: str
    op: str
    values: Tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.op == IN:
            return has and labels[self.key] in self.values
        if self.op == NOT_IN:
            return (not has) or labels[self.key] not in self.values
        if self.op == EXISTS:
            return has
        if self.op == DOES_NOT_EXIST:
            return not has
        if self.op in (GT, LT):
            if not has or len(self.values) != 1:
                return False
            try:
                lhs = int(labels[self.key])
                rhs = int(self.values[0])
            except ValueError:
                return False
            return lhs > rhs if self.op == GT else lhs < rhs
        raise ValueError(f"unknown operator {self.op!r}")


@dataclass(frozen=True)
class Selector:
    """AND of requirements. Empty selector matches everything."""

    requirements: Tuple[Requirement, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        return all(r.matches(labels) for r in self.requirements)

    def is_empty(self) -> bool:
        return not self.requirements

    @staticmethod
    def from_match_labels(match_labels: Mapping[str, str]) -> "Selector":
        return Selector(
            tuple(Requirement(k, IN, (v,)) for k, v in sorted(match_labels.items()))
        )

    @staticmethod
    def from_label_selector(sel: Optional[Mapping]) -> Optional["Selector"]:
        """Convert a k8s LabelSelector dict ({matchLabels, matchExpressions}).

        Returns None for a nil selector (matches nothing — callers must check),
        mirroring metav1.LabelSelectorAsSelector.
        """
        if sel is None:
            return None
        reqs: List[Requirement] = []
        for k, v in sorted((sel.get("matchLabels") or {}).items()):
            reqs.append(Requirement(k, IN, (v,)))
        for e in sel.get("matchExpressions") or []:
            reqs.append(parse_requirement(e))
        return Selector(tuple(reqs))


def parse_requirement(e: Mapping) -> Requirement:
    """Parse and validate one {key, operator, values} expression."""
    op = e["operator"]
    if op not in _OPS:
        raise ValueError(f"unknown selector operator {op!r}")
    return Requirement(e["key"], op, tuple(e.get("values") or ()))


@dataclass(frozen=True)
class NodeSelectorTerm:
    """AND of matchExpressions (on labels) + matchFields (on metadata.name)."""

    match_expressions: Tuple[Requirement, ...] = ()
    match_fields: Tuple[Requirement, ...] = ()

    def matches(self, node) -> bool:
        if not self.match_expressions and not self.match_fields:
            return False  # empty term matches nothing (nodeaffinity.go)
        if not all(r.matches(node.metadata.labels) for r in self.match_expressions):
            return False
        fields = {"metadata.name": node.metadata.name}
        return all(r.matches(fields) for r in self.match_fields)


@dataclass(frozen=True)
class NodeSelector:
    """OR of terms. Empty selector (no terms) matches nothing."""

    terms: Tuple[NodeSelectorTerm, ...] = ()

    def matches(self, node) -> bool:
        return any(t.matches(node) for t in self.terms)

    @staticmethod
    def from_dict(d: Optional[Mapping]) -> Optional["NodeSelector"]:
        if d is None:
            return None
        terms = []
        for t in d.get("nodeSelectorTerms") or []:
            terms.append(
                NodeSelectorTerm(
                    tuple(parse_requirement(e) for e in t.get("matchExpressions") or []),
                    tuple(parse_requirement(e) for e in t.get("matchFields") or []),
                )
            )
        return NodeSelector(tuple(terms))


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    term: NodeSelectorTerm

    @staticmethod
    def from_dict(d: Mapping) -> "PreferredSchedulingTerm":
        p = d["preference"]
        return PreferredSchedulingTerm(
            weight=int(d["weight"]),
            term=NodeSelectorTerm(
                tuple(parse_requirement(e) for e in p.get("matchExpressions") or []),
                tuple(parse_requirement(e) for e in p.get("matchFields") or []),
            ),
        )


def parse_selector_string(raw: str) -> Selector:
    """Parse the label-selector QUERY STRING grammar
    (apimachinery/pkg/labels/selector.go Parse): comma-joined requirements of
    the forms `k=v`, `k==v`, `k!=v`, `k in (a,b)`, `k notin (a,b)`, `k`,
    `!k`. Raises ValueError on malformed input (the apiserver's 400)."""
    import re

    reqs: List[Requirement] = []
    raw = raw.strip()
    # split on commas NOT inside parentheses
    parts: List[str] = []
    depth = 0
    start = 0
    for i, ch in enumerate(raw):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(raw[start:i])
            start = i + 1
    parts.append(raw[start:])
    set_re = re.compile(r"^(?P<key>[^!=\s]+)\s+(?P<op>in|notin)\s*"
                        r"\((?P<vals>[^)]*)\)$")
    key_re = re.compile(r"^[A-Za-z0-9._/-]+$")

    def checked_key(k: str, part: str) -> str:
        k = k.strip()
        if not key_re.match(k):
            raise ValueError(f"invalid label key in clause {part!r}")
        return k

    for part in parts:
        part = part.strip()
        if not part:
            continue
        m = set_re.match(part)
        if m:
            vals = tuple(v.strip() for v in m.group("vals").split(",")
                         if v.strip())
            if not vals:
                raise ValueError(f"empty value set in {part!r}")
            reqs.append(Requirement(checked_key(m.group("key"), part),
                                    IN if m.group("op") == "in" else NOT_IN,
                                    vals))
        elif "!=" in part:
            k, _, v = part.partition("!=")
            reqs.append(Requirement(checked_key(k, part), NOT_IN, (v.strip(),)))
        elif "=" in part:
            k, _, v = part.partition("=")
            if v.startswith("="):  # the == alias
                v = v[1:]
            reqs.append(Requirement(checked_key(k, part), IN, (v.strip(),)))
        elif part.startswith("!"):
            reqs.append(Requirement(checked_key(part[1:], part), DOES_NOT_EXIST))
        elif key_re.match(part):
            reqs.append(Requirement(part, EXISTS))
        else:
            raise ValueError(f"unparsable selector clause {part!r}")
    return Selector(tuple(reqs))
