"""Typed object model: ObjectMeta, Pod, Node, Binding.

Re-provides (subset of) the k8s core/v1 API surface relevant to scheduling and
control loops (reference: staging/src/k8s.io/api/core/v1/types.go — Pod, PodSpec,
Node, Taint, Toleration, Affinity, TopologySpreadConstraint) and ObjectMeta
(reference: staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go).

Objects parse from / serialize to k8s-style camelCase dicts so standard manifests
round-trip. Construction helpers keep tests fluent (mirroring the reference's
st.MakePod() builders in pkg/scheduler/testing/wrappers.go).
"""

from __future__ import annotations

import itertools
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Tuple

from .labels import (
    NodeSelector,
    PreferredSchedulingTerm,
    Selector,
)

# Well-known label keys (reference: staging/src/k8s.io/api/core/v1/well_known_labels.go)
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_REGION = "topology.kubernetes.io/region"

# Taint effects
TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"

# Pod phases
PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"

DEFAULT_SCHEDULER_NAME = "default-scheduler"

_uid_counter = itertools.count(1)
# one urandom draw per process, not per uid: uuid4 costs ~200us of entropy
# syscall on this rig and uid generation sits on event/create hot paths; the
# counter already guarantees in-process uniqueness, the session suffix keeps
# uids from different processes distinct
_uid_session = uuid.uuid4().hex[:8]


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}-{_uid_session}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    owner_references: List[Dict[str, Any]] = field(default_factory=list)
    finalizers: List[str] = field(default_factory=list)
    # server-side-apply field ownership (raw wire entries: manager,
    # operation, fieldsType, fieldsV1) — maintained by server/fieldmanager.py
    managed_fields: List[Dict[str, Any]] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Mapping) -> "ObjectMeta":
        return ObjectMeta(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid", ""),
            resource_version=int(d.get("resourceVersion", 0) or 0),
            generation=int(d.get("generation", 0) or 0),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            creation_timestamp=float(d.get("creationTimestamp", 0.0) or 0.0),
            deletion_timestamp=d.get("deletionTimestamp"),
            owner_references=list(d.get("ownerReferences") or []),
            finalizers=list(d.get("finalizers") or []),
            managed_fields=list(d.get("managedFields") or []),
        )

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "namespace": self.namespace,
            "uid": self.uid,
            "resourceVersion": self.resource_version,
        }
        if self.generation:
            d["generation"] = self.generation
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.creation_timestamp:
            d["creationTimestamp"] = self.creation_timestamp
        if self.deletion_timestamp is not None:
            d["deletionTimestamp"] = self.deletion_timestamp
        if self.owner_references:
            d["ownerReferences"] = self.owner_references
        if self.finalizers:
            d["finalizers"] = self.finalizers
        if self.managed_fields:
            d["managedFields"] = self.managed_fields
        return d


@dataclass(frozen=True)
class ContainerPort:
    container_port: int
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Container:
    name: str = ""
    image: str = ""
    resources: Dict[str, Dict[str, Any]] = field(default_factory=dict)  # requests/limits
    ports: List[ContainerPort] = field(default_factory=list)
    image_pull_policy: str = ""  # "", Always, IfNotPresent, Never
    # raw core/v1 SecurityContext dict (privileged, runAsNonRoot,
    # allowPrivilegeEscalation, capabilities, seccompProfile, ...) — consumed
    # by the PodSecurity admission level checks
    security_context: Dict[str, Any] = field(default_factory=dict)
    # raw core/v1 EnvVar list ({name, value} | {name, valueFrom:
    # {configMapKeyRef|secretKeyRef}}) + EnvFromSource list — the kubelet
    # resolves references at container start (CreateContainerConfigError
    # when the source is missing)
    env: List[Dict[str, Any]] = field(default_factory=list)
    env_from: List[Dict[str, Any]] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Mapping) -> "Container":
        return Container(
            name=d.get("name", ""),
            image=d.get("image", ""),
            resources=dict(d.get("resources") or {}),
            image_pull_policy=d.get("imagePullPolicy", ""),
            security_context=dict(d.get("securityContext") or {}),
            env=[dict(e) for e in d.get("env") or []],
            env_from=[dict(e) for e in d.get("envFrom") or []],
            ports=[
                ContainerPort(
                    container_port=int(p["containerPort"]),
                    host_port=int(p.get("hostPort", 0) or 0),
                    protocol=p.get("protocol", "TCP"),
                    host_ip=p.get("hostIP", ""),
                )
                for p in d.get("ports") or []
            ],
        )

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name}
        if self.image:
            d["image"] = self.image
        if self.resources:
            d["resources"] = self.resources
        if self.image_pull_policy:
            d["imagePullPolicy"] = self.image_pull_policy
        if self.security_context:
            d["securityContext"] = self.security_context
        if self.env:
            d["env"] = self.env
        if self.env_from:
            d["envFrom"] = self.env_from
        if self.ports:
            d["ports"] = [
                {
                    "containerPort": p.container_port,
                    **({"hostPort": p.host_port} if p.host_port else {}),
                    "protocol": p.protocol,
                    **({"hostIP": p.host_ip} if p.host_ip else {}),
                }
                for p in self.ports
            ]
        return d


@dataclass(frozen=True)
class Volume:
    """Pod volume source (reference: staging/src/k8s.io/api/core/v1/types.go Volume;
    only the sources the scheduler inspects: PVC references and the shared-disk
    sources VolumeRestrictions checks for conflicts)."""

    name: str
    pvc_claim_name: str = ""  # persistentVolumeClaim.claimName
    pvc_read_only: bool = False
    gce_pd: str = ""  # gcePersistentDisk.pdName
    gce_read_only: bool = False
    aws_ebs: str = ""  # awsElasticBlockStore.volumeID
    rbd: str = ""  # rbd.image
    rbd_read_only: bool = False
    iscsi: str = ""  # iscsi "iqn/lun"
    iscsi_read_only: bool = False
    ephemeral: bool = False  # ephemeral.volumeClaimTemplate (claim name = pod-volname)
    host_path: str = ""  # hostPath.path — PodSecurity baseline forbids these
    config_map: str = ""  # configMap.name — kubelet resolves at start
    config_map_optional: bool = False
    secret: str = ""  # secret.secretName
    secret_optional: bool = False

    @property
    def scheduling_relevant(self) -> bool:
        """True when any scheduler plugin inspects this source (PVC/ephemeral
        for VolumeBinding/Zone/Limits, shared-disk sources for
        VolumeRestrictions). configMap/secret/emptyDir/projected volumes parse
        to name-only entries and never constrain placement."""
        return bool(self.pvc_claim_name or self.ephemeral or self.gce_pd
                    or self.aws_ebs or self.rbd or self.iscsi)

    @staticmethod
    def from_dict(d: Mapping) -> "Volume":
        pvc = d.get("persistentVolumeClaim") or {}
        gce = d.get("gcePersistentDisk") or {}
        ebs = d.get("awsElasticBlockStore") or {}
        rbd = d.get("rbd") or {}
        iscsi = d.get("iscsi") or {}
        return Volume(
            name=d.get("name", ""),
            pvc_claim_name=pvc.get("claimName", ""),
            pvc_read_only=bool(pvc.get("readOnly", False)),
            gce_pd=gce.get("pdName", ""),
            gce_read_only=bool(gce.get("readOnly", False)),
            aws_ebs=ebs.get("volumeID", ""),
            rbd=rbd.get("image", ""),
            rbd_read_only=bool(rbd.get("readOnly", False)),
            iscsi=(f"{iscsi.get('iqn', '')}/{iscsi.get('lun', 0)}" if iscsi else ""),
            iscsi_read_only=bool(iscsi.get("readOnly", False)),
            ephemeral="ephemeral" in d,
            host_path=(d.get("hostPath") or {}).get("path", ""),
            config_map=(d.get("configMap") or {}).get("name", ""),
            config_map_optional=bool((d.get("configMap") or {}).get("optional", False)),
            secret=(d.get("secret") or {}).get("secretName", ""),
            secret_optional=bool((d.get("secret") or {}).get("optional", False)),
        )

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name}
        if self.pvc_claim_name:
            d["persistentVolumeClaim"] = {"claimName": self.pvc_claim_name,
                                          **({"readOnly": True} if self.pvc_read_only else {})}
        if self.gce_pd:
            d["gcePersistentDisk"] = {"pdName": self.gce_pd,
                                      **({"readOnly": True} if self.gce_read_only else {})}
        if self.aws_ebs:
            d["awsElasticBlockStore"] = {"volumeID": self.aws_ebs}
        if self.rbd:
            d["rbd"] = {"image": self.rbd, **({"readOnly": True} if self.rbd_read_only else {})}
        if self.iscsi:
            iqn, _, lun = self.iscsi.rpartition("/")
            d["iscsi"] = {"iqn": iqn, "lun": int(lun or 0),
                          **({"readOnly": True} if self.iscsi_read_only else {})}
        if self.ephemeral:
            d["ephemeral"] = {"volumeClaimTemplate": {}}
        if self.host_path:
            d["hostPath"] = {"path": self.host_path}
        if self.config_map:
            d["configMap"] = {"name": self.config_map,
                              **({"optional": True} if self.config_map_optional
                                 else {})}
        if self.secret:
            d["secret"] = {"secretName": self.secret,
                           **({"optional": True} if self.secret_optional
                              else {})}
        return d


@dataclass(frozen=True)
class Toleration:
    """reference: staging/src/k8s.io/api/core/v1/types.go Toleration."""

    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: "Taint") -> bool:
        """ToleratesTaint semantics (reference:
        staging/src/k8s.io/api/core/v1/toleration.go:38)."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator in ("", "Equal"):
            return self.value == taint.value
        return self.operator == "Exists"

    @staticmethod
    def from_dict(d: Mapping) -> "Toleration":
        return Toleration(
            key=d.get("key", ""),
            operator=d.get("operator", "Equal"),
            value=d.get("value", ""),
            effect=d.get("effect", ""),
            toleration_seconds=d.get("tolerationSeconds"),
        )


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = TAINT_NO_SCHEDULE

    @staticmethod
    def from_dict(d: Mapping) -> "Taint":
        return Taint(key=d["key"], value=d.get("value", ""), effect=d.get("effect", TAINT_NO_SCHEDULE))


def find_matching_untolerated_taint(taints, tolerations, effects=(TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE)):
    """reference: staging/src/k8s.io/component-helpers/scheduling/corev1/helpers.go
    FindMatchingUntoleratedTaint filtered to DoNotSchedule effects."""
    for taint in taints:
        if taint.effect not in effects:
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return taint
    return None


@dataclass(frozen=True)
class PodAffinityTerm:
    """reference: staging/src/k8s.io/api/core/v1/types.go PodAffinityTerm."""

    topology_key: str
    selector: Optional[Selector]  # label_selector over pods; None matches nothing
    namespaces: Tuple[str, ...] = ()
    namespace_selector: Optional[Selector] = None  # over namespace labels; empty matches all
    match_label_keys: Tuple[str, ...] = ()

    @staticmethod
    def from_dict(d: Mapping) -> "PodAffinityTerm":
        return PodAffinityTerm(
            topology_key=d.get("topologyKey", ""),
            selector=Selector.from_label_selector(d.get("labelSelector")),
            namespaces=tuple(d.get("namespaces") or ()),
            namespace_selector=Selector.from_label_selector(d.get("namespaceSelector")),
            match_label_keys=tuple(d.get("matchLabelKeys") or ()),
        )


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm

    @staticmethod
    def from_dict(d: Mapping) -> "WeightedPodAffinityTerm":
        return WeightedPodAffinityTerm(int(d["weight"]), PodAffinityTerm.from_dict(d["podAffinityTerm"]))


@dataclass
class Affinity:
    node_affinity_required: Optional[NodeSelector] = None
    node_affinity_preferred: List[PreferredSchedulingTerm] = field(default_factory=list)
    pod_affinity_required: List[PodAffinityTerm] = field(default_factory=list)
    pod_affinity_preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_required: List[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Optional[Mapping]) -> Optional["Affinity"]:
        if not d:
            return None
        a = Affinity()
        na = d.get("nodeAffinity") or {}
        a.node_affinity_required = NodeSelector.from_dict(
            na.get("requiredDuringSchedulingIgnoredDuringExecution")
        )
        a.node_affinity_preferred = [
            PreferredSchedulingTerm.from_dict(t)
            for t in na.get("preferredDuringSchedulingIgnoredDuringExecution") or []
        ]
        pa = d.get("podAffinity") or {}
        a.pod_affinity_required = [
            PodAffinityTerm.from_dict(t)
            for t in pa.get("requiredDuringSchedulingIgnoredDuringExecution") or []
        ]
        a.pod_affinity_preferred = [
            WeightedPodAffinityTerm.from_dict(t)
            for t in pa.get("preferredDuringSchedulingIgnoredDuringExecution") or []
        ]
        paa = d.get("podAntiAffinity") or {}
        a.pod_anti_affinity_required = [
            PodAffinityTerm.from_dict(t)
            for t in paa.get("requiredDuringSchedulingIgnoredDuringExecution") or []
        ]
        a.pod_anti_affinity_preferred = [
            WeightedPodAffinityTerm.from_dict(t)
            for t in paa.get("preferredDuringSchedulingIgnoredDuringExecution") or []
        ]
        return a


@dataclass(frozen=True)
class TopologySpreadConstraint:
    """reference: staging/src/k8s.io/api/core/v1/types.go TopologySpreadConstraint."""

    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    selector: Optional[Selector]
    min_domains: Optional[int] = None
    node_affinity_policy: str = "Honor"  # Honor | Ignore
    node_taints_policy: str = "Ignore"  # Honor | Ignore
    match_label_keys: Tuple[str, ...] = ()

    @staticmethod
    def from_dict(d: Mapping) -> "TopologySpreadConstraint":
        return TopologySpreadConstraint(
            max_skew=int(d["maxSkew"]),
            topology_key=d["topologyKey"],
            when_unsatisfiable=d["whenUnsatisfiable"],
            selector=Selector.from_label_selector(d.get("labelSelector")),
            min_domains=d.get("minDomains"),
            node_affinity_policy=d.get("nodeAffinityPolicy", "Honor"),
            node_taints_policy=d.get("nodeTaintsPolicy", "Ignore"),
            match_label_keys=tuple(d.get("matchLabelKeys") or ()),
        )


@dataclass
class PodSpec:
    node_name: str = ""
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    priority: int = 0
    priority_class_name: str = ""
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    scheduling_gates: List[str] = field(default_factory=list)
    overhead: Optional[Dict[str, Any]] = None
    host_network: bool = False
    host_pid: bool = False
    host_ipc: bool = False
    # raw core/v1 PodSecurityContext dict (runAsNonRoot, seccompProfile, ...)
    security_context: Dict[str, Any] = field(default_factory=dict)
    restart_policy: str = "Always"
    termination_grace_period_seconds: int = 30
    volumes: List[Volume] = field(default_factory=list)
    # DRA (core/v1 PodSpec.ResourceClaims): [(claim ref name, ResourceClaim
    # object name)] — reference: PodResourceClaim, core/v1/types.go
    resource_claims: List[Tuple[str, str]] = field(default_factory=list)
    # [(claim ref name, ResourceClaimTemplate name)] — the resourceclaim
    # controller stamps a generated claim per pod and records it in
    # status.resource_claim_statuses
    resource_claim_templates: List[Tuple[str, str]] = field(default_factory=list)
    service_account_name: str = ""

    @staticmethod
    def from_dict(d: Mapping) -> "PodSpec":
        return PodSpec(
            node_name=d.get("nodeName", ""),
            scheduler_name=d.get("schedulerName", DEFAULT_SCHEDULER_NAME),
            containers=[Container.from_dict(c) for c in d.get("containers") or []],
            init_containers=[Container.from_dict(c) for c in d.get("initContainers") or []],
            node_selector=dict(d.get("nodeSelector") or {}),
            affinity=Affinity.from_dict(d.get("affinity")),
            tolerations=[Toleration.from_dict(t) for t in d.get("tolerations") or []],
            topology_spread_constraints=[
                TopologySpreadConstraint.from_dict(t)
                for t in d.get("topologySpreadConstraints") or []
            ],
            priority=int(d.get("priority", 0) or 0),
            priority_class_name=d.get("priorityClassName", ""),
            preemption_policy=d.get("preemptionPolicy", "PreemptLowerPriority"),
            scheduling_gates=[g["name"] if isinstance(g, Mapping) else g for g in d.get("schedulingGates") or []],
            overhead=d.get("overhead"),
            host_network=bool(d.get("hostNetwork", False)),
            host_pid=bool(d.get("hostPID", False)),
            host_ipc=bool(d.get("hostIPC", False)),
            security_context=dict(d.get("securityContext") or {}),
            restart_policy=d.get("restartPolicy", "Always"),
            termination_grace_period_seconds=int(d.get("terminationGracePeriodSeconds", 30) or 30),
            volumes=[Volume.from_dict(v) for v in d.get("volumes") or []],
            resource_claims=[
                (rc.get("name", ""), rc.get("resourceClaimName", ""))
                for rc in d.get("resourceClaims") or []
                if not rc.get("resourceClaimTemplateName")
            ],
            resource_claim_templates=[
                (rc.get("name", ""), rc.get("resourceClaimTemplateName", ""))
                for rc in d.get("resourceClaims") or []
                if rc.get("resourceClaimTemplateName")
            ],
            service_account_name=d.get("serviceAccountName", ""),
        )


@dataclass
class PodCondition:
    type: str
    status: str
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class PodStatus:
    phase: str = PENDING
    conditions: List[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""
    # claim ref name -> generated ResourceClaim name (status.resourceClaimStatuses,
    # written by the resourceclaim controller for template-backed refs)
    resource_claim_statuses: Dict[str, str] = field(default_factory=dict)


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind = "Pod"

    @staticmethod
    def from_dict(d: Mapping) -> "Pod":
        st = d.get("status") or {}
        return Pod(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodSpec.from_dict(d.get("spec") or {}),
            status=PodStatus(
                resource_claim_statuses={
                    rs.get("name", ""): rs.get("resourceClaimName", "")
                    for rs in st.get("resourceClaimStatuses") or []},
                phase=st.get("phase", PENDING),
                conditions=[
                    PodCondition(
                        type=c.get("type", ""),
                        status=c.get("status", ""),
                        reason=c.get("reason", ""),
                        message=c.get("message", ""),
                        last_transition_time=float(c.get("lastTransitionTime", 0.0) or 0.0),
                    )
                    for c in st.get("conditions") or []
                ],
                nominated_node_name=st.get("nominatedNodeName", ""),
            ),
        )

    @property
    def key(self) -> str:
        # memoized: read several times per pod per scheduling cycle (clone,
        # assume, confirm, bind paths at 100k-pod rates); structural clones
        # inherit it via __dict__ copy, and namespace/name never change on a
        # live object (every rename parses a NEW Pod)
        k = self.__dict__.get("_key_cache")
        if k is None:
            k = f"{self.metadata.namespace}/{self.metadata.name}"
            self.__dict__["_key_cache"] = k
        return k

    def is_terminal(self) -> bool:
        return self.status.phase in (SUCCEEDED, FAILED)


@dataclass(frozen=True)
class ContainerImage:
    names: Tuple[str, ...]
    size_bytes: int = 0


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Mapping) -> "NodeSpec":
        return NodeSpec(
            unschedulable=bool(d.get("unschedulable", False)),
            taints=[Taint.from_dict(t) for t in d.get("taints") or []],
        )


@dataclass
class NodeCondition:
    type: str
    status: str
    reason: str = ""
    last_heartbeat_time: float = 0.0
    last_transition_time: float = 0.0


@dataclass
class NodeStatus:
    capacity: Dict[str, Any] = field(default_factory=dict)
    allocatable: Dict[str, Any] = field(default_factory=dict)
    images: List[ContainerImage] = field(default_factory=list)
    conditions: List[NodeCondition] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Mapping) -> "NodeStatus":
        return NodeStatus(
            capacity=dict(d.get("capacity") or {}),
            allocatable=dict(d.get("allocatable") or d.get("capacity") or {}),
            images=[
                ContainerImage(tuple(i.get("names") or ()), int(i.get("sizeBytes", 0) or 0))
                for i in d.get("images") or []
            ],
            conditions=[
                NodeCondition(
                    type=c.get("type", ""),
                    status=c.get("status", ""),
                    reason=c.get("reason", ""),
                    last_heartbeat_time=float(c.get("lastHeartbeatTime", 0.0) or 0.0),
                    last_transition_time=float(c.get("lastTransitionTime", 0.0) or 0.0),
                )
                for c in d.get("conditions") or []
            ],
        )


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    kind = "Node"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped: one store key scheme

    @staticmethod
    def from_dict(d: Mapping) -> "Node":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""  # Nodes are cluster-scoped
        return Node(
            metadata=meta,
            spec=NodeSpec.from_dict(d.get("spec") or {}),
            status=NodeStatus.from_dict(d.get("status") or {}),
        )


@dataclass
class Namespace:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    kind = "Namespace"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped

    @staticmethod
    def from_dict(d: Mapping) -> "Namespace":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""  # Namespaces are cluster-scoped
        return Namespace(metadata=meta)


@dataclass
class Binding:
    """Pod->Node binding subresource (reference:
    staging/src/k8s.io/api/core/v1/types.go Binding; handled by BindingREST.Create,
    pkg/registry/core/pod/storage/storage.go:149)."""

    pod_namespace: str
    pod_name: str
    node_name: str
