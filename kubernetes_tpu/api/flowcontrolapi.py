"""flowcontrol.apiserver.k8s.io API objects.

reference: staging/src/k8s.io/api/flowcontrol/v1 — PriorityLevelConfiguration
and FlowSchema are API objects the apiserver watches; edits reconfigure
dispatch live (server/flowcontrol.py FlowConfigSource consumes these).
"""

from __future__ import annotations


class PriorityLevelConfiguration:
    """Wire form subset: spec.type Exempt|Limited, spec.limited.seats,
    queueLength, queueTimeoutSeconds (the queuing knobs collapsed to the
    one-FIFO model documented above)."""

    kind = "PriorityLevelConfiguration"

    def __init__(self, metadata=None, type: str = "Limited", seats: int = 10,
                 queue_length: int = 50, queue_timeout: float = 5.0):
        from ..api.types import ObjectMeta

        self.metadata = metadata or ObjectMeta()
        self.metadata.namespace = ""  # cluster-scoped
        self.type = type
        self.seats = seats
        self.queue_length = queue_length
        self.queue_timeout = queue_timeout

    @staticmethod
    def from_dict(d):
        from ..api.types import ObjectMeta

        spec = d.get("spec") or {}
        limited = spec.get("limited") or {}

        def val(key, default):
            # explicit zeros are meaningful (queueLength 0 = reject
            # immediately) — only ABSENT fields take defaults
            v = limited.get(key)
            return default if v is None else v

        return PriorityLevelConfiguration(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            type=spec.get("type", "Limited"),
            seats=int(val("seats", 10)),
            queue_length=int(val("queueLength", 50)),
            queue_timeout=float(val("queueTimeoutSeconds", 5.0)),
        )

    def to_dict(self):
        spec = {"type": self.type}
        if self.type == "Limited":
            spec["limited"] = {"seats": self.seats,
                               "queueLength": self.queue_length,
                               "queueTimeoutSeconds": self.queue_timeout}
        return {"apiVersion": "flowcontrol.apiserver.k8s.io/v1",
                "kind": "PriorityLevelConfiguration",
                "metadata": self.metadata.to_dict(), "spec": spec}

    def to_level(self):
        from ..server.flowcontrol import PriorityLevel

        return PriorityLevel(self.metadata.name, seats=self.seats,
                             queue_length=self.queue_length,
                             queue_timeout=self.queue_timeout,
                             exempt=self.type == "Exempt")


class FlowSchemaConfiguration:
    """FlowSchema as an API object: matchingPrecedence orders schemas, the
    subject/rule lists collapse to the FlowSchema matcher's tuples."""

    kind = "FlowSchema"

    def __init__(self, metadata=None, priority_level: str = "global-default",
                 matching_precedence: int = 1000, users=("*",), groups=("*",),
                 verbs=("*",), resources=("*",)):
        from ..api.types import ObjectMeta

        self.metadata = metadata or ObjectMeta()
        self.metadata.namespace = ""  # cluster-scoped
        self.priority_level = priority_level
        self.matching_precedence = matching_precedence
        self.users = tuple(users)
        self.groups = tuple(groups)
        self.verbs = tuple(verbs)
        self.resources = tuple(resources)

    @staticmethod
    def from_dict(d):
        from ..api.types import ObjectMeta

        spec = d.get("spec") or {}
        def sel(key):
            # explicit [] means "match nothing", not wildcard
            v = spec.get(key)
            return ("*",) if v is None else tuple(v)

        return FlowSchemaConfiguration(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            priority_level=(spec.get("priorityLevelConfiguration") or {}).get(
                "name", "global-default"),
            matching_precedence=int(spec.get("matchingPrecedence", 1000) or 1000),
            users=sel("users"),
            groups=sel("groups"),
            verbs=sel("verbs"),
            resources=sel("resources"),
        )

    def to_dict(self):
        return {"apiVersion": "flowcontrol.apiserver.k8s.io/v1",
                "kind": "FlowSchema",
                "metadata": self.metadata.to_dict(),
                "spec": {
                    "priorityLevelConfiguration": {"name": self.priority_level},
                    "matchingPrecedence": self.matching_precedence,
                    "users": list(self.users),
                    "groups": list(self.groups),
                    "verbs": list(self.verbs),
                    "resources": list(self.resources),
                }}

    def to_schema(self):
        from ..server.flowcontrol import FlowSchema

        return FlowSchema(self.metadata.name, self.priority_level,
                          users=self.users, groups=self.groups,
                          verbs=self.verbs, resources=self.resources)


