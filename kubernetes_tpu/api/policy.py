"""Policy/autoscaling API types: ResourceQuota, LimitRange,
HorizontalPodAutoscaler, PodDisruptionBudget.

reference: staging/src/k8s.io/api/core/v1/types.go (ResourceQuota, LimitRange),
staging/src/k8s.io/api/autoscaling/v2/types.go (HorizontalPodAutoscaler),
staging/src/k8s.io/api/policy/v1/types.go (PodDisruptionBudget).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional

from .labels import Selector
from .types import ObjectMeta


@dataclass
class ResourceQuota:
    """Per-namespace aggregate limits; usage tracked in status
    (core/v1 ResourceQuota). Quantities kept in their string form — comparison
    happens through resources.quantity_milli_value."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    hard: Dict[str, Any] = field(default_factory=dict)  # spec.hard
    used: Dict[str, Any] = field(default_factory=dict)  # status.used

    kind = "ResourceQuota"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "ResourceQuota":
        return ResourceQuota(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            hard=dict((d.get("spec") or {}).get("hard") or {}),
            used=dict((d.get("status") or {}).get("used") or {}),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"apiVersion": "v1", "kind": "ResourceQuota",
                "metadata": self.metadata.to_dict(),
                "spec": {"hard": dict(self.hard)},
                "status": {"hard": dict(self.hard), "used": dict(self.used)}}


@dataclass
class LimitRange:
    """Per-namespace default/min/max for container resources (core/v1
    LimitRange, type=Container only — the admission-relevant subset)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    default_limits: Dict[str, Any] = field(default_factory=dict)  # default
    default_requests: Dict[str, Any] = field(default_factory=dict)  # defaultRequest
    max: Dict[str, Any] = field(default_factory=dict)
    min: Dict[str, Any] = field(default_factory=dict)

    kind = "LimitRange"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "LimitRange":
        lr = LimitRange(metadata=ObjectMeta.from_dict(d.get("metadata") or {}))
        for item in (d.get("spec") or {}).get("limits") or []:
            if item.get("type", "Container") != "Container":
                continue
            lr.default_limits.update(item.get("default") or {})
            lr.default_requests.update(item.get("defaultRequest") or {})
            lr.max.update(item.get("max") or {})
            lr.min.update(item.get("min") or {})
        return lr

    def to_dict(self) -> Dict[str, Any]:
        item: Dict[str, Any] = {"type": "Container"}
        if self.default_limits:
            item["default"] = dict(self.default_limits)
        if self.default_requests:
            item["defaultRequest"] = dict(self.default_requests)
        if self.max:
            item["max"] = dict(self.max)
        if self.min:
            item["min"] = dict(self.min)
        return {"apiVersion": "v1", "kind": "LimitRange",
                "metadata": self.metadata.to_dict(),
                "spec": {"limits": [item]}}


@dataclass
class HorizontalPodAutoscaler:
    """autoscaling/v2 subset: CPU-utilization target on a scale target."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    target_kind: str = "Deployment"  # scaleTargetRef.kind
    target_name: str = ""
    min_replicas: int = 1
    max_replicas: int = 10
    target_cpu_utilization: int = 80  # percent of requests
    current_replicas: int = 0
    desired_replicas: int = 0
    last_scale_time: Optional[float] = None

    kind = "HorizontalPodAutoscaler"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "HorizontalPodAutoscaler":
        sp = d.get("spec") or {}
        ref = sp.get("scaleTargetRef") or {}
        target = 80
        for m in sp.get("metrics") or []:
            res = m.get("resource") or {}
            if res.get("name") == "cpu":
                target = int((res.get("target") or {}).get("averageUtilization", 80))
        if "targetCPUUtilizationPercentage" in sp:  # autoscaling/v1 shape
            target = int(sp["targetCPUUtilizationPercentage"])
        st = d.get("status") or {}
        return HorizontalPodAutoscaler(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            target_kind=ref.get("kind", "Deployment"),
            target_name=ref.get("name", ""),
            min_replicas=int(sp.get("minReplicas", 1) or 1),
            max_replicas=int(sp.get("maxReplicas", 10) or 10),
            target_cpu_utilization=target,
            current_replicas=int(st.get("currentReplicas", 0) or 0),
            desired_replicas=int(st.get("desiredReplicas", 0) or 0),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
            "metadata": self.metadata.to_dict(),
            "spec": {
                "scaleTargetRef": {"apiVersion": "apps/v1", "kind": self.target_kind,
                                   "name": self.target_name},
                "minReplicas": self.min_replicas,
                "maxReplicas": self.max_replicas,
                "metrics": [{"type": "Resource", "resource": {
                    "name": "cpu",
                    "target": {"type": "Utilization",
                               "averageUtilization": self.target_cpu_utilization}}}],
            },
            "status": {"currentReplicas": self.current_replicas,
                       "desiredReplicas": self.desired_replicas},
        }


@dataclass
class PodDisruptionBudget:
    """policy/v1 PDB: bounds voluntary evictions (consumed by preemption)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[Selector] = None
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None
    disruptions_allowed: int = 0

    kind = "PodDisruptionBudget"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "PodDisruptionBudget":
        sp = d.get("spec") or {}
        return PodDisruptionBudget(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            selector=Selector.from_label_selector(sp.get("selector")),
            min_available=sp.get("minAvailable"),
            max_unavailable=sp.get("maxUnavailable"),
            disruptions_allowed=int((d.get("status") or {}).get("disruptionsAllowed", 0) or 0),
        )

    def to_dict(self) -> Dict[str, Any]:
        sp: Dict[str, Any] = {}
        if self.min_available is not None:
            sp["minAvailable"] = self.min_available
        if self.max_unavailable is not None:
            sp["maxUnavailable"] = self.max_unavailable
        return {"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
                "metadata": self.metadata.to_dict(), "spec": sp,
                "status": {"disruptionsAllowed": self.disruptions_allowed}}


@dataclass
class PriorityClass:
    """scheduling.k8s.io/v1 PriorityClass (reference:
    staging/src/k8s.io/api/scheduling/v1/types.go): named priority values the
    Priority admission plugin resolves into pod.spec.priority."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    description: str = ""

    kind = "PriorityClass"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped

    @staticmethod
    def from_dict(d: Mapping) -> "PriorityClass":
        return PriorityClass(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            value=int(d.get("value", 0) or 0),
            global_default=bool(d.get("globalDefault", False)),
            preemption_policy=d.get("preemptionPolicy", "PreemptLowerPriority"),
            description=d.get("description", ""),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind, "apiVersion": "scheduling.k8s.io/v1",
            "metadata": self.metadata.to_dict(), "value": self.value,
            **({"globalDefault": True} if self.global_default else {}),
            **({"preemptionPolicy": self.preemption_policy}
               if self.preemption_policy != "PreemptLowerPriority" else {}),
            **({"description": self.description} if self.description else {}),
        }


@dataclass
class ServiceAccount:
    """core/v1 ServiceAccount (identity for in-cluster workloads; the
    serviceaccount admission plugin + controller pair keep a 'default' SA in
    every namespace — reference: plugin/pkg/admission/serviceaccount,
    pkg/controller/serviceaccount)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    secrets: list = field(default_factory=list)
    automount_token: bool = True

    kind = "ServiceAccount"

    @staticmethod
    def from_dict(d: Mapping) -> "ServiceAccount":
        return ServiceAccount(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            secrets=list(d.get("secrets") or []),
            automount_token=bool(d.get("automountServiceAccountToken", True)),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind, "apiVersion": "v1",
            "metadata": self.metadata.to_dict(),
            **({"secrets": list(self.secrets)} if self.secrets else {}),
            **({} if self.automount_token
               else {"automountServiceAccountToken": False}),
        }

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"
