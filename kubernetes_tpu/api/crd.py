"""CustomResourceDefinitions: dynamic API types served without code.

reference: staging/src/k8s.io/apiextensions-apiserver/pkg/apis/apiextensions/
types.go (CustomResourceDefinition{Spec,Names,Version}) and
pkg/apiserver/schema/ (structural schemas: validation + defaulting). The
reference runs a second aggregated apiserver; here the same store serves
dynamic kinds directly — a CRD create makes `/apis/{group}/{version}/{plural}`
live on the next request, with structural-schema validation and defaulting on
writes and full list/watch/patch semantics inherited from the store.

Custom objects are held as `Unstructured`: typed ObjectMeta (so the store,
namespace lifecycle, GC owner references, and field selectors work unchanged)
plus the raw spec/status payload as plain dicts — there is no codegen step and
none is needed; the tensorizer never sees these objects unless a scheduler
plugin opts in.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Tuple

from .types import ObjectMeta


@dataclass
class CRDNames:
    """reference: apiextensions/types.go CustomResourceDefinitionNames."""

    plural: str = ""
    singular: str = ""
    kind: str = ""
    list_kind: str = ""
    short_names: List[str] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Mapping) -> "CRDNames":
        return CRDNames(
            plural=d.get("plural", ""),
            singular=d.get("singular", ""),
            kind=d.get("kind", ""),
            list_kind=d.get("listKind", ""),
            short_names=list(d.get("shortNames") or []),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"plural": self.plural, "kind": self.kind}
        if self.singular:
            out["singular"] = self.singular
        if self.list_kind:
            out["listKind"] = self.list_kind
        if self.short_names:
            out["shortNames"] = list(self.short_names)
        return out


@dataclass
class CRDVersion:
    """One served version; `schema` is the openAPIV3Schema dict (structural
    subset — see validate_structural)."""

    name: str = "v1"
    served: bool = True
    storage: bool = True
    schema: Optional[Dict[str, Any]] = None

    @staticmethod
    def from_dict(d: Mapping) -> "CRDVersion":
        schema = None
        if d.get("schema"):
            schema = d["schema"].get("openAPIV3Schema")
        return CRDVersion(
            name=d.get("name", "v1"),
            served=bool(d.get("served", True)),
            storage=bool(d.get("storage", True)),
            schema=schema,
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"name": self.name, "served": self.served,
                               "storage": self.storage}
        if self.schema is not None:
            out["schema"] = {"openAPIV3Schema": self.schema}
        return out


@dataclass
class CustomResourceDefinition:
    """Cluster-scoped; metadata.name must be `<plural>.<group>`
    (reference: apiextensions validation.ValidateCustomResourceDefinition)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    group: str = ""
    scope: str = "Namespaced"  # or "Cluster"
    names: CRDNames = field(default_factory=CRDNames)
    versions: List[CRDVersion] = field(default_factory=list)

    kind = "CustomResourceDefinition"

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped: one store key scheme

    def validate(self) -> Optional[str]:
        if not self.group or "." not in self.group:
            return f"spec.group must be a DNS domain, got {self.group!r}"
        if not self.names.plural:
            return "spec.names.plural is required"
        if not self.names.kind:
            return "spec.names.kind is required"
        if self.scope not in ("Namespaced", "Cluster"):
            return f"spec.scope must be Namespaced or Cluster, got {self.scope!r}"
        want = f"{self.names.plural}.{self.group}"
        if self.metadata.name != want:
            return (f"metadata.name must be spec.names.plural+\".\"+spec.group: "
                    f"expected {want!r}, got {self.metadata.name!r}")
        if not self.versions:
            return "spec.versions must have at least one version"
        if sum(1 for v in self.versions if v.storage) != 1:
            return "exactly one version must have storage=true"
        return None

    def served_version(self) -> Optional[CRDVersion]:
        for v in self.versions:
            if v.storage and v.served:
                return v
        for v in self.versions:
            if v.served:
                return v
        return None

    @property
    def group_prefix(self) -> str:
        v = self.served_version()
        return f"/apis/{self.group}/{v.name if v else 'v1'}"

    @staticmethod
    def from_dict(d: Mapping) -> "CustomResourceDefinition":
        spec = d.get("spec") or {}
        return CustomResourceDefinition(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            group=spec.get("group", ""),
            scope=spec.get("scope", "Namespaced"),
            names=CRDNames.from_dict(spec.get("names") or {}),
            versions=[CRDVersion.from_dict(v) for v in spec.get("versions") or []],
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": self.metadata.to_dict(),
            "spec": {
                "group": self.group,
                "scope": self.scope,
                "names": self.names.to_dict(),
                "versions": [v.to_dict() for v in self.versions],
            },
        }


@dataclass
class Unstructured:
    """A dynamic object: typed metadata + raw payload. The payload keeps every
    top-level field except apiVersion/kind/metadata (spec, status, data, ...)."""

    api_version: str = ""
    kind: str = ""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    content: Dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Mapping) -> "Unstructured":
        content = {k: v for k, v in d.items()
                   if k not in ("apiVersion", "kind", "metadata")}
        return Unstructured(
            api_version=d.get("apiVersion", ""),
            kind=d.get("kind", ""),
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            content=content,
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"apiVersion": self.api_version, "kind": self.kind,
                               "metadata": self.metadata.to_dict()}
        out.update(self.content)
        return out

    def get(self, key: str, default=None):
        return self.content.get(key, default)


# ---- structural-schema validation + defaulting --------------------------------
#
# The subset of OpenAPI v3 the reference calls "structural"
# (apiextensions-apiserver/pkg/apiserver/schema/structural.go): type,
# properties, required, items, enum, minimum/maximum, minLength/maxLength,
# minItems/maxItems, pattern, additionalProperties, default, and
# x-kubernetes-preserve-unknown-fields. Unknown fields are PRUNED (the v1
# default) unless preserve-unknown-fields is set.

_TYPE_CHECKS = {
    "object": lambda v: isinstance(v, dict),
    "array": lambda v: isinstance(v, list),
    "string": lambda v: isinstance(v, str),
    "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "number": lambda v: isinstance(v, (int, float)) and not isinstance(v, bool),
    "boolean": lambda v: isinstance(v, bool),
}


def validate_structural(schema: Optional[Mapping], value: Any,
                        path: str = "") -> List[str]:
    """-> list of error strings (empty = valid)."""
    if schema is None:
        return []
    errs: List[str] = []
    loc = path or "<root>"
    t = schema.get("type")
    if t:
        check = _TYPE_CHECKS.get(t)
        if check is None:
            errs.append(f"{loc}: unknown schema type {t!r}")
            return errs
        if not check(value):
            errs.append(f"{loc}: expected {t}, got {type(value).__name__}")
            return errs
    if "enum" in schema and value not in schema["enum"]:
        errs.append(f"{loc}: {value!r} not in enum {schema['enum']!r}")
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        if "minimum" in schema and value < schema["minimum"]:
            errs.append(f"{loc}: {value} < minimum {schema['minimum']}")
        if "maximum" in schema and value > schema["maximum"]:
            errs.append(f"{loc}: {value} > maximum {schema['maximum']}")
    if isinstance(value, str):
        if "minLength" in schema and len(value) < schema["minLength"]:
            errs.append(f"{loc}: length {len(value)} < minLength {schema['minLength']}")
        if "maxLength" in schema and len(value) > schema["maxLength"]:
            errs.append(f"{loc}: length {len(value)} > maxLength {schema['maxLength']}")
        if "pattern" in schema and not re.search(schema["pattern"], value):
            errs.append(f"{loc}: does not match pattern {schema['pattern']!r}")
    if isinstance(value, list):
        if "minItems" in schema and len(value) < schema["minItems"]:
            errs.append(f"{loc}: {len(value)} items < minItems {schema['minItems']}")
        if "maxItems" in schema and len(value) > schema["maxItems"]:
            errs.append(f"{loc}: {len(value)} items > maxItems {schema['maxItems']}")
        items = schema.get("items")
        if items is not None:
            for i, v in enumerate(value):
                errs.extend(validate_structural(items, v, f"{path}[{i}]"))
    if isinstance(value, dict):
        props = schema.get("properties") or {}
        for k in schema.get("required") or []:
            if k not in value:
                errs.append(f"{loc}: required field {k!r} missing")
        for k, v in value.items():
            sub = props.get(k)
            if sub is not None:
                errs.extend(validate_structural(sub, v, f"{path}.{k}" if path else k))
            elif isinstance(schema.get("additionalProperties"), dict):
                errs.extend(validate_structural(schema["additionalProperties"], v,
                                                f"{path}.{k}" if path else k))
    return errs


def prune_and_default(schema: Optional[Mapping], value: Any) -> Any:
    """Apply defaults for absent properties and prune unknown fields
    (reference: schema/defaulting/algorithm.go + pruning/algorithm.go).
    Returns the new value; does not mutate the input."""
    if schema is None or not isinstance(schema, Mapping):
        return value
    if isinstance(value, dict):
        props = schema.get("properties") or {}
        ap = schema.get("additionalProperties")
        # additionalProperties: false means PRUNE unknowns (not preserve);
        # a schema or true means keep; bare objects with no properties keep
        # everything (free-form maps)
        preserve = (schema.get("x-kubernetes-preserve-unknown-fields")
                    or isinstance(ap, Mapping) or ap is True
                    or (not props and ap is not False))
        out = {}
        for k, v in value.items():
            if k in props:
                out[k] = prune_and_default(props[k], v)
            elif preserve:
                ap = schema.get("additionalProperties")
                out[k] = prune_and_default(ap if isinstance(ap, Mapping) else None, v)
        for k, sub in props.items():
            if k not in out and isinstance(sub, Mapping) and "default" in sub:
                out[k] = sub["default"]
        return out
    if isinstance(value, list) and schema.get("items") is not None:
        return [prune_and_default(schema["items"], v) for v in value]
    return value


class DynamicRegistry:
    """plural -> CustomResourceDefinition, kept current by draining a store
    watch on `customresourcedefinitions` (no polling, no per-request relist —
    the informer pattern applied in-process)."""

    RESOURCE = "customresourcedefinitions"

    def __init__(self, store):
        self._store = store
        self._lock = threading.Lock()
        self._by_plural: Dict[str, CustomResourceDefinition] = {}
        self._by_name: Dict[str, CustomResourceDefinition] = {}  # metadata.name
        self._short: Dict[str, str] = {}  # shortName/singular/kind.lower -> plural
        crds, rv = store.list(self.RESOURCE)
        for crd in crds:
            self._index(crd)
        self._watch = store.watch(kind=self.RESOURCE, since_rv=rv)

    def _index(self, crd: CustomResourceDefinition) -> None:
        self._by_plural[crd.names.plural] = crd
        self._by_name[crd.metadata.name] = crd
        for alias in ([crd.names.singular, crd.names.kind.lower()]
                      + list(crd.names.short_names)):
            if alias:
                self._short[alias] = crd.names.plural

    def _drop(self, crd: CustomResourceDefinition) -> None:
        self._by_plural.pop(crd.names.plural, None)
        self._by_name.pop(crd.metadata.name, None)
        self._short = {a: p for a, p in self._short.items()
                       if p != crd.names.plural}

    def _sync(self) -> None:
        if self._watch.terminated:
            # evicted as a slow watcher: relist (the reflector 410 contract)
            crds, rv = self._store.list(self.RESOURCE)
            self._by_plural.clear()
            self._by_name.clear()
            self._short.clear()
            for crd in crds:
                self._index(crd)
            self._watch = self._store.watch(kind=self.RESOURCE, since_rv=rv)
            return
        for ev in self._watch.drain():
            # MODIFIED may have renamed aliases (or the plural): drop the
            # previous index entries for this CRD before re-indexing so stale
            # shortNames/singulars stop resolving
            old = self._by_name.get(ev.obj.metadata.name)
            if old is not None:
                self._drop(old)
            if ev.type != "DELETED":
                self._index(ev.obj)

    def resolve(self, name: str) -> Optional[CustomResourceDefinition]:
        """Accepts plural, singular, kind, or a shortName."""
        with self._lock:
            self._sync()
            crd = self._by_plural.get(name)
            if crd is None and name in self._short:
                crd = self._by_plural.get(self._short[name])
            return crd

    def all(self) -> List[CustomResourceDefinition]:
        with self._lock:
            self._sync()
            return list(self._by_plural.values())


def validate_custom_object(crd: CustomResourceDefinition,
                           obj: Unstructured) -> Tuple[Optional[Unstructured], List[str]]:
    """Defaulting + pruning + validation for one write. Returns the processed
    object and errors; metadata is excluded from the schema walk (the reference
    validates it separately and never prunes it)."""
    version = crd.served_version()
    if version is None:
        return None, [f"no served version for {crd.metadata.name}"]
    if obj.api_version and obj.api_version != f"{crd.group}/{version.name}":
        # accept any declared served version, reject foreign groups
        served = {f"{crd.group}/{v.name}" for v in crd.versions if v.served}
        if obj.api_version not in served:
            return None, [f"apiVersion {obj.api_version!r} not served "
                          f"(want one of {sorted(served)})"]
    if obj.kind and obj.kind != crd.names.kind:
        return None, [f"kind {obj.kind!r} does not match CRD kind {crd.names.kind!r}"]
    if crd.scope == "Cluster":
        obj.metadata.namespace = ""  # cluster-scoped key scheme
    schema = version.schema
    if schema is None:
        return obj, []
    # schema applies to the whole object; carve metadata/apiVersion/kind out
    body = dict(obj.content)
    body = prune_and_default(schema, body)
    errs = validate_structural(schema, body)
    if errs:
        return None, errs
    processed = Unstructured(api_version=obj.api_version or f"{crd.group}/{version.name}",
                             kind=obj.kind or crd.names.kind,
                             metadata=obj.metadata, content=body)
    return processed, []
