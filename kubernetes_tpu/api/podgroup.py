"""Gang-scheduling API surface: the PodGroup object and its label convention.

The distributed-training workload the ROADMAP opens with this PR: a multi-host
training job is a set of ranks that must start together (all-or-nothing) or the
half-placed job deadlocks holding capacity. The API mirrors the coscheduling
ecosystem's shape (reference: sigs.k8s.io/scheduler-plugins
apis/scheduling/v1alpha1 PodGroup — minMember + a pod label naming the group),
narrowed to what the batched TPU solver consumes:

  - a PodGroup object (kind "podgroups" in the store) with spec.min_member:
    the quorum of members that must be placeable in one solve for ANY member
    to bind;
  - pods join a group by carrying the POD_GROUP_LABEL whose value is the
    PodGroup's name in the pod's own namespace (groups never span namespaces);
  - nodes advertise their TPU slice (ICI domain) via LABEL_TPU_SLICE — the
    cluster-level analog of a jax device's slice_index
    (parallel/multislice.slice_topology) — which the gang packing score uses
    to keep a gang's ranks on one interconnect.

PodGroups are stored and watched like any object; the scheduler's gang
directory (scheduler/gang.py) is their consumer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Mapping

from .types import ObjectMeta

# Pods opt into a gang with this label; the value names a PodGroup in the
# pod's namespace.
POD_GROUP_LABEL = "pod-group.scheduling/name"

# A gang member's rank (its position in the job's collective order, the MPI
# rank of the rank-aware-scheduling literature). POSITIONAL METADATA, not a
# scheduling constraint: the batched path excludes this one label from
# pod_class_signature so a 250-rank gang stays ONE equivalence class (one
# solver dispatch, one filter row) — consequently label selectors keying on
# it are not supported on the batched path. Consumed by the rank-alignment
# pass (models/gangcover.py rank_align): ranks r and r+1 prefer ICI-adjacent
# nodes.
POD_GROUP_RANK_LABEL = "pod-group.scheduling/rank"

# Node label carrying the TPU slice (ICI domain) the node's chips belong to.
# Nodes of one slice share terabit ICI; crossing slices pays DCN — the gang
# packing score prefers placing a whole gang inside one slice.
LABEL_TPU_SLICE = "tpu.scheduling/slice"

# Optional node label: the node's position on its slice's ICI ring/torus
# (an integer). Rank-aware placement measures neighbor distance along these
# positions; nodes without it fall back to their enumeration order within
# the slice (deterministic, and exact when nodes are listed in ring order).
LABEL_TPU_SLICE_INDEX = "tpu.scheduling/slice-index"


@dataclass
class PodGroupSpec:
    # quorum: the minimum number of members that must be schedulable together
    # before any member binds (all-or-nothing floor, not a replica target)
    min_member: int = 1

    @staticmethod
    def from_dict(d: Mapping) -> "PodGroupSpec":
        return PodGroupSpec(min_member=int(d.get("minMember", 1) or 1))


@dataclass
class PodGroupStatus:
    phase: str = "Pending"  # Pending | Scheduled (best-effort, controller-set)
    scheduled: int = 0  # members observed bound

    @staticmethod
    def from_dict(d: Mapping) -> "PodGroupStatus":
        return PodGroupStatus(
            phase=d.get("phase", "Pending"),
            scheduled=int(d.get("scheduled", 0) or 0),
        )


@dataclass
class PodGroup:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)

    kind = "PodGroup"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @staticmethod
    def from_dict(d: Mapping) -> "PodGroup":
        return PodGroup(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodGroupSpec.from_dict(d.get("spec") or {}),
            status=PodGroupStatus.from_dict(d.get("status") or {}),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "apiVersion": "scheduling.x-k8s.io/v1alpha1",
            "kind": "PodGroup",
            "metadata": self.metadata.to_dict(),
            "spec": {"minMember": self.spec.min_member},
        }
        if self.status.phase != "Pending" or self.status.scheduled:
            out["status"] = {"phase": self.status.phase,
                             "scheduled": self.status.scheduled}
        return out


def pod_gang_rank(pod) -> int:
    """The pod's gang rank (POD_GROUP_RANK_LABEL parsed as int), or -1 when
    absent/unparseable — rank-less members align by arrival order."""
    v = pod.metadata.labels.get(POD_GROUP_RANK_LABEL)
    if not v:
        return -1
    try:
        return int(v)
    except ValueError:
        return -1


def pod_group_key(pod) -> str:
    """Group key ("namespace/name") for a labeled pod, or "" when the pod is
    not a gang member. Groups are namespace-scoped: the label value names a
    PodGroup in the pod's own namespace."""
    name = pod.metadata.labels.get(POD_GROUP_LABEL)
    if not name:
        return ""
    return f"{pod.metadata.namespace}/{name}"
