"""scheduler_perf-equivalent benchmark DSL."""

from .dsl import WorkloadResult, WorkloadRunner, run_config  # noqa: F401
