"""scheduler_perf-equivalent workload DSL.

reference: test/integration/scheduler_perf/scheduler_perf.go:79-94 (opcode
union), :477, :1493-1497 (SchedulingThroughput threshold check) and the YAML
shape of misc/performance-config.yaml. Supported opcodes:

  createNodes   {count, nodeTemplate?, zones?}
  createPods    {count, podTemplate?, collectMetrics?, namespace?}
  churn         {number, intervalMilliseconds?, templatePaths? -> inline templates}
  barrier       {}   (wait until no pending pods)
  sleep         {durationMilliseconds}

A workload runs against an in-process store + scheduler (integration style: no
kubelets, pods just become Bound — SURVEY.md §4). Throughput = pods scheduled
per second during collectMetrics createPods phases; a run fails its threshold
like the reference's CI gate.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..api import Node, Pod
from ..scheduler import Framework
from ..scheduler.batch import BatchScheduler
from ..scheduler.plugins import default_plugins
from ..store import APIStore

DEFAULT_NODE = {
    "metadata": {"name": "node-{i}"},
    "status": {"capacity": {"cpu": "8", "memory": "32Gi", "pods": "110"}},
}
DEFAULT_POD = {
    "metadata": {"name": "pod-{i}"},
    "spec": {"containers": [{"name": "c", "resources": {
        "requests": {"cpu": "500m", "memory": "1Gi"}}}]},
}


@dataclass
class ThroughputSample:
    pods: int
    seconds: float

    @property
    def pods_per_second(self) -> float:
        return self.pods / self.seconds if self.seconds > 0 else 0.0


@dataclass
class WorkloadResult:
    name: str
    samples: List[ThroughputSample] = field(default_factory=list)
    threshold: float = 0.0

    @property
    def throughput(self) -> float:
        pods = sum(s.pods for s in self.samples)
        secs = sum(s.seconds for s in self.samples)
        return pods / secs if secs else 0.0

    @property
    def passed(self) -> bool:
        # 30% error margin like scheduler_perf.go:1493
        return self.threshold == 0 or self.throughput >= self.threshold * 0.7


def _fill(template: Dict, i: int, prefix: str = "") -> Dict:
    import json

    raw = json.dumps(template)
    raw = raw.replace("{i}", str(i)).replace("{prefix}", prefix)
    return json.loads(raw)


class WorkloadRunner:
    def __init__(self, solver: str = "auto", percentage_of_nodes_to_score: int = 100):
        self.store = APIStore(deep_copy_on_write=False)  # perf harness mode
        self.sched = BatchScheduler(
            self.store, Framework(default_plugins()), solver=solver,
            percentage_of_nodes_to_score=percentage_of_nodes_to_score,
        )
        self._synced = False
        self._pod_seq = 0
        self._node_seq = 0

    def run(self, workload: Dict) -> WorkloadResult:
        result = WorkloadResult(
            name=workload.get("name", "workload"),
            threshold=float(workload.get("threshold", 0)),
        )
        for op in workload.get("workloadTemplate", []):
            self._run_op(op, result)
        return result

    def _ensure_synced(self):
        if not self._synced:
            self.sched.sync()
            self._synced = True

    def _run_op(self, op: Dict, result: WorkloadResult) -> None:
        code = op["opcode"]
        if code == "createNodes":
            template = op.get("nodeTemplate", DEFAULT_NODE)
            zones = op.get("zones", 0)
            for i in range(op["count"]):
                d = _fill(template, self._node_seq)
                self._node_seq += 1
                if zones:
                    d.setdefault("metadata", {}).setdefault("labels", {})[
                        "topology.kubernetes.io/zone"] = f"zone-{i % zones}"
                self.store.create("nodes", Node.from_dict(d))
        elif code == "createPods":
            template = op.get("podTemplate", DEFAULT_POD)
            count = op["count"]
            ns = op.get("namespace", "default")
            pods = []
            for _ in range(count):
                d = _fill(template, self._pod_seq, prefix=ns)
                d.setdefault("metadata", {})["namespace"] = ns
                self._pod_seq += 1
                pods.append(Pod.from_dict(d))
            self._ensure_synced()
            collect = op.get("collectMetrics", False)
            t0 = time.perf_counter()
            for p in pods:
                self.store.create("pods", p)
            before = self.sched.scheduled_count
            self.sched.run_until_idle()
            dt = time.perf_counter() - t0
            if collect:
                result.samples.append(ThroughputSample(
                    pods=self.sched.scheduled_count - before, seconds=dt))
        elif code == "churn":
            self._ensure_synced()
            number = op.get("number", 100)
            interval = op.get("intervalMilliseconds", 0) / 1000.0
            template = op.get("podTemplate", DEFAULT_POD)
            for i in range(number):
                d = _fill(template, self._pod_seq)
                self._pod_seq += 1
                pod = self.store.create("pods", Pod.from_dict(d))
                self.sched.run_until_idle()
                try:
                    self.store.delete("pods", pod.key)
                except Exception:
                    pass
                if interval:
                    time.sleep(interval)
        elif code == "barrier":
            self._ensure_synced()
            self.sched.run_until_idle()
        elif code == "sleep":
            time.sleep(op.get("durationMilliseconds", 0) / 1000.0)
        else:
            raise ValueError(f"unknown opcode {code!r}")


def run_config(config: List[Dict], solver: str = "auto") -> List[WorkloadResult]:
    """Run a performance-config list: [{name, workloadTemplate, threshold}]."""
    out = []
    for workload in config:
        runner = WorkloadRunner(solver=solver)
        out.append(runner.run(workload))
    return out
