"""Apiserver watch fan-out at scale: N streaming watchers, one select loop.

The kubemark question for the HTTP surface (VERDICT r4 weak #6): the
thread-per-watch ThreadingHTTPServer is fine at hundreds of watchers —
prove (and measure) it at thousands. Server side each watch costs one
mostly-BLOCKED thread (cheap) plus per-event fan-out work; the fan-out
serialization is shared across watchers via the event wire cache
(rest.py, the cacher's cachingObject analog).

The client half multiplexes every stream over ONE thread with selectors
(a 5k-thread client would drown the measurement on a small host). Events
are counted by scanning for the type marker; chunked framing is skipped
by carrying a tail across reads.

run() returns the recorded numbers; __main__ prints one JSON line —
the `ApiserverWatchFanout` bench rung wraps this.
"""

from __future__ import annotations

import json
import selectors
import socket
import time
from typing import Dict


def run(n_watchers: int = 5000, n_events: int = 100,
        connect_timeout: float = 120.0,
        drain_timeout: float = 300.0) -> Dict:
    from ..server import APIServer
    from ..store import APIStore
    from ..testing import MakePod

    store = APIStore()
    srv = APIServer(store).start()
    host, port = srv._httpd.server_address[:2]
    out: Dict = {"watchers": n_watchers, "events": n_events}
    socks = []
    try:
        rv = store.list("pods")[1]
        t0 = time.perf_counter()
        request = (f"GET /api/v1/namespaces/default/pods?watch=true"
                   f"&resourceVersion={rv} HTTP/1.1\r\n"
                   f"Host: {host}\r\nUser-Agent: watch-scale\r\n\r\n"
                   ).encode()
        sel = selectors.DefaultSelector()
        for i in range(n_watchers):
            s = socket.create_connection((host, port), timeout=10)
            s.setblocking(False)
            s.sendall(request)
            socks.append(s)
        # wait until every stream has response headers (the server thread
        # pool is warming 1 thread per watcher here)
        got_headers = 0
        buffers = {}
        for s in socks:
            sel.register(s, selectors.EVENT_READ)
            buffers[s] = b""
        deadline = time.monotonic() + connect_timeout
        while got_headers < n_watchers and time.monotonic() < deadline:
            for key, _ in sel.select(timeout=1.0):
                s = key.fileobj
                try:
                    chunk = s.recv(65536)
                except BlockingIOError:
                    continue
                if buffers[s] == b"" and chunk:
                    got_headers += 1
                buffers[s] += chunk
        connect_s = time.perf_counter() - t0
        out["connect_s"] = round(connect_s, 2)
        out["streams_established"] = got_headers
        if got_headers < n_watchers:
            out["error"] = (f"only {got_headers}/{n_watchers} streams "
                            f"established in {connect_timeout:.0f}s")
            return out

        # fan-out: E pod creates -> N*E deliveries
        marker = b'"type": "ADDED"'
        counts = {s: buffers[s].count(marker) for s in socks}
        tails = {s: buffers[s][-32:] for s in socks}
        t1 = time.perf_counter()
        for i in range(n_events):
            store.create("pods", MakePod(f"fan-{i}").req(
                {"cpu": "100m"}).obj())
        want = n_events
        done = 0
        closed = 0
        deadline = time.monotonic() + drain_timeout
        while done + closed < n_watchers and time.monotonic() < deadline:
            for key, _ in sel.select(timeout=1.0):
                s = key.fileobj
                try:
                    chunk = s.recv(262144)
                except BlockingIOError:
                    continue
                if not chunk:
                    sel.unregister(s)
                    if counts[s] < want:
                        closed += 1  # server evicted: never completing
                    continue
                data = tails[s] + chunk
                if counts[s] < want:
                    before = counts[s]
                    counts[s] = before + data.count(marker)
                    # marker may span the carry boundary; the 32-byte tail
                    # overlap makes double counting impossible only because
                    # we count on tail+chunk and subtract tail's own hits
                    counts[s] -= tails[s].count(marker)
                    if before < want <= counts[s]:
                        done += 1
                tails[s] = data[-32:]
        fan_s = time.perf_counter() - t1
        delivered = sum(min(c, want) for c in counts.values())
        out["watchers_complete"] = done
        out["deliveries"] = delivered
        out["fanout_s"] = round(fan_s, 3)
        out["deliveries_per_s"] = round(delivered / fan_s, 1)
        out["events_per_s_per_watcher"] = round(
            delivered / fan_s / max(1, n_watchers), 2)
        # watch-bus telemetry (ISSUE 7 satellite): subscriber buffer state +
        # dropped-delivery counters at the end of the fan-out — a watcher
        # silently losing events (chaos drop, overflow eviction) is now a
        # number in the rung output, not an invisible gap
        tel = store.watch_telemetry()
        out["watch_subscribers"] = len(tel["subscribers"])
        out["watch_queue_max"] = max(
            (s["queue_length"] for s in tel["subscribers"]), default=0)
        out["watch_dropped"] = tel["dropped"]
        if done < n_watchers:
            incomplete = sum(1 for c in counts.values() if c < want)
            out["error"] = (f"{incomplete} watchers missed events "
                            f"within {drain_timeout:.0f}s")
        return out
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        srv.stop()


if __name__ == "__main__":
    import sys

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 5000
    e = int(sys.argv[2]) if len(sys.argv) > 2 else 100
    print(json.dumps(run(n_watchers=n, n_events=e)))
