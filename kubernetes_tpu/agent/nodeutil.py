"""Shared node-agent plumbing: Node registration and Lease renewal.

reference: pkg/kubelet/nodestatus (node object construction) and
pkg/kubelet/nodelease (the 10s Lease heartbeat) — used by both the full
Kubelet and the hollow kubemark agent.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api import Node
from ..api.types import ObjectMeta, new_uid
from ..api.workloads import Lease
from ..store import AlreadyExistsError, APIStore, NotFoundError

LEASE_NAMESPACE = "kube-node-lease"


def register_node(store: APIStore, node_name: str, capacity: Dict,
                  labels: Optional[Dict[str, str]] = None) -> None:
    """Create the Node object if absent (idempotent re-register)."""
    all_labels = {"kubernetes.io/hostname": node_name, **(labels or {})}
    node = Node(metadata=ObjectMeta(name=node_name, namespace="", uid=new_uid(),
                                    labels=all_labels))
    node.status.capacity = dict(capacity)
    node.status.allocatable = dict(capacity)
    try:
        store.create("nodes", node)
    except AlreadyExistsError:
        pass


def renew_lease(store: APIStore, node_name: str, now: float) -> None:
    """Renew (or create) the node's coordination Lease."""
    key = f"{LEASE_NAMESPACE}/{node_name}"
    try:
        def renew(lease: Lease) -> Lease:
            lease.renew_time = now
            lease.holder_identity = node_name
            return lease

        store.guaranteed_update("leases", key, renew)
    except NotFoundError:
        lease = Lease(metadata=ObjectMeta(name=node_name,
                                          namespace=LEASE_NAMESPACE, uid=new_uid()),
                      holder_identity=node_name, acquire_time=now, renew_time=now)
        try:
            store.create("leases", lease)
        except AlreadyExistsError:
            pass
