"""Kubelet resource managers: static CPU pinning + NUMA topology hints.

reference: pkg/kubelet/cm/cpumanager/policy_static.go (the static policy:
guaranteed-QoS pods with integer CPU requests get EXCLUSIVE cpus carved out
of the shared pool, checkpointed so restarts keep assignments) and
pkg/kubelet/cm/topologymanager (per-resource NUMA hints merged into one
affinity; best-effort admits unaligned allocations, restricted rejects the
pod with TopologyAffinityError).

Device locality IS the product on a TPU host — the chip sits on one NUMA
node and the feeding dataloader threads must pin beside it — so the static
policy here prefers single-NUMA allocations exactly as the reference's hint
merge does, and the chosen cpus are deterministic (lowest ids within the
chosen NUMA node first) for reproducible tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set


@dataclass
class CPUTopology:
    """n_cpus spread evenly over numa_nodes (cpu i lives on NUMA
    i // (n_cpus // numa_nodes)) — the discovery result of cadvisor's
    topology probe, simplified."""

    n_cpus: int = 8
    numa_nodes: int = 2

    def numa_of(self, cpu: int) -> int:
        per = max(1, self.n_cpus // max(1, self.numa_nodes))
        return min(cpu // per, self.numa_nodes - 1)

    def cpus_of_numa(self, numa: int) -> List[int]:
        return [c for c in range(self.n_cpus) if self.numa_of(c) == numa]


class TopologyAffinityError(Exception):
    """restricted policy: no single-NUMA allocation exists
    (topologymanager scope container, policy restricted)."""


def pod_is_guaranteed(pod) -> bool:
    """Guaranteed QoS (qos.GetPodQOS): every container's requests == limits
    for cpu and memory, and both are set."""
    containers = list(pod.spec.containers) + list(pod.spec.init_containers)
    if not containers:
        return False
    for c in containers:
        req = (c.resources or {}).get("requests") or {}
        lim = (c.resources or {}).get("limits") or {}
        for res in ("cpu", "memory"):
            if res not in req or res not in lim:
                return False
            if req[res] != lim[res]:
                return False
    return True


def _integer_cpus(container) -> int:
    """Exclusive-cpu count for a container: its integer cpu request, or 0
    when fractional (fractional guaranteed containers stay in the shared
    pool — policy_static.go guaranteedCPUs)."""
    from ..api.resources import parse_quantity_milli

    req = (container.resources or {}).get("requests") or {}
    if "cpu" not in req:
        return 0
    millis = parse_quantity_milli(req["cpu"])
    if millis <= 0 or millis % 1000:
        return 0
    return millis // 1000


class CPUManager:
    """Static policy + topology hints, checkpointed.

    State: pod key -> container -> sorted cpu ids. The shared pool is
    everything unassigned; non-guaranteed pods always run there."""

    CHECKPOINT_KEY = "cpu-manager-state"

    def __init__(self, topology: Optional[CPUTopology] = None,
                 checkpoints=None, topology_policy: str = "best-effort"):
        self.topology = topology or CPUTopology()
        self.checkpoints = checkpoints
        self.topology_policy = topology_policy
        self.assignments: Dict[str, Dict[str, List[int]]] = {}
        self._restore()

    # -- pool accounting -------------------------------------------------------

    def _used(self) -> Set[int]:
        return {c for pods in self.assignments.values()
                for cpus in pods.values() for c in cpus}

    def shared_pool(self) -> List[int]:
        used = self._used()
        return [c for c in range(self.topology.n_cpus) if c not in used]

    # -- allocation ------------------------------------------------------------

    def _pick(self, n: int) -> Optional[List[int]]:
        """n cpus from the free pool, single-NUMA when possible (the
        topology manager's merged hint); deterministic lowest-id order."""
        free = self.shared_pool()
        if len(free) < n:
            return None
        by_numa: Dict[int, List[int]] = {}
        for c in free:
            by_numa.setdefault(self.topology.numa_of(c), []).append(c)
        aligned = [cpus for _numa, cpus in sorted(by_numa.items())
                   if len(cpus) >= n]
        if aligned:
            return sorted(aligned[0])[:n]
        if self.topology_policy == "restricted":
            raise TopologyAffinityError(
                f"no single-NUMA placement for {n} exclusive cpus "
                f"(free per NUMA: "
                f"{ {k: len(v) for k, v in sorted(by_numa.items())} })")
        return sorted(free)[:n]  # best-effort: spill across NUMA nodes

    def allocate_pod(self, pod) -> Dict[str, List[int]]:
        """Exclusive cpus for every eligible container of a guaranteed pod;
        {} for pods that stay entirely in the shared pool. Raises
        TopologyAffinityError (restricted) or RuntimeError (pool empty) —
        the caller fails pod admission like the reference kubelet."""
        key = pod.key
        if key in self.assignments:
            return self.assignments[key]
        if not pod_is_guaranteed(pod):
            return {}
        got: Dict[str, List[int]] = {}
        try:
            # init containers allocate too (policy_static.go allocates for
            # them; the reference lets app containers REUSE released init
            # cpus — this build holds both conservatively, which only
            # over-reserves, never under-aligns)
            for c in list(pod.spec.init_containers) + list(pod.spec.containers):
                n = _integer_cpus(c)
                if n == 0:
                    continue
                picked = self._pick(n)
                if picked is None:
                    raise RuntimeError(
                        f"not enough free exclusive cpus for "
                        f"{key}/{c.name} (want {n}, free "
                        f"{len(self.shared_pool())})")
                got[c.name] = picked
                # commit incrementally so _pick sees earlier containers
                self.assignments.setdefault(key, {})[c.name] = picked
        except Exception:
            self.assignments.pop(key, None)  # all-or-nothing per pod
            raise
        if got:
            self._persist()
        return got

    def release_pod(self, pod_key: str) -> None:
        if self.assignments.pop(pod_key, None) is not None:
            self._persist()

    def reconcile(self, live_pod_keys) -> int:
        """Drop assignments for pods that no longer exist (restart
        recovery: checkpointed state vs the live pod list —
        policy_static.go removeStaleState). Returns #released."""
        live = set(live_pod_keys)  # hoisted: a generator arg would empty
        stale = [k for k in self.assignments if k not in live]
        for k in stale:
            self.assignments.pop(k, None)
        if stale:
            self._persist()
        return len(stale)

    # -- checkpointing ---------------------------------------------------------

    def _persist(self) -> None:
        if self.checkpoints is None:
            return
        self.checkpoints.save(self.CHECKPOINT_KEY, {
            "topology": {"nCPUs": self.topology.n_cpus,
                         "numaNodes": self.topology.numa_nodes},
            "assignments": {k: {c: list(v) for c, v in pods.items()}
                            for k, pods in self.assignments.items()},
        })

    def _restore(self) -> None:
        if self.checkpoints is None:
            return
        data = self.checkpoints.load(self.CHECKPOINT_KEY)
        if not data:
            return
        saved = data.get("topology") or {}
        if (saved.get("nCPUs") != self.topology.n_cpus
                or saved.get("numaNodes") != self.topology.numa_nodes):
            # topology changed under the checkpoint: stale cpu ids would be
            # meaningless — discard, like the reference's restore failure
            # ("configured topology differs from state checkpoint")
            self.assignments = {}
            self._persist()
            return
        self.assignments = {
            k: {c: [int(x) for x in v] for c, v in pods.items()}
            for k, pods in (data.get("assignments") or {}).items()}
