"""Node agent with the kubelet's real internal structure against the CRI
boundary: pod workers, PLEG, probers, status manager, eviction manager,
checksummed checkpoints, Lease heartbeat.

reference: pkg/kubelet — syncLoop/syncLoopIteration (kubelet.go:2410/:2484)
selects over config updates, PLEG events, probe results and housekeeping;
per-pod workers (pod_workers.go:735); PLEG 1s relist (pleg/generic.go:163);
status manager PATCHes phase/conditions; eviction manager watches memory
signals (pkg/kubelet/eviction); checkpoint manager writes checksummed local
state (pkg/kubelet/checkpointmanager). Driven by `tick()` under a fake clock
in tests or `start()` as a daemon.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..api import Node, Pod
from ..api.types import FAILED, ObjectMeta, RUNNING, SUCCEEDED, new_uid
from ..api.workloads import Lease
from ..store import AlreadyExistsError, APIStore, ConflictError, NotFoundError
from ..utils import Clock
from .cri import CONTAINER_EXITED, CONTAINER_RUNNING, CRIRuntime, FakeRuntime

LEASE_NAMESPACE = "kube-node-lease"


# ---------------------------------------------------------------------------
# PLEG — pod lifecycle event generator (pleg/generic.go)
# ---------------------------------------------------------------------------

CONTAINER_STARTED = "ContainerStarted"
CONTAINER_DIED = "ContainerDied"


@dataclass(frozen=True)
class PodLifecycleEvent:
    pod_key: str
    type: str
    container: str


class PLEG:
    """Relists the runtime, diffs against the previous snapshot, and emits
    per-container lifecycle events (generic.go relist)."""

    def __init__(self, runtime: CRIRuntime, relist_period: float = 1.0,
                 clock: Optional[Clock] = None):
        self.runtime = runtime
        self.relist_period = relist_period
        self.clock = clock or Clock()
        self._last_states: Dict[Tuple[str, str], str] = {}  # (pod, container) -> state
        self._last_relist = float("-inf")

    def relist(self, force: bool = False) -> List[PodLifecycleEvent]:
        now = self.clock.now()
        if not force and now - self._last_relist < self.relist_period:
            return []
        self._last_relist = now
        states: Dict[Tuple[str, str], str] = {}
        events: List[PodLifecycleEvent] = []
        for sb in self.runtime.list_pod_sandboxes():
            for c in sb.containers.values():
                key = (sb.pod_key, c.name)
                states[key] = c.state
                prev = self._last_states.get(key)
                if prev != c.state:
                    if c.state == CONTAINER_RUNNING:
                        events.append(PodLifecycleEvent(sb.pod_key, CONTAINER_STARTED, c.name))
                    elif c.state == CONTAINER_EXITED:
                        events.append(PodLifecycleEvent(sb.pod_key, CONTAINER_DIED, c.name))
        self._last_states = states
        return events


# ---------------------------------------------------------------------------
# probers (pkg/kubelet/prober)
# ---------------------------------------------------------------------------


@dataclass
class ProbeSpec:
    """Liveness/readiness probe config; the probe itself is a callable (the
    fake of an HTTP/exec probe) returning bool."""

    kind: str  # "liveness" | "readiness"
    probe: Callable[[], bool]
    period: float = 10.0
    failure_threshold: int = 3
    success_threshold: int = 1


class ProbeWorker:
    def __init__(self, spec: ProbeSpec, clock: Clock):
        self.spec = spec
        self.clock = clock
        self._last_run = float("-inf")
        self._failures = 0
        self._successes = 0
        self.healthy = True

    def tick(self) -> Optional[bool]:
        """Run if due; returns new health state on transition, else None."""
        now = self.clock.now()
        if now - self._last_run < self.spec.period:
            return None
        self._last_run = now
        ok = bool(self.spec.probe())
        if ok:
            self._successes += 1
            self._failures = 0
            if not self.healthy and self._successes >= self.spec.success_threshold:
                self.healthy = True
                return True
        else:
            self._failures += 1
            self._successes = 0
            if self.healthy and self._failures >= self.spec.failure_threshold:
                self.healthy = False
                return False
        return None


# ---------------------------------------------------------------------------
# checkpoint manager (pkg/kubelet/checkpointmanager/checkpoint_manager.go)
# ---------------------------------------------------------------------------


class CorruptCheckpointError(Exception):
    pass


class CheckpointManager:
    """Checksummed JSON state files; a bad checksum is surfaced, never
    silently loaded (checksum.go Verify)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, f"{key}.json")

    def save(self, key: str, data: dict) -> None:
        payload = json.dumps(data, sort_keys=True)
        checksum = hashlib.sha256(payload.encode()).hexdigest()
        tmp = self._path(key) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"checksum": checksum, "data": payload}, f)
        os.replace(tmp, self._path(key))

    def load(self, key: str) -> Optional[dict]:
        try:
            with open(self._path(key)) as f:
                wrapper = json.load(f)
        except FileNotFoundError:
            return None
        except json.JSONDecodeError as e:
            raise CorruptCheckpointError(str(e))
        if not isinstance(wrapper, dict):
            raise CorruptCheckpointError(f"checkpoint {key!r} is not an object")
        payload = wrapper.get("data", "")
        if hashlib.sha256(payload.encode()).hexdigest() != wrapper.get("checksum"):
            raise CorruptCheckpointError(f"checksum mismatch for {key!r}")
        return json.loads(payload)

    def remove(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


# ---------------------------------------------------------------------------
# eviction manager (pkg/kubelet/eviction)
# ---------------------------------------------------------------------------


@dataclass
class EvictionConfig:
    memory_available_threshold: int = 100 * 1024 * 1024  # evictionHard memory.available


class EvictionManager:
    """Ranks pods for eviction under memory pressure: pods exceeding their
    requests first, then lowest priority, then highest usage
    (eviction/helpers.go rankMemoryPressure)."""

    def __init__(self, config: EvictionConfig,
                 stats: Callable[[], Dict[str, int]],
                 usage_of: Callable[[Pod], int]):
        self.config = config
        self.stats = stats
        self.usage_of = usage_of
        self.under_pressure = False

    def select_victim(self, pods: List[Pod]) -> Optional[Pod]:
        available = self.stats().get("memory_available", 1 << 62)
        self.under_pressure = available < self.config.memory_available_threshold
        if not self.under_pressure or not pods:
            return None
        from ..api import compute_pod_resource_request

        def rank(p: Pod):
            usage = self.usage_of(p)
            req = compute_pod_resource_request(p).memory
            exceeds = usage > req
            return (not exceeds, p.spec.priority, -usage)

        return sorted(pods, key=rank)[0]


# ---------------------------------------------------------------------------
# the kubelet
# ---------------------------------------------------------------------------


@dataclass
class _PodWorker:
    """Per-pod worker state (pod_workers.go podSyncStatus)."""

    pod: Pod
    sandbox_id: str = ""
    terminating: bool = False
    probes: List[ProbeWorker] = field(default_factory=list)
    ready: bool = True


class Kubelet:
    """Real sync-loop structure against a (fake) CRI runtime."""

    def __init__(self, store: APIStore, node_name: str,
                 runtime: Optional[CRIRuntime] = None,
                 capacity: Optional[Dict] = None,
                 labels: Optional[Dict[str, str]] = None,
                 clock: Optional[Clock] = None,
                 checkpoint_dir: Optional[str] = None,
                 eviction: Optional[EvictionManager] = None,
                 relist_period: float = 1.0,
                 heartbeat_period: float = 10.0):
        self.store = store
        self.node_name = node_name
        self.clock = clock or Clock()
        self.runtime = runtime or FakeRuntime(clock=self.clock)
        self._config_errors: Dict[str, str] = {}  # pod key -> last config error
        self.capacity = capacity or {"cpu": "8", "memory": "32Gi", "pods": "110"}
        self.labels = labels or {}
        self.pleg = PLEG(self.runtime, relist_period=relist_period, clock=self.clock)
        self.workers: Dict[str, _PodWorker] = {}
        self.eviction = eviction
        self.heartbeat_period = heartbeat_period
        self._last_heartbeat = float("-inf")
        self.checkpoints = (CheckpointManager(checkpoint_dir)
                            if checkpoint_dir else None)
        # cm/: static CPU pinning + NUMA topology hints (policy_static.go,
        # topologymanager) — exclusive cpus for guaranteed-QoS pods,
        # checkpointed beside the kubelet's other local state
        from ..api.resources import parse_quantity_milli
        from .cm import CPUManager, CPUTopology

        n_cpus = max(1, parse_quantity_milli(
            self.capacity.get("cpu", "8")) // 1000)
        self.cpu_manager = CPUManager(
            CPUTopology(n_cpus=n_cpus, numa_nodes=2 if n_cpus >= 2 else 1),
            checkpoints=self.checkpoints)
        self._watch = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # probe factories: pod key -> list of ProbeSpec (tests inject fakes)
        self.probe_factory: Callable[[Pod], List[ProbeSpec]] = lambda pod: []

    # -- registration + heartbeat ---------------------------------------------

    def register(self) -> None:
        from .nodeutil import register_node

        register_node(self.store, self.node_name, self.capacity, self.labels)
        self.heartbeat()
        _, rv = self.store.list("pods")
        # pods for config + exec/port-forward session channels (the
        # kubelet-server surface of pkg/kubelet/server/server.go)
        self._watch = self.store.watch(
            ("pods", "podexecs", "podportforwards"), since_rv=rv)
        self._serve_pending_sessions()
        # adopt pods already bound here (restart recovery: state comes from
        # the store + runtime relist, kubelet is stateless modulo checkpoints)
        pods, _ = self.store.list("pods", lambda p: p.spec.node_name == self.node_name)
        # restart recovery ORDER: checkpointed cpu assignments are pruned
        # against the live pod list FIRST (removeStaleState), so re-adopted
        # guaranteed pods keep their exact pre-restart cpus and dead pods'
        # cpus return to the shared pool
        self.cpu_manager.reconcile(
            [p.key for p in pods if not p.is_terminal()])
        for p in pods:
            if not p.is_terminal():
                self._start_pod(p)
        self._publish_cpu_assignments()
        if self.checkpoints is not None:
            self.checkpoints.save("node-registration", {"node": self.node_name})

    def heartbeat(self) -> None:
        from .nodeutil import renew_lease

        now = self.clock.now()
        self._last_heartbeat = now
        renew_lease(self.store, self.node_name, now)

    # -- syncLoopIteration ----------------------------------------------------

    def tick(self) -> int:
        """One syncLoopIteration: config updates -> runtime tick -> PLEG ->
        probes -> eviction -> heartbeat. Returns #events handled."""
        n = self._pump_config()
        self._retry_config_blocked()
        if isinstance(self.runtime, FakeRuntime):
            self.runtime.tick()
        for ev in self.pleg.relist():
            n += 1
            self._handle_pleg_event(ev)
        self._tick_probes()
        self._tick_eviction()
        if self.clock.now() - self._last_heartbeat >= self.heartbeat_period:
            self.heartbeat()
        return n

    def _pump_config(self) -> int:
        if self._watch is None:
            return 0
        if self._watch.terminated:
            # evicted as a slow watcher: relist + rewatch, reconcile workers
            # against the fresh pod list (Reflector restart; kubelet is
            # stateless modulo checkpoints)
            self._watch.stop()
            _, rv = self.store.list("pods")
            self._watch = self.store.watch(
                ("pods", "podexecs", "podportforwards"), since_rv=rv)
            # sessions created during the watch gap would otherwise be lost
            self._serve_pending_sessions()
            pods, _ = self.store.list(
                "pods", lambda p: p.spec.node_name == self.node_name)
            live = {p.key for p in pods if not p.is_terminal()}
            for p in pods:
                if not p.is_terminal() and p.key not in self.workers:
                    self._start_pod(p)
            for key in list(self.workers):
                if key not in live:
                    self._stop_pod(key)
            return 0
        n = 0
        for ev in self._watch.drain():
            if ev.kind == "podexecs":
                if ev.type != "DELETED":
                    self._serve_exec(ev.obj)
                continue
            if ev.kind == "podportforwards":
                if ev.type != "DELETED":
                    self._serve_portforward(ev.obj)
                continue
            pod = ev.obj
            if pod.spec.node_name != self.node_name:
                continue
            n += 1
            if ev.type == "DELETED":
                self._stop_pod(pod.key)
            elif pod.is_terminal():
                continue  # our own status write echoed back
            elif pod.key not in self.workers:
                self._start_pod(pod)
        return n

    # -- exec / attach / port-forward (kubelet server analog) ------------------

    def _serve_pending_sessions(self) -> None:
        """Answer sessions whose events this kubelet never saw (fresh
        registration, or a watch-eviction relist gap)."""
        for sess in self.store.list("podexecs", lambda s: not s.done)[0]:
            self._serve_exec(sess)
        for sess in self.store.list("podportforwards",
                                    lambda s: not s.done)[0]:
            self._serve_portforward(sess)

    def _owns_session_pod(self, sess):
        """The pod this session targets, when it is bound HERE; else None."""
        from ..store import NotFoundError

        try:
            pod = self.store.get(
                "pods", f"{sess.metadata.namespace}/{sess.pod_name}")
        except NotFoundError:
            return None
        return pod if pod.spec.node_name == self.node_name else None

    def _serve_exec(self, sess) -> None:
        import base64

        from ..api.execapi import ATTACH_COMMAND

        if sess.done:
            return
        pod = self._owns_session_pod(sess)
        if pod is None:
            return
        pod_key = pod.key
        container = sess.container or (
            pod.spec.containers[0].name if pod.spec.containers else "")
        try:
            # inside the guard: malformed base64 from a client must fail
            # THIS session, never the kubelet's sync loop
            stdin = base64.b64decode(sess.stdin) if sess.stdin else b""
            if sess.command == [ATTACH_COMMAND]:
                # attach: stdin goes to the container (folded into its log —
                # the fake runtime's terminal), output = recent log lines
                if stdin:
                    self._log_line(
                        pod, container,
                        "stdin: "
                        + stdin.decode(errors="replace").rstrip("\n"))
                from ..store import NotFoundError

                try:
                    log = self.store.get("podlogs", pod_key)
                    out = "\n".join(log.entries[-10:]) + "\n"
                except NotFoundError:
                    out = ""
                stdout, stderr, code = out.encode(), b"", 0
            else:
                stdout, stderr, code = self.runtime.exec_sync(
                    pod_key, container, sess.command, stdin)
            err_text = ""
        except Exception as e:  # runtime failure surfaces in the session
            stdout, stderr, code = b"", b"", 1
            err_text = str(e)

        def finish(s):
            s.stdout = stdout.decode(errors="replace")
            s.stdout_b64 = base64.b64encode(stdout).decode()
            s.stderr = stderr.decode(errors="replace")
            s.exit_code = int(code)
            s.done = True
            s.error = err_text
            return s

        try:
            self.store.guaranteed_update("podexecs", sess.key, finish)
        except Exception:
            pass  # session deleted under us (client gave up)

    def _serve_portforward(self, sess) -> None:
        import base64

        if sess.done:
            return
        pod = self._owns_session_pod(sess)
        if pod is None:
            return
        try:
            data = base64.b64decode(sess.data) if sess.data else b""
            answer = self.runtime.port_data(pod.key, sess.port, data)
            response = base64.b64encode(answer).decode()
            err_text = ""
        except Exception as e:
            response = ""
            err_text = str(e)

        def finish(s):
            s.response = response
            s.done = True
            s.error = err_text
            return s

        try:
            self.store.guaranteed_update("podportforwards", sess.key, finish)
        except Exception:
            pass

    def _retry_config_blocked(self) -> None:
        """Pods blocked on missing ConfigMap/Secret refs get re-attempted
        every tick (the reference kubelet's container-start backoff) — the
        blocking event already drained from the watch, so only this retry
        notices the reference appearing."""
        for key in list(self._config_errors):
            if key in self.workers:
                self._config_errors.pop(key, None)
                continue
            try:
                pod = self.store.get("pods", key)
            except NotFoundError:
                self._config_errors.pop(key, None)
                continue
            if pod.spec.node_name == self.node_name and not pod.is_terminal():
                self._start_pod(pod)
            else:
                self._config_errors.pop(key, None)

    def _missing_config_refs(self, pod: Pod) -> list:
        """ConfigMap/Secret references a container start needs
        (kuberuntime makeEnvironmentVariables + volume mounts): missing
        non-optional sources block the start — the
        CreateContainerConfigError state."""
        missing = []
        ns = pod.metadata.namespace

        def check(kind: str, name: str, optional) -> None:
            if not name or optional:
                return
            try:
                self.store.get(kind, f"{ns}/{name}")
            except NotFoundError:
                missing.append(f"{kind[:-1]} {name!r}")

        for c in list(pod.spec.containers) + list(pod.spec.init_containers):
            for e in c.env:
                vf = e.get("valueFrom") or {}
                cm = vf.get("configMapKeyRef") or {}
                check("configmaps", cm.get("name", ""), cm.get("optional"))
                sk = vf.get("secretKeyRef") or {}
                check("secrets", sk.get("name", ""), sk.get("optional"))
            for e in c.env_from:
                cm = e.get("configMapRef") or {}
                check("configmaps", cm.get("name", ""), cm.get("optional"))
                sk = e.get("secretRef") or {}
                check("secrets", sk.get("name", ""), sk.get("optional"))
        for v in pod.spec.volumes:
            check("configmaps", v.config_map, v.config_map_optional)
            check("secrets", v.secret, v.secret_optional)
        return missing

    def _start_pod(self, pod: Pod) -> None:
        """SyncPod: sandbox, image pulls, containers (kuberuntime SyncPod)."""
        missing = self._missing_config_refs(pod)
        if missing:
            # CreateContainerConfigError: stay Pending; retried every tick
            # until the reference appears (the reference kubelet backs off).
            # Log once per distinct error, not per tick.
            msg = f"CreateContainerConfigError: {', '.join(missing)} not found"
            if self._config_errors.get(pod.key) != msg:
                self._config_errors[pod.key] = msg
                self._log_line(pod, "kubelet", msg)
            return
        self._config_errors.pop(pod.key, None)
        # cm admission: exclusive-cpu carve-out BEFORE containers start
        # (SyncPod's cm admission step); failure fails the POD, like the
        # reference's TopologyAffinityError / SMTAlignmentError admission
        from .cm import TopologyAffinityError

        try:
            pinned = self.cpu_manager.allocate_pod(pod)
        except (TopologyAffinityError, RuntimeError) as e:
            reason = ("TopologyAffinityError"
                      if isinstance(e, TopologyAffinityError)
                      else "InsufficientExclusiveCPUs")
            self._log_line(pod, "kubelet", f"{reason}: {e}")
            self._write_phase(pod.key, FAILED)
            return
        if pinned:
            for cname, cpus in pinned.items():
                self._log_line(pod, cname,
                               f"Pinned to exclusive CPUs {cpus}")
            self._publish_cpu_assignments()
        existing = (self.runtime.sandbox_for(pod.key)
                    if hasattr(self.runtime, "sandbox_for") else None)
        if existing is not None:
            sid = existing.id
        else:
            sid = self.runtime.run_pod_sandbox(pod.key, pod.metadata.uid)
            for c in pod.spec.containers:
                image = c.image or "pause"
                self.runtime.pull_image(image)
                self.runtime.create_container(sid, c.name or "main", image)
                self.runtime.start_container(sid, c.name or "main")
                self._log_line(pod, c.name or "main",
                               f"Started container with image {image}")
        worker = _PodWorker(pod=pod, sandbox_id=sid)
        worker.probes = [ProbeWorker(s, self.clock) for s in self.probe_factory(pod)]
        self.workers[pod.key] = worker
        self._write_phase(pod.key, RUNNING)

    def _log_line(self, pod: Pod, container: str, message: str) -> None:
        from ..api.events import append_pod_log

        append_pod_log(self.store, pod.metadata.namespace, pod.metadata.name,
                       container, message, self.clock.now(),
                       pod_uid=pod.metadata.uid)

    def _stop_pod(self, pod_key: str) -> None:
        worker = self.workers.pop(pod_key, None)
        if worker is not None and worker.sandbox_id:
            self.runtime.stop_pod_sandbox(worker.sandbox_id)
            self.runtime.remove_pod_sandbox(worker.sandbox_id)
            self._log_line(worker.pod, "sandbox", "Stopped pod sandbox")
        if pod_key in self.cpu_manager.assignments:
            self.cpu_manager.release_pod(pod_key)
            self._publish_cpu_assignments()

    def _publish_cpu_assignments(self) -> None:
        """Mirror the pinning state into a node annotation so `ktl describe
        node` can render it (the reference surfaces cm state via podresources
        gRPC; an annotation is this build's API-visible equivalent)."""
        payload = json.dumps(self.cpu_manager.assignments, sort_keys=True)

        def stamp(node):
            node.metadata.annotations[
                "cpumanager.kubernetes-tpu.io/assignments"] = payload
            return node

        try:
            self.store.guaranteed_update("nodes", self.node_name, stamp)
        except Exception:
            pass  # node deleted mid-shutdown: nothing to annotate

    def _handle_pleg_event(self, ev: PodLifecycleEvent) -> None:
        worker = self.workers.get(ev.pod_key)
        if worker is None:
            return
        if ev.type == CONTAINER_DIED:
            self._sync_pod_status(worker)

    def _sync_pod_status(self, worker: _PodWorker) -> None:
        """Phase from container states (kubelet_pods.go getPhase):
        all exited 0 -> Succeeded; any exited non-0 with restartPolicy Never ->
        Failed; exited with Always/OnFailure -> restart."""
        sb = self.runtime.sandbox_for(worker.pod.key)
        if sb is None:
            return
        statuses = list(sb.containers.values())
        exited = [c for c in statuses if c.state == CONTAINER_EXITED]
        if not exited:
            return
        policy = worker.pod.spec.restart_policy
        failed = [c for c in exited if c.exit_code != 0]
        if len(exited) == len(statuses):
            if not failed and policy != "Always":
                self._write_phase(worker.pod.key, SUCCEEDED)
                self.workers.pop(worker.pod.key, None)
                return
            if failed and policy == "Never":
                self._write_phase(worker.pod.key, FAILED)
                self.workers.pop(worker.pod.key, None)
                return
        # restart path (Always, or OnFailure with non-zero exits); Never
        # containers stay exited even while siblings run
        if policy == "Never":
            return
        for c in exited:
            if c.exit_code == 0 and policy == "OnFailure":
                continue
            self.runtime.create_container(sb.id, c.name, c.image)
            self.runtime.start_container(sb.id, c.name)

    def _tick_probes(self) -> None:
        for worker in list(self.workers.values()):
            for pw in worker.probes:
                changed = pw.tick()
                if changed is None:
                    continue
                if pw.spec.kind == "readiness":
                    worker.ready = all(
                        p.healthy for p in worker.probes
                        if p.spec.kind == "readiness")
                    self._write_ready(worker.pod.key, worker.ready)
                elif pw.spec.kind == "liveness" and changed is False:
                    # liveness failure: kill + restart per policy
                    sb = self.runtime.sandbox_for(worker.pod.key)
                    if sb is None:
                        continue
                    for name in list(sb.containers):
                        self.runtime.stop_container(sb.id, name)
                        if worker.pod.spec.restart_policy != "Never":
                            self.runtime.create_container(
                                sb.id, name, sb.containers[name].image)
                            self.runtime.start_container(sb.id, name)
                    if worker.pod.spec.restart_policy == "Never":
                        self._write_phase(worker.pod.key, FAILED)
                        self.workers.pop(worker.pod.key, None)

    def _tick_eviction(self) -> None:
        if self.eviction is None:
            return
        victim = self.eviction.select_victim(
            [w.pod for w in self.workers.values() if not w.terminating])
        # pressure state comes from the signal, not from victim availability:
        # a pressured node with nothing evictable still reports pressure
        self._set_pressure_condition(self.eviction.under_pressure)
        if victim is None:
            return
        self._stop_pod(victim.key)
        from ..api.types import PodCondition

        def mark_evicted(st):
            st.phase = FAILED
            st.conditions.append(PodCondition(
                type="DisruptionTarget", status="True",
                reason="TerminationByKubelet",
                message="evicted: node memory pressure",
                last_transition_time=self.clock.now()))

        try:
            self.store.update_pod_status(
                victim.metadata.namespace, victim.metadata.name, mark_evicted)
        except (NotFoundError, ConflictError):
            pass

    def _set_pressure_condition(self, pressure: bool) -> None:
        from ..api.types import NodeCondition

        # only write on transition: a no-op write per tick would bump the
        # node's resourceVersion and wake every node watcher
        if getattr(self, "_last_pressure", None) == pressure:
            return
        self._last_pressure = pressure

        def mutate(node: Node) -> Node:
            node.status.conditions = [
                c for c in node.status.conditions if c.type != "MemoryPressure"]
            node.status.conditions.append(NodeCondition(
                type="MemoryPressure", status="True" if pressure else "False",
                reason="KubeletHasInsufficientMemory" if pressure
                else "KubeletHasSufficientMemory",
                last_transition_time=self.clock.now()))
            return node

        try:
            self.store.guaranteed_update("nodes", self.node_name, mutate)
        except NotFoundError:
            pass

    # -- status writes ---------------------------------------------------------

    def _write_phase(self, pod_key: str, phase: str) -> None:
        if phase in (SUCCEEDED, FAILED) \
                and pod_key in self.cpu_manager.assignments:
            # terminated pods return their exclusive cpus to the shared
            # pool immediately (removeStaleState runs continuously in the
            # reference, not just at startup) — every terminal transition
            # funnels through here, so completed Jobs can't drain the pool
            self.cpu_manager.release_pod(pod_key)
            self._publish_cpu_assignments()
        ns, name = pod_key.split("/", 1)
        try:
            self.store.update_pod_status(ns, name,
                                         lambda st: setattr(st, "phase", phase))
        except (NotFoundError, ConflictError):
            pass

    def _write_ready(self, pod_key: str, ready: bool) -> None:
        ns, name = pod_key.split("/", 1)
        from ..api.types import PodCondition

        def mutate(st):
            st.conditions = [c for c in st.conditions if c.type != "Ready"]
            st.conditions.append(PodCondition(
                type="Ready", status="True" if ready else "False",
                last_transition_time=self.clock.now()))

        try:
            self.store.update_pod_status(ns, name, mutate)
        except (NotFoundError, ConflictError):
            pass

    # -- daemon mode -----------------------------------------------------------

    def start(self, interval: float = 0.05) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                self.tick()
                self.clock.sleep(interval)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._watch is not None:
            self._watch.stop()
            self._watch = None
