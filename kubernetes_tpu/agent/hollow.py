"""Hollow node agent — the kubemark analog.

reference: pkg/kubemark/hollow_kubelet.go:63,104 (real kubelet logic against
containertest.FakeOS / fake CRI) and the kubelet syncLoop shape
(pkg/kubelet/kubelet.go:2410): watch pods bound to this node, 'run' them by
flipping status to Running, handle deletes, renew the node Lease heartbeat and
keep NodeStatus fresh. Lets scale/churn tests run thousands of nodes in-process
without machines — the same trick kubemark uses for 10k-node clusters.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..api import Node
from ..api.workloads import Lease
from ..chaos import faultinject as _chaos
from ..api.types import ObjectMeta, RUNNING, new_uid
from ..store import APIStore, AlreadyExistsError, ConflictError, NotFoundError
from ..utils import Clock

LEASE_NAMESPACE = "kube-node-lease"

_podtrace = None


def _trace():
    """scheduler.podtrace, imported on first use: the submit->running span
    taps (ISSUE 9) must not make the node agent import the scheduler stack
    at module load. note_pod_event is an O(1) no-op for unsampled pods."""
    global _podtrace
    if _podtrace is None:
        from ..scheduler import podtrace as _pt

        _podtrace = _pt
    return _podtrace


class HollowKubelet:
    def __init__(self, store: APIStore, node_name: str, capacity: Optional[Dict] = None,
                 labels: Optional[Dict[str, str]] = None, clock: Optional[Clock] = None):
        self.store = store
        self.node_name = node_name
        self.capacity = capacity or {"cpu": "8", "memory": "32Gi", "pods": "110"}
        self.labels = labels or {}
        self.clock = clock or Clock()
        self._watch = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.running_pods: Dict[str, str] = {}  # pod key -> phase

    # -- registration + heartbeat (kubelet nodestatus + Lease) -----------------

    def register(self) -> None:
        labels = {"kubernetes.io/hostname": self.node_name, **self.labels}
        node = Node(metadata=ObjectMeta(name=self.node_name, namespace="", uid=new_uid(),
                                        labels=labels))
        node.status.capacity = dict(self.capacity)
        node.status.allocatable = dict(self.capacity)
        try:
            self.store.create("nodes", node)
        except AlreadyExistsError:
            pass
        self.heartbeat()
        _, rv = self.store.list("pods")
        self._watch = self.store.watch("pods", since_rv=rv)
        # adopt pods already bound to us
        pods, _ = self.store.list("pods", lambda p: p.spec.node_name == self.node_name)
        for p in pods:
            self._run_pod(p)

    def heartbeat(self) -> None:
        if _chaos.ACTIVE is not None and _chaos.ACTIVE.should_drop(
                "kubelet.heartbeat", self.node_name):
            return  # injected missed renewal: node_lifecycle must notice
        key = f"{LEASE_NAMESPACE}/{self.node_name}"
        now = self.clock.now()
        try:
            def renew(lease: Lease) -> Lease:
                lease.renew_time = now
                lease.holder_identity = self.node_name
                return lease

            self.store.guaranteed_update("leases", key, renew)
        except NotFoundError:
            lease = Lease(metadata=ObjectMeta(name=self.node_name, namespace=LEASE_NAMESPACE,
                                              uid=new_uid()),
                          holder_identity=self.node_name, acquire_time=now, renew_time=now)
            try:
                self.store.create("leases", lease)
            except AlreadyExistsError:
                pass

    # -- the syncLoop (fake CRI: phase flips instead of containers) ------------

    def pump(self) -> int:
        """Process pending pod events for this node (syncLoopIteration analog)."""
        if self._watch is None:
            return 0
        if self._watch.terminated:
            # evicted slow watcher: relist + rewatch (Reflector restart)
            self._watch.stop()
            _, rv = self.store.list("pods")
            self._watch = self.store.watch("pods", since_rv=rv)
            pods, _ = self.store.list(
                "pods", lambda p: p.spec.node_name == self.node_name)
            live = set()
            for p in pods:
                if not p.is_terminal():
                    live.add(p.key)
                    if p.key not in self.running_pods:
                        self._run_pod(p)
            for key in list(self.running_pods):
                if key not in live:
                    self.running_pods.pop(key, None)
            return 0
        n = 0
        pt = _trace()
        for ev in self._watch.drain():
            pod = ev.obj
            if pod.spec.node_name != self.node_name:
                continue
            if ev.type == "DELETED":
                self.running_pods.pop(pod.key, None)
            elif not pod.is_terminal() and pod.key not in self.running_pods:
                # submit->running span edge (ISSUE 9): the pod's bind event
                # was dequeued by ITS kubelet — the watch-delivery leg of
                # the true end-to-end latency. O(1) no-op when unsampled.
                pt.note_pod_event(pod.key, "watch_delivered")
                self._run_pod(pod)
            n += 1
        return n

    def _run_pod(self, pod) -> None:
        pt = _trace()
        pt.note_pod_event(pod.key, "kubelet_observed")
        self.running_pods[pod.key] = RUNNING
        try:
            self.store.update_pod_status(
                pod.metadata.namespace, pod.metadata.name,
                lambda st: setattr(st, "phase", RUNNING),
            )
            pt.note_pod_event(pod.key, "running")
        except (NotFoundError, ConflictError):
            self.running_pods.pop(pod.key, None)

    # -- daemon mode -----------------------------------------------------------

    def start(self, heartbeat_interval: float = 10.0) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            last_beat = 0.0
            while not self._stop.is_set():
                self.pump()
                now = self.clock.now()
                if now - last_beat >= heartbeat_interval:
                    self.heartbeat()
                    last_beat = now
                self.clock.sleep(0.05)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._watch is not None:
            self._watch.stop()
            self._watch = None


class HollowCluster:
    """Convenience: n hollow nodes driven manually (tests) or as daemons."""

    def __init__(self, store: APIStore, n_nodes: int, clock: Optional[Clock] = None,
                 capacity: Optional[Dict] = None, zone_count: int = 0):
        self.kubelets = []
        for i in range(n_nodes):
            labels = {}
            if zone_count:
                labels["topology.kubernetes.io/zone"] = f"zone-{i % zone_count}"
            k = HollowKubelet(store, f"hollow-{i}", capacity=capacity, labels=labels, clock=clock)
            self.kubelets.append(k)

    def register_all(self) -> None:
        for k in self.kubelets:
            k.register()

    def pump_all(self) -> int:
        return sum(k.pump() for k in self.kubelets)

    def heartbeat_all(self) -> None:
        for k in self.kubelets:
            k.heartbeat()

    def stop_all(self) -> None:
        for k in self.kubelets:
            k.stop()
