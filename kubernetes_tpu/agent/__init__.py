"""L6 — node agent (hollow/kubemark-style kubelet)."""

from .cri import CRIRuntime, FakeRuntime  # noqa: F401
from .hollow import HollowCluster, HollowKubelet  # noqa: F401
from .kubelet import (  # noqa: F401
    CheckpointManager,
    CorruptCheckpointError,
    EvictionConfig,
    EvictionManager,
    Kubelet,
    PLEG,
    ProbeSpec,
)
