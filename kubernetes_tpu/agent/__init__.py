"""L6 — node agent (hollow/kubemark-style kubelet)."""

from .hollow import HollowCluster, HollowKubelet  # noqa: F401
